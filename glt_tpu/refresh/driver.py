"""Layer-wise whole-graph embedding refresh driver.

The reference engine refreshes whole-graph embeddings between training
rounds by running inference layer by layer: instead of sampling a
multi-hop subgraph per seed (fanout blow-up, every node recomputed
once per seed that reaches it), layer ``l`` is computed for *all* nodes
before layer ``l+1`` starts, so each node is touched exactly once per
layer and the per-step working set is one node partition plus its
1-hop frontier.

Data path per sweep (one partition of ``block_size`` nodes):

1. host builds the frontier: the partition's nodes first, then the
   sorted set of their CSR neighbors not already in the partition,
   -1-padded to the static cap ``block_size * (max_degree + 1)``;
2. the *next* sweep's frontier is handed to
   :meth:`~glt_tpu.data.feature.Feature.stage_ahead` so the DRAM
   stager fills ahead of the gather (the block-ahead prefetch oracle);
3. ``feature.gather`` pulls the frontier rows through the HBM / DRAM /
   disk tiers (compressed stores dequantize on-chip in the gather
   epilogue);
4. a jitted step under ``compilewatch.label("refresh_sweep_{l}")``
   expands the frontier's induced edges with
   :func:`~glt_tpu.ops.subgraph.node_subgraph` and applies one layer —
   messages flow neighbor → owner, so rows ``[:block_len]`` (the
   partition, by frontier construction) are exact layer-``l`` outputs;
5. the partition's rows stream into a
   :class:`~glt_tpu.store.disk.FeatureStoreWriter`; finalize publishes
   ``workdir/layer_{l}`` atomically and the next layer reads it back
   through a fresh tiered ``Feature``.

Sweeps cover disjoint row ranges and row encoding is a pure function,
so resuming from a sweep-boundary checkpoint and rewriting a range is
bit-identical to an uninterrupted run (the writer re-attaches to its
deterministic partial file; the final sha256 matches).

Nodes whose degree exceeds ``max_degree`` are truncated to their first
``max_degree`` CSR neighbors — the same static-shape cap the sampling
paths use; size it to the graph's max degree for exact refresh.
"""
from __future__ import annotations

import math
import os
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import compilewatch
from ..obs import metrics as _metrics
from ..ops.subgraph import node_subgraph
from ..store.disk import DiskFeatureStore, FeatureStoreWriter

LayerFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


class RefreshReport(dict):
    """``run()`` summary: plain dict with attribute sugar."""

    __getattr__ = dict.__getitem__


def sage_refresh_layers(model, params) -> List[LayerFn]:
    """Split a :class:`~glt_tpu.models.sage.GraphSAGE` into per-layer
    inference callables ``fn(x, edge_index, edge_mask) -> h``.

    Matches the model's ``train=False`` forward exactly: ``conv{i}``
    then ReLU on every non-last layer (dropout is identity at
    inference).  Each callable closes over its own parameter subtree so
    the driver never materializes unused layers' weights on device
    together.
    """
    import flax.linen as nn

    from ..models.conv import SAGEConv

    tree = params["params"] if "params" in params else params
    fns: List[LayerFn] = []
    for i in range(model.num_layers):
        last = i == model.num_layers - 1
        dim = model.out_features if last else model.hidden_features
        conv = SAGEConv(dim, dtype=model.dtype)
        layer_params = tree[f"conv{i}"]

        def fn(x, edge_index, edge_mask, *, _conv=conv, _p=layer_params,
               _last=last):
            h = _conv.apply({"params": _p}, x, edge_index, edge_mask)
            return h if _last else nn.relu(h)

        fns.append(fn)
    return fns


class RefreshDriver:
    """Drive a layer-wise whole-graph refresh over a tiered store.

    Parameters
    ----------
    indptr, indices:
        Whole-graph CSR (host numpy; pushed to device once).
    layer_fns:
        One inference callable per layer, ``fn(x, edge_index,
        edge_mask) -> h`` (see :func:`sage_refresh_layers`).
    store:
        Layer-0 input :class:`~glt_tpu.store.disk.DiskFeatureStore`
        (any codec — compressed rows dequantize on-chip).
    workdir:
        Output directory; layer ``l`` publishes to
        ``workdir/layer_{l}``.
    out_codec:
        Codec for the published embedding stores — ``raw`` or ``bf16``
        (``int8`` needs whole-matrix calibration a streaming writer
        cannot do).
    checkpointer:
        Optional :class:`~glt_tpu.ckpt.driver.Checkpointer`; the driver
        registers itself as the ``"refresh"`` component and saves at
        sweep boundaries (step = ``layer * num_sweeps + sweep + 1``).
    on_sweep:
        Optional ``hook(driver, layer, sweep)`` called after each sweep
        is durably written (tests use it to simulate preemption).
    """

    def __init__(self, indptr, indices, layer_fns: Sequence[LayerFn],
                 store: DiskFeatureStore, workdir: str, *,
                 block_size: int = 256, max_degree: int = 32,
                 out_codec: str = "raw",
                 dram_budget_bytes: int = 64 << 20,
                 split_ratio: float = 0.0, stage_threads: int = 1,
                 checkpointer=None,
                 on_sweep: Optional[Callable] = None):
        if out_codec not in ("raw", "bf16"):
            raise ValueError(
                f"refresh out_codec must be raw|bf16, got {out_codec!r}")
        self._indptr_np = np.asarray(indptr, np.int64)
        self._indices_np = np.asarray(indices, np.int64)
        self._indptr = jnp.asarray(self._indptr_np, jnp.int32)
        self._indices = jnp.asarray(self._indices_np, jnp.int32)
        self.num_nodes = int(self._indptr_np.shape[0] - 1)
        if store.num_rows != self.num_nodes:
            raise ValueError(
                f"store has {store.num_rows} rows but CSR has "
                f"{self.num_nodes} nodes")
        self.layer_fns = list(layer_fns)
        self.store = store
        self.workdir = os.path.abspath(workdir)
        self.block_size = int(block_size)
        self.max_degree = int(max_degree)
        self.out_codec = out_codec
        self.dram_budget_bytes = int(dram_budget_bytes)
        self.split_ratio = float(split_ratio)
        self.stage_threads = int(stage_threads)
        self.checkpointer = checkpointer
        self.on_sweep = on_sweep
        self.num_sweeps = max(
            1, math.ceil(self.num_nodes / self.block_size))
        self.frontier_cap = self.block_size * (self.max_degree + 1)
        # Resume cursor: the next (layer, sweep) to run.
        self._layer = 0
        self._sweep = 0
        self.totals = {"nodes": 0, "seconds": 0.0, "bytes_from_hbm": 0,
                       "bytes_from_dram": 0, "bytes_from_disk": 0,
                       "stage_errors": 0, "hits": 0, "misses": 0}

    # -- PR-8 checkpoint protocol ------------------------------------
    def state_dict(self) -> dict:
        return {"layer": self._layer, "sweep": self._sweep}

    def load_state_dict(self, state: dict) -> None:
        self._layer = int(state["layer"])
        self._sweep = int(state["sweep"])

    # -- host-side frontier construction -----------------------------
    def _frontier(self, sweep: int):
        """Partition nodes first, then their sorted out-of-partition
        CSR neighbors, -1-padded to the static ``frontier_cap``."""
        lo = sweep * self.block_size
        hi = min(self.num_nodes, lo + self.block_size)
        nodes = np.arange(lo, hi, dtype=np.int32)
        start = self._indptr_np[nodes]
        deg = np.minimum(self._indptr_np[nodes + 1] - start,
                         self.max_degree)
        offs = np.arange(self.max_degree, dtype=np.int64)[None, :]
        valid = offs < deg[:, None]
        flat = start[:, None] + np.where(valid, offs, 0)
        nbrs = self._indices_np[flat][valid]
        ext = np.setdiff1d(np.unique(nbrs), nodes).astype(np.int32)
        frontier = np.full(self.frontier_cap, -1, np.int32)
        frontier[: nodes.size] = nodes
        frontier[nodes.size: nodes.size + ext.size] = ext
        return frontier, int(nodes.size), int(lo)

    # -- device step --------------------------------------------------
    def _build_step(self, layer_fn: LayerFn):
        indptr, indices = self._indptr, self._indices
        max_degree = self.max_degree

        @jax.jit
        def step(x, frontier):
            sub = node_subgraph(indptr, indices, frontier, max_degree)
            # CSR rows own their neighbor lists; messages flow
            # neighbor -> owner, so src = cols, dst = rows.
            edge_index = jnp.stack([sub.cols, sub.rows])
            return layer_fn(x, edge_index, sub.mask)

        return step

    def _out_dim(self, layer_fn: LayerFn, in_dim: int) -> int:
        shapes = (jax.ShapeDtypeStruct((1, in_dim), jnp.float32),
                  jax.ShapeDtypeStruct((2, 1), jnp.int32),
                  jax.ShapeDtypeStruct((1,), jnp.bool_))
        return int(jax.eval_shape(layer_fn, *shapes).shape[-1])

    def _layer_root(self, layer: int) -> str:
        return os.path.join(self.workdir, f"layer_{layer}")

    # -- main loop -----------------------------------------------------
    def run(self) -> RefreshReport:
        """Refresh every layer; returns a summary report.

        With a ``checkpointer``, first resumes the latest snapshot and
        skips already-completed (layer, sweep) work; the re-attached
        partial writer makes the final stores bit-identical to an
        uninterrupted run.
        """
        if self.checkpointer is not None:
            self.checkpointer.resume({"refresh": self})
        os.makedirs(self.workdir, exist_ok=True)
        nodes_per_s = _metrics.gauge(
            "glt.refresh.nodes_per_s",
            "whole-graph refresh throughput (nodes/sec, last sweep)")
        sweep_ms = _metrics.histogram(
            "glt.refresh.sweep_ms", "per-sweep wall time (ms)")
        tier_counters = {
            k: _metrics.counter(
                f"glt.refresh.bytes_from_{k}",
                f"refresh gather bytes served from the {k} tier")
            for k in ("hbm", "dram", "disk")
        }

        from ..data.feature import Feature

        start_layer = self._layer
        for layer in range(start_layer, len(self.layer_fns)):
            layer_fn = self.layer_fns[layer]
            src = (self.store if layer == 0
                   else DiskFeatureStore(self._layer_root(layer - 1)))
            feature = Feature.from_store(
                src, self.dram_budget_bytes,
                split_ratio=self.split_ratio,
                stage_threads=self.stage_threads)
            out_dim = self._out_dim(layer_fn, src.dim)
            writer = FeatureStoreWriter(
                self._layer_root(layer), self.num_nodes, out_dim,
                logical_dtype=np.float32, codec=self.out_codec,
                overwrite=True)
            step_fn = self._build_step(layer_fn)
            label = f"refresh_sweep_{layer}"
            try:
                first = self._sweep if layer == self._layer else 0
                if first > 0 and not writer.reattached:
                    # The checkpoint says sweeps [0, first) are done but
                    # their partial output did not survive — earlier
                    # rows would publish as zeros.  Sweeps are
                    # idempotent, so just redo the layer.
                    first = 0
                nxt = self._frontier(first) if first < self.num_sweeps \
                    else None
                for sweep in range(first, self.num_sweeps):
                    frontier_np, block_len, lo = nxt
                    if sweep + 1 < self.num_sweeps:
                        nxt = self._frontier(sweep + 1)
                        feature.stage_ahead(nxt[0])
                    else:
                        nxt = None
                    stats0 = feature.store_stats() or {}
                    t0 = time.perf_counter()
                    frontier = jnp.asarray(frontier_np)
                    x = feature.gather(frontier)
                    with compilewatch.label(label):
                        h = step_fn(x, frontier)
                    writer.write_rows(
                        lo, np.asarray(h[:block_len], np.float32))
                    dt = time.perf_counter() - t0
                    stats1 = feature.store_stats() or {}
                    nodes_per_s.set(block_len / max(dt, 1e-9))
                    sweep_ms.observe(dt * 1e3)
                    for k, c in tier_counters.items():
                        c.inc(stats1.get(f"bytes_from_{k}", 0)
                              - stats0.get(f"bytes_from_{k}", 0))
                        self.totals[f"bytes_from_{k}"] += (
                            stats1.get(f"bytes_from_{k}", 0)
                            - stats0.get(f"bytes_from_{k}", 0))
                    self.totals["nodes"] += block_len
                    self.totals["seconds"] += dt
                    self._layer, self._sweep = layer, sweep + 1
                    ckpt = self.checkpointer
                    if ckpt is not None:
                        step_no = layer * self.num_sweeps + sweep + 1
                        if ckpt.due(step_no):
                            writer.flush()
                            ckpt.save(step_no, {"refresh": self})
                    if self.on_sweep is not None:
                        self.on_sweep(self, layer, sweep)
                end_stats = feature.store_stats() or {}
                for k in ("stage_errors", "hits", "misses"):
                    self.totals[k] += end_stats.get(k, 0)
            except BaseException:
                feature.close()
                raise
            feature.close()
            writer.finalize()
            self._layer, self._sweep = layer + 1, 0
        secs = self.totals["seconds"]
        lookups = self.totals["hits"] + self.totals["misses"]
        return RefreshReport(
            out_root=self._layer_root(len(self.layer_fns) - 1),
            layers=len(self.layer_fns), num_sweeps=self.num_sweeps,
            nodes=self.totals["nodes"],
            nodes_per_s=self.totals["nodes"] / secs if secs else 0.0,
            bytes_from_hbm=self.totals["bytes_from_hbm"],
            bytes_from_dram=self.totals["bytes_from_dram"],
            bytes_from_disk=self.totals["bytes_from_disk"],
            stage_errors=self.totals["stage_errors"],
            dram_hit_rate=(self.totals["hits"] / lookups if lookups
                           else 0.0))
