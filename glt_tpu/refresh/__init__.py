"""glt_tpu.refresh — layer-wise whole-graph embedding refresh.

Full-graph inference layer by layer (docs/refresh.md): layer ``l``
sweeps every node partition once, gathers the previous layer's
embeddings for the partition plus its 1-hop frontier through the
tiered :class:`~glt_tpu.data.feature.Feature` (HBM / DRAM stager /
disk), applies one GNN layer on device, and streams the partition's
rows into a :class:`~glt_tpu.store.disk.FeatureStoreWriter` that
atomically publishes ``layer_{l}`` when the sweep set completes.  Each
node is touched exactly once per layer, so the working set is one
partition's frontier — never ``O(fanout^L)`` and never the full
``[N, d]`` matrix.

Sweep boundaries are the checkpoint unit: :class:`RefreshDriver`
implements the PR-8 ``state_dict`` protocol and resumes bit-identically
(disjoint sweeps + pure row encoding make partial-output rewrites
idempotent).
"""
from .driver import RefreshDriver, RefreshReport, sage_refresh_layers

__all__ = ["RefreshDriver", "RefreshReport", "sage_refresh_layers"]
