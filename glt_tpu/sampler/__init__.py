from .base import (
    BaseSampler,
    EdgeSamplerInput,
    HeteroSamplerOutput,
    NegativeSampling,
    NodeSamplerInput,
    SamplerOutput,
    SamplingConfig,
)
from .neighbor_sampler import NeighborSampler

__all__ = [
    "BaseSampler",
    "EdgeSamplerInput",
    "HeteroSamplerOutput",
    "NegativeSampling",
    "NodeSamplerInput",
    "SamplerOutput",
    "SamplingConfig",
    "NeighborSampler",
]
