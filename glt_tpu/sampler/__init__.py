from .base import (
    BaseSampler,
    EdgeSamplerInput,
    HeteroSamplerOutput,
    NegativeSampling,
    NodeSamplerInput,
    SamplerOutput,
    SamplingConfig,
)
from .neighbor_sampler import (
    NeighborSampler,
    calibrate_node_capacity,
    measure_occupancy,
)

__all__ = [
    "calibrate_node_capacity",
    "measure_occupancy",
    "BaseSampler",
    "EdgeSamplerInput",
    "HeteroSamplerOutput",
    "NegativeSampling",
    "NodeSamplerInput",
    "SamplerOutput",
    "SamplingConfig",
    "NeighborSampler",
]
