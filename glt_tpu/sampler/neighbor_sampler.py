"""Multi-hop neighbor sampling engine, fully jitted, static shapes.

Rebuild of the reference's single-machine sampling engine
(``graphlearn_torch/python/sampler/neighbor_sampler.py``).  The reference
loops hops on the host, calling a CUDA kernel + a hash-table inducer per hop
with a forced device sync per hop to size ragged outputs
(random_sampler.cu:288-300).  Here the **entire multi-hop pipeline is one
XLA program**: per-hop frontiers, cumulative first-occurrence dedup, and
relabeled COO edges all have trace-time-constant shapes, so sampling runs
back-to-back with the train step with no host round-trips.

Key design points:

* The cumulative unique node list (the reference's persistent hash-table
  inducer, csrc/cuda/inducer.cu:75-95) is a -1-padded buffer rebuilt per hop
  by :func:`unique_first_occurrence` over ``concat(old_buffer, new_nbrs)``;
  old uniques provably keep their positions (they occur first).
* The hop-``i+1`` frontier — only the *globally new* nodes discovered at hop
  ``i`` — is ``lax.dynamic_slice(buffer, [old_count], [hop_i_width])``:
  a traced start with a static width.  This replaces the inducer's
  "return newly inserted keys" contract exactly.
* Edge direction is transposed on output to PyG's dst<-src convention
  (out-edges sampled, then row=neighbor, col=seed), mirroring
  neighbor_sampler.py:159-165.
* ``frontier_cap`` bounds per-hop frontier width (nodes past the cap stay
  leaves), the static-shape analog of the reference's implicit bound
  ``_max_sampled_nodes`` (neighbor_sampler.py:595-612).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph
from ..ops.neighbor_sample import sample_neighbors
from ..ops.negative_sample import sample_negative_edges, weighted_draw
from ..ops.subgraph import node_subgraph
from ..ops.unique import (
    dense_induce,
    dense_induce_final,
    dense_induce_init,
    dense_map_fits,
    relabel_by_reference,
    unique_first_occurrence,
)
from ..typing import PADDING_ID
from .base import (
    BaseSampler,
    EdgeSamplerInput,
    NegativeSampling,
    NodeSamplerInput,
    SamplerOutput,
)


def _pad_ids(ids: np.ndarray, size: int) -> np.ndarray:
    """Right-pad a host id array with PADDING_ID to a static length."""
    ids = np.asarray(ids).astype(np.int32).ravel()
    if ids.shape[0] > size:
        raise ValueError(f"batch of {ids.shape[0]} exceeds static size {size}")
    out = np.full((size,), PADDING_ID, np.int32)
    out[: ids.shape[0]] = ids
    return out


def hop_widths(batch_size: int, fanouts: Sequence[int],
               frontier_cap: Optional[int] = None) -> List[int]:
    """Static frontier width per hop: B, B*f0, B*f0*f1, ... (capped)."""
    widths = [batch_size]
    for f in fanouts[:-1]:
        w = widths[-1] * f
        if frontier_cap is not None:
            w = min(w, frontier_cap)
        widths.append(w)
    return widths


def max_sampled_nodes(batch_size: int, fanouts: Sequence[int],
                      frontier_cap: Optional[int] = None) -> int:
    """Padded node capacity (cf. ``_max_sampled_nodes``, neighbor_sampler.py:595)."""
    widths = hop_widths(batch_size, fanouts, frontier_cap)
    return widths[0] + sum(w * f for w, f in zip(widths, fanouts))


def measure_occupancy(sampler: "NeighborSampler", seed_batches) -> np.ndarray:
    """Unique-node counts per seed batch (ONE host fetch for all batches).

    The sampler's padded node buffer is sized to the zero-dedup worst case
    (the reference's ``_max_sampled_nodes``, neighbor_sampler.py:595-612);
    on real graphs per-batch occupancy is far lower.  This measures the
    actual interior-unique count per batch so callers can size the static
    capacity to a percentile instead of the worst case — feature-gather
    cost (~121 ns per padded row on v5e), the train step's segment ops,
    and HBM footprint all scale with the padded width.

    In leaf-block mode (``last_hop_dedup=False``) the final hop's width is
    static, so only interior hops are counted.
    """
    import jax as _jax

    counts = []
    for seeds in seed_batches:
        out = sampler.sample_from_nodes(NodeSamplerInput(seeds))
        n = out.num_sampled_nodes
        if not sampler.last_hop_dedup:
            n = n[:-1]
        counts.append(jnp.sum(n))
    return np.asarray(_jax.device_get(jnp.stack(counts)))


def calibrate_node_capacity(sampler: "NeighborSampler", seed_batches=None,
                            pct: float = 99.0, margin: float = 1.05,
                            multiple: int = 256,
                            counts: Optional[np.ndarray] = None) -> int:
    """Occupancy-sized static node capacity for a calibrated workload.

    Samples ``seed_batches`` through ``sampler`` (typically uncapped),
    takes the ``pct`` percentile of interior-unique counts, applies a
    safety ``margin``, rounds up to ``multiple`` rows (sublane/lane tile
    alignment), and re-adds the static leaf-block width in leaf mode.
    Feed the result to ``NeighborSampler(node_capacity=...)``; batches
    that exceed it are flagged via ``metadata['overflow']`` and their
    excess-node edges are masked (or exactly re-sampled by the loaders'
    full-capacity fallback).
    """
    if counts is None:
        counts = measure_occupancy(sampler, seed_batches)
    interior = float(np.percentile(counts, pct)) * margin
    leaf_w = (0 if sampler.last_hop_dedup
              else sampler._widths[-1] * sampler.num_neighbors[-1])
    cap = int(np.ceil(interior / multiple) * multiple) + leaf_w
    cap = max(cap, sum(sampler._widths) + leaf_w)
    return min(cap, sampler.full_node_capacity)


class NeighborSampler(BaseSampler):
    """Fixed-fanout multi-hop sampler over a :class:`~glt_tpu.data.graph.Graph`.

    Args:
      graph: device-resident CSR graph.
      num_neighbors: per-hop fanouts, e.g. ``[15, 10, 5]``.
      batch_size: static seed-batch width (callers pad the last batch).
      frontier_cap: optional cap on per-hop frontier width (memory knob).
      with_edge: emit global edge ids.
      seed: base PRNG seed; each ``sample_from_nodes`` call advances a
        counter so batches are independent yet reproducible (the analog of
        the curand Philox stream setup, random_sampler.cu:71-73).
      dedup: 'dense' (O(N) scatter-map inducer, ~10x faster at wide
        frontiers), 'sort' (O(M log^2 M) argsort-based, no O(N) state), or
        'auto' (dense unless the id map would exceed ~1GB).
      last_hop_dedup: when False, final-hop neighbors skip the inducer
        entirely and land in a contiguous leaf block of the node list
        (duplicates allowed).  The sampled edge multiset, every edge's
        endpoint features, and all shapes are identical (static
        capacities already assume zero dedup); the one semantic change
        is that a final-hop duplicate of an *interior* node becomes a
        fresh leaf — it aggregates from raw features instead of reusing
        the interior node's sampled out-edges (the tree-unrolled
        semantics of the original GraphSAGE algorithm).  The node list
        may repeat leaf ids, so ``num_sampled_nodes[-1]`` counts sampled
        (not unique) leaves.  Cuts the widest frontier from six random
        element-ops per candidate to one (the neighbor read) — ~1.7x
        end-to-end; see BASELINE.md.  Default True = exact reference
        semantics (unique node list, csrc/cuda/inducer.cu:95).
    """

    def __init__(
        self,
        graph: Graph,
        num_neighbors: Sequence[int],
        batch_size: int = 512,
        frontier_cap: Optional[int] = None,
        with_edge: bool = True,
        seed: int = 0,
        dedup: str = "auto",
        last_hop_dedup: bool = True,
        node_capacity: Optional[int] = None,
        sample_force: str = "auto",
    ):
        self.graph = graph
        self.num_neighbors = list(num_neighbors)
        self.batch_size = int(batch_size)
        self.frontier_cap = frontier_cap
        self.with_edge = with_edge
        self.last_hop_dedup = bool(last_hop_dedup)
        # Neighbor-read kernel seam, passed through to every
        # sample_neighbors call ('auto'|'pallas'|'xla'|'interpret'; see
        # ops/sample_pallas.py).  'auto' serves whatever autotune_sample
        # memoized for each hop's exact (width, fanout) shape.
        self.sample_force = sample_force
        self._base_key = jax.random.PRNGKey(seed)
        self._call_count = 0

        if dedup not in ("auto", "dense", "sort"):
            raise ValueError(f"dedup must be auto|dense|sort, got {dedup!r}")
        if dedup == "auto":
            dedup = "dense" if dense_map_fits(graph.num_nodes) else "sort"
        self.dedup = dedup

        self._widths = hop_widths(self.batch_size, self.num_neighbors,
                                  frontier_cap)
        self.full_node_capacity = max_sampled_nodes(
            self.batch_size, self.num_neighbors, frontier_cap)
        if node_capacity is None:
            # Zero-dedup worst case — the reference's sizing
            # (_max_sampled_nodes, neighbor_sampler.py:595-612).
            self.node_capacity = self.full_node_capacity
        else:
            # Occupancy-sized cap (see calibrate_node_capacity): the
            # buffer holds only the first `node_capacity` uniques; later
            # discoveries overflow — their edges are masked and the batch
            # is flagged via metadata['overflow'].
            nc = int(node_capacity)
            leaf_w = (0 if self.last_hop_dedup
                      else self._widths[-1] * self.num_neighbors[-1])
            floor_cap = sum(self._widths) + leaf_w
            if nc < floor_cap:
                raise ValueError(
                    f"node_capacity {nc} below the frontier floor "
                    f"{floor_cap} (sum of hop widths + leaf block)")
            self.node_capacity = min(nc, self.full_node_capacity)
        self.capped = self.node_capacity < self.full_node_capacity
        self.edge_capacity = sum(
            w * f for w, f in zip(self._widths, self.num_neighbors))

        self._sample_jit = jax.jit(self._sample_impl)
        self._sample_many_jit = {}
        self._sample_edges_jit = {}
        self._subgraph_jit = {}
        self._full_sibling: Optional["NeighborSampler"] = None

    def full_capacity_sibling(self) -> "NeighborSampler":
        """Uncapped twin (same graph/fanouts) for exact re-sampling of
        overflow-flagged batches (its program compiles lazily on the
        first overflow; shapes differ, so consumers see a second
        compiled bucket)."""
        if not self.capped:
            return self
        if self._full_sibling is None:
            self._full_sibling = NeighborSampler(
                self.graph, self.num_neighbors, self.batch_size,
                frontier_cap=self.frontier_cap, with_edge=self.with_edge,
                dedup=self.dedup, last_hop_dedup=self.last_hop_dedup,
                sample_force=self.sample_force)
        return self._full_sibling

    # -- key management ----------------------------------------------------
    def _next_key(self) -> jax.Array:
        key = jax.random.fold_in(self._base_key, self._call_count)
        self._call_count += 1
        return key

    # -- core jitted multi-hop program ------------------------------------
    def _sample_impl(self, indptr, indices, edge_ids, seeds, key):
        """One fused multi-hop sample. seeds: [batch_size], -1 padded.

        Dedup strategy ('dense' default): an O(N) scatter-map inducer
        (:func:`dense_induce`) replaces per-hop argsorts — on TPU the
        sorts were ~10x the rest of the pipeline at hop-3 frontier
        widths.  'sort' keeps the growing-buffer argsort path for graphs
        too large for the dense id map.
        """
        fanouts = self.num_neighbors
        widths = self._widths
        cap = self.node_capacity
        dense = self.dedup == "dense"

        if dense:
            state = dense_induce_init(self.graph.num_nodes, cap)
            state, _ = dense_induce(state, seeds)
            node_buf = state.node_buf
            count = state.count
            frontier = node_buf[: widths[0]]
        else:
            u0 = unique_first_occurrence(seeds)
            # The unique buffer GROWS hop by hop (static per-hop sizes):
            # hop i sorts only O(nodes discoverable by hop i) keys.
            node_buf = u0.uniques            # [widths[0]], -1 padded
            count = u0.count                 # valid uniques so far
            frontier = u0.uniques            # [widths[0]]
        frontier_start = jnp.zeros((), jnp.int32)

        rows, cols, eids, emasks = [], [], [], []
        counts_per_hop = [count]
        edges_per_hop = []
        keys = jax.random.split(key, len(fanouts))
        # Static interior capacity: where the no-dedup leaf block starts.
        leaf_off = cap - widths[-1] * fanouts[-1]
        leaf_mask = None
        capped = self.capped
        # Largest valid interior local index + 1: under an occupancy-sized
        # cap, nodes assigned locals past this are overflow — their edges
        # are masked and the batch flagged (the uncapped program compiles
        # byte-identically: every `capped` branch below is trace-time
        # static and off).
        interior_cap = cap if self.last_hop_dedup else leaf_off

        for i, f in enumerate(fanouts):
            w = widths[i]
            last = i + 1 == len(fanouts)
            out = sample_neighbors(indptr, indices, frontier, f, keys[i],
                                   edge_ids=edge_ids,
                                   with_edge=self.with_edge,
                                   force=self.sample_force)
            # Seed-side local indices (position of frontier nodes in node_buf).
            src_local = frontier_start + jnp.arange(w, dtype=jnp.int32)
            src_local = jnp.where(frontier >= 0, src_local, PADDING_ID)
            emask = out.mask
            if capped:
                # Frontier slots past the cap hold garbage on overflow
                # batches; mask every edge they source.
                src_local = jnp.where(src_local < interior_cap, src_local,
                                      PADDING_ID)
                emask = emask & (src_local >= 0)[:, None]

            # Insert this hop's neighbors into the cumulative unique list;
            # old uniques keep their positions.
            cand = out.nbrs.ravel()                        # [w*f]
            if last and not self.last_hop_dedup:
                # Leaf block: no inducer at the widest frontier.  Local
                # ids are static offsets; the only memory traffic is one
                # CONTIGUOUS store of the candidates themselves.
                leaf_mask = emask.ravel()
                leaf_ids = jnp.where(leaf_mask, cand, PADDING_ID)
                nbr_local = (leaf_off
                             + jnp.arange(w * f, dtype=jnp.int32)
                             ).reshape(w, f)
                if dense:
                    node_buf = jax.lax.dynamic_update_slice(
                        node_buf, leaf_ids, (leaf_off,))
                elif capped:
                    # The growing sort-path buffer has full-width interior
                    # length L >= leaf_off; truncate to leaf_off so the
                    # leaf block lands exactly where nbr_local points
                    # (interior locals >= leaf_off are already masked).
                    node_buf = jnp.concatenate([node_buf[:leaf_off],
                                                leaf_ids])
                else:
                    node_buf = jnp.concatenate([node_buf, leaf_ids])
                new_count = count + jnp.sum(leaf_mask.astype(jnp.int32))
            elif dense:
                induce = dense_induce_final if last else dense_induce
                state, nbr_local = induce(state, cand)
                node_buf = state.node_buf
                new_count = state.count
                nbr_local = nbr_local.reshape(w, f)
            else:
                buflen = node_buf.shape[0]
                merged = unique_first_occurrence(
                    jnp.concatenate([node_buf, cand]))
                node_buf = merged.uniques              # [buflen + w*f]
                new_count = merged.count
                nbr_local = merged.inverse[buflen:].reshape(w, f)
            nbr_local = jnp.where(emask, nbr_local, PADDING_ID)
            if capped and not (last and not self.last_hop_dedup):
                # Induced locals past the cap point at dropped nodes
                # (dense_induce dump-slot clamp / sort-path truncation):
                # mask those edges out.
                lim = cap if self.last_hop_dedup else interior_cap
                nbr_local = jnp.where(nbr_local < lim, nbr_local,
                                      PADDING_ID)
                emask = emask & (nbr_local >= 0)

            rows.append(nbr_local.ravel())
            cols.append(jnp.broadcast_to(src_local[:, None], (w, f)).ravel())
            if self.with_edge:
                eids.append(out.eids.ravel())
            emasks.append(emask.ravel())
            edges_per_hop.append(jnp.sum(emask.astype(jnp.int32)))

            if not last:
                nw = widths[i + 1]
                frontier = jax.lax.dynamic_slice(
                    jnp.concatenate(
                        [node_buf,
                         jnp.full((nw,), PADDING_ID, jnp.int32)]),
                    (jnp.clip(count, 0, node_buf.shape[0]),), (nw,))
                frontier_start = count
            count = new_count
            counts_per_hop.append(count)

        # Pad/trim the final buffer to the static capacity.
        if node_buf.shape[0] < cap:
            node_buf = jnp.concatenate(
                [node_buf,
                 jnp.full((cap - node_buf.shape[0],), PADDING_ID,
                          jnp.int32)])
        node_buf = node_buf[:cap]
        count = jnp.minimum(count, cap)
        if leaf_mask is None:
            node_mask = jnp.arange(cap, dtype=jnp.int32) < count
        else:
            # Interior prefix is compact; the leaf block keeps its own
            # validity mask (holes between interior count and leaf_off).
            interior = jnp.minimum(count - edges_per_hop[-1], leaf_off)
            node_mask = (jnp.arange(cap, dtype=jnp.int32) < interior) | (
                jnp.concatenate([jnp.zeros((leaf_off,), bool), leaf_mask]))

        num_sampled_nodes = jnp.stack(
            [counts_per_hop[0]]
            + [counts_per_hop[i + 1] - counts_per_hop[i]
               for i in range(len(fanouts))])
        metadata = None
        if capped:
            # `count` keeps counting uniques past the cap (dense_induce's
            # dump slot absorbs their writes), so overflow is exactly
            # "more uniques discovered than the buffer holds".  Loaders
            # check this flag to fall back to the exact full-capacity
            # program; the flagged batch itself is still safe to train on
            # (overflow-node edges are masked above).
            # counts_per_hop holds the UNCLAMPED totals (`count` itself is
            # min'd to cap just above for the node_mask).
            if self.last_hop_dedup:
                overflow = counts_per_hop[-1] > cap
            else:
                overflow = counts_per_hop[len(fanouts) - 1] > leaf_off
            metadata = {"overflow": overflow}
        return SamplerOutput(
            node=node_buf,
            # Direction transpose: row = neighbor side, col = seed side
            # (neighbor_sampler.py:159-165).
            row=jnp.concatenate(rows),
            col=jnp.concatenate(cols),
            edge=jnp.concatenate(eids) if self.with_edge else None,
            batch=seeds,
            node_mask=node_mask,
            edge_mask=jnp.concatenate(emasks),
            num_sampled_nodes=num_sampled_nodes,
            num_sampled_edges=jnp.stack(edges_per_hop),
            metadata=metadata,
        )

    # -- public API (cf. sampler/neighbor_sampler.py:138) ------------------
    def sample_from_nodes(self, inputs: NodeSamplerInput,
                          key: Optional[jax.Array] = None) -> SamplerOutput:
        ids = inputs.node
        if (isinstance(ids, jax.Array)
                and ids.shape == (self.batch_size,)):
            # Pre-staged device seeds (already padded): skip the host
            # round-trip — prefetching loaders ship seed batches to HBM
            # ahead of time (the reference's pin_memory + .to(device)).
            seeds = ids.astype(jnp.int32)
        else:
            seeds = jnp.asarray(_pad_ids(np.asarray(ids), self.batch_size))
        if key is None:
            key = self._next_key()
        g = self.graph
        return self._sample_jit(g.indptr, g.indices, g.gather_edge_ids,
                                seeds, key)

    def sample_from_nodes_batched(self, seeds: jnp.ndarray,
                                  key: Optional[jax.Array] = None
                                  ) -> SamplerOutput:
        """Sample ``G`` seed batches in ONE device program.

        ``seeds``: ``[G, batch_size]`` (-1 padded) device or host array.
        Returns a stacked :class:`SamplerOutput` pytree (leading axis G).

        This is the TPU analog of the reference's per-worker in-flight
        concurrency (``worker_concurrency`` <= 32 async batches,
        dist_options.py / event_loop.py): a ``lax.scan`` chains G
        independent batches inside one XLA program, amortising host
        dispatch (one call instead of G).  Measured device time per batch
        is ~parity with the single-batch path at batch 1024 (device work
        dominates); the win appears when dispatch is the constraint —
        many small batches, or busy host threads.  The scan keeps
        scatters unbatched: a vmap formulation batches the dense-inducer
        scatters and is ~60x slower.
        """
        seeds = jnp.asarray(seeds, jnp.int32)
        if seeds.ndim != 2 or seeds.shape[1] != self.batch_size:
            raise ValueError(
                f"expected [G, {self.batch_size}] seeds, got {seeds.shape}")
        g = int(seeds.shape[0])
        if key is None:
            key = self._next_key()
        if g not in self._sample_many_jit:
            def many(indptr, indices, edge_ids, seeds_g, key):
                keys = jax.random.split(key, g)

                def body(carry, inp):
                    sd, k = inp
                    return carry, self._sample_impl(indptr, indices,
                                                    edge_ids, sd, k)

                _, outs = jax.lax.scan(body, jnp.zeros((), jnp.int32),
                                       (seeds_g, keys))
                return outs

            # One program per group count, cached in _sample_many_jit —
            # the closure over `g` is the compile-cache key, not a leak.
            self._sample_many_jit[g] = jax.jit(many)  # gltlint: disable=recompile-hazard
        gr = self.graph
        return self._sample_many_jit[g](gr.indptr, gr.indices,
                                        gr.gather_edge_ids, seeds, key)

    def sample_one_hop(self, srcs: jnp.ndarray, fanout: int,
                       key: Optional[jax.Array] = None):
        """Single-hop primitive, used by the distributed sampler
        (cf. neighbor_sampler.py:118 ``sample_one_hop``)."""
        if key is None:
            key = self._next_key()
        g = self.graph
        return sample_neighbors(g.indptr, g.indices, srcs, fanout, key,
                                edge_ids=g.gather_edge_ids,
                                with_edge=self.with_edge,
                                force=self.sample_force)

    # -- link path (cf. neighbor_sampler.py:255 sample_from_edges) ---------
    def sample_from_edges(self, inputs: EdgeSamplerInput,
                          key: Optional[jax.Array] = None) -> SamplerOutput:
        neg = inputs.neg_sampling
        q = self.batch_size  # static positive-edge width
        src = _pad_ids(inputs.row, q)
        dst = _pad_ids(inputs.col, q)
        num_pos = int(len(inputs))
        if key is None:
            key = self._next_key()

        mode = None if neg is None else neg.mode
        amount = 0 if neg is None else int(round(neg.amount))
        cdf = None if neg is None else neg.cdf()
        fn = self._get_edges_jit(mode, amount, cdf is not None)
        g = self.graph
        label = (None if inputs.label is None
                 else jnp.asarray(_pad_ids(inputs.label, q)))
        sorted_indices = (g.sorted_indices if mode is not None else g.indices)
        out = fn(g.indptr, g.indices, g.gather_edge_ids, sorted_indices,
                 jnp.asarray(src), jnp.asarray(dst),
                 jnp.zeros((1,), jnp.float32) if cdf is None else cdf, key)
        # Labels are host-side metadata; attach eagerly.
        if mode == "binary":
            meta = out.metadata or {}
            pos_label = (jnp.ones((q,), jnp.int32) if label is None
                         else label + 1)
            pos_label = jnp.where(jnp.asarray(src) >= 0, pos_label, PADDING_ID)
            neg_label = jnp.zeros((q * amount,), jnp.int32)
            meta["edge_label"] = jnp.concatenate([pos_label, neg_label])
            out.metadata = meta
        elif mode is None and label is not None:
            # Pass the caller's labels through unchanged (reference homo
            # None branch: edge_label untouched, no +1 increment).
            meta = out.metadata or {}
            meta["edge_label"] = jnp.where(jnp.asarray(src) >= 0, label,
                                           PADDING_ID)
            out.metadata = meta
        out.metadata = out.metadata or {}
        out.metadata["num_pos"] = jnp.asarray(num_pos, jnp.int32)
        return out

    def _get_edges_jit(self, mode: Optional[str], amount: int,
                       weighted: bool = False):
        k = (mode, amount, weighted)
        if k not in self._sample_edges_jit:
            self._sample_edges_jit[k] = jax.jit(
                partial(self._sample_edges_impl, mode, amount, weighted))
        return self._sample_edges_jit[k]

    def _sample_edges_impl(self, mode, amount, weighted, indptr, indices,
                           edge_ids, sorted_indices, src, dst, cdf, key):
        q = self.batch_size
        kneg, ksample = jax.random.split(key)
        num_nodes = self.graph.num_nodes
        node_cdf = cdf if weighted else None

        if mode == "binary":
            # Strict rejection (trials + non-strict padding); weighted
            # draws bias both endpoints through NegativeSampling.weight.
            negs = sample_negative_edges(indptr, sorted_indices, q * amount,
                                         kneg, num_nodes,
                                         src_cdf=node_cdf, dst_cdf=node_cdf)
            seed_ids = jnp.concatenate([src, dst, negs.src, negs.dst])
        elif mode == "triplet":
            # amount negative destinations per positive source
            # (cf. neighbor_sampler.py:332-381 triplet reconstruction).
            if weighted:
                neg_dst = weighted_draw(kneg, cdf, (q * amount,))
            else:
                neg_dst = jax.random.randint(kneg, (q * amount,), 0,
                                             num_nodes, dtype=jnp.int32)
            neg_dst = jnp.where(jnp.repeat(src >= 0, amount), neg_dst,
                                PADDING_ID)
            seed_ids = jnp.concatenate([src, dst, neg_dst])
        else:
            seed_ids = jnp.concatenate([src, dst])

        # Dedup seeds, then run the node path with the union as the batch.
        seed_width = seed_ids.shape[0]
        if seed_width != self.batch_size:
            sub = NeighborSampler.__new__(NeighborSampler)
            sub.__dict__.update(self.__dict__)
            sub.batch_size = seed_width
            sub._widths = hop_widths(seed_width, self.num_neighbors,
                                     self.frontier_cap)
            sub.node_capacity = max_sampled_nodes(seed_width,
                                                  self.num_neighbors,
                                                  self.frontier_cap)
            # The seed union runs at its own width's full capacity; an
            # occupancy cap on the node path does not transfer (different
            # batch width => different occupancy distribution).
            sub.full_node_capacity = sub.node_capacity
            sub.capped = False
            out = sub._sample_impl(indptr, indices, edge_ids, seed_ids,
                                   ksample)
        else:
            out = self._sample_impl(indptr, indices, edge_ids, seed_ids,
                                    ksample)

        meta = dict(out.metadata or {})
        # Seed ids all first-occur within the hop-0 prefix of the node
        # list, so relabel against that slice only — with
        # last_hop_dedup=False the tail leaf block may hold duplicate
        # copies of a seed, and a leaf copy has no deep embedding.
        ref = out.node[:seed_width]
        if mode == "binary":
            all_src = jnp.concatenate([src, negs.src])
            all_dst = jnp.concatenate([dst, negs.dst])
            meta["edge_label_index"] = jnp.stack([
                relabel_by_reference(ref, all_src),
                relabel_by_reference(ref, all_dst),
            ])
        elif mode == "triplet":
            meta["src_index"] = relabel_by_reference(ref, src)
            meta["dst_pos_index"] = relabel_by_reference(ref, dst)
            meta["dst_neg_index"] = relabel_by_reference(
                ref, neg_dst).reshape(q, amount)
        else:
            # No negative sampling still emits edge_label_index so the
            # LinkLoader can locate seed edges in the batch
            # (neighbor_sampler.py:366-372, the None-or-binary branch).
            meta["edge_label_index"] = jnp.stack([
                relabel_by_reference(ref, src),
                relabel_by_reference(ref, dst),
            ])
        out.metadata = meta
        return out

    # -- hotness estimation (cf. neighbor_sampler.py:435-562 sample_prob,
    #    CalNbrProb kernel random_sampler.cu:168-209) ----------------------
    def sample_prob(self, seed_ids: np.ndarray, node_count: int) -> jnp.ndarray:
        """Per-node probability of being touched by sampling from ``seeds``.

        One full-graph sparse propagation per hop: an edge ``u -> v``
        contributes ``p_u * min(fanout / deg_u, 1)`` to ``p_v`` (exactly the
        per-edge weight the CUDA ``CalNbrProb`` kernel applies); hop results
        are union-bounded into a cumulative visit probability.  Used by the
        frequency partitioner's hotness scores.
        """
        g = self.graph
        indptr, indices = g.indptr, g.indices
        num_nodes = int(indptr.shape[0]) - 1
        edge_src = jnp.searchsorted(
            indptr, jnp.arange(indices.shape[0], dtype=indptr.dtype),
            side="right").astype(jnp.int32) - 1
        deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)

        prob = jnp.zeros((num_nodes,), jnp.float32)
        prob = prob.at[jnp.asarray(seed_ids, jnp.int32)].set(1.0)
        total = prob
        for f in self.num_neighbors:
            w = jnp.minimum(f / jnp.maximum(deg, 1.0), 1.0)
            contrib = prob[edge_src] * w[edge_src]
            nxt = jax.ops.segment_sum(contrib, indices,
                                      num_segments=num_nodes)
            prob = jnp.minimum(nxt, 1.0)
            total = jnp.minimum(total + prob, 1.0)
        if node_count > num_nodes:
            total = jnp.concatenate(
                [total, jnp.zeros((node_count - num_nodes,), jnp.float32)])
        return total

    # -- induced subgraph (cf. neighbor_sampler.py:409-433) ---------------
    def subgraph(self, inputs: NodeSamplerInput, max_degree: int = 64,
                 key: Optional[jax.Array] = None) -> SamplerOutput:
        """Hop expansion + induced-subgraph extraction (SubGraphOp path).

        Unlike ``sample_from_nodes`` (whose ``row`` is the transposed
        message-source side), the induced subgraph keeps **graph-direction
        COO**: ``row`` = CSR source, ``col`` = destination, matching the
        reference SubGraph op (csrc/cuda/subgraph_op.cu) and PyG's
        ``subgraph()``. Subgraph models (SEAL/DGCNN) treat the extract as
        a standalone graph, so the raw direction is preserved.
        """
        if not self.last_hop_dedup:
            raise ValueError(
                "subgraph() requires last_hop_dedup=True: the induced "
                "extract relabels against a unique node set")
        ids = inputs.node
        if isinstance(ids, jax.Array) and ids.shape == (self.batch_size,):
            seeds = ids.astype(jnp.int32)
        else:
            seeds = jnp.asarray(_pad_ids(np.asarray(ids), self.batch_size))
        if key is None:
            key = self._next_key()
        # ONE program: hop expansion + induced extraction.  The eager
        # composition (sample jit, then op-by-op node_subgraph) paid ~20
        # per-op dispatches per batch — pure host/tunnel overhead.
        k = int(max_degree)
        if k not in self._subgraph_jit:
            def fused(indptr, indices, hop_eids, sub_eids, seeds, key,
                      _k=k):
                base = self._sample_impl(indptr, indices, hop_eids, seeds,
                                         key)
                sub = node_subgraph(indptr, indices, base.node, _k,
                                    edge_ids=sub_eids)
                return base, sub

            # One program per max_degree, cached in _subgraph_jit — the
            # baked `_k=k` default is the compile-cache key, not a leak.
            self._subgraph_jit[k] = jax.jit(fused)  # gltlint: disable=recompile-hazard
        g = self.graph
        # gather_edge_ids for the hop loop (None when ids are positional
        # — skips identity gathers); real edge ids for the extract.
        base, sub = self._subgraph_jit[k](g.indptr, g.indices,
                                          g.gather_edge_ids, g.edge_ids,
                                          seeds, key)
        return SamplerOutput(
            node=base.node,
            row=sub.rows,
            col=sub.cols,
            edge=sub.eids,
            batch=base.batch,
            node_mask=base.node_mask,
            edge_mask=sub.mask,
            num_sampled_nodes=base.num_sampled_nodes,
            metadata={"mapping": jnp.arange(self.batch_size, dtype=jnp.int32),
                      **(base.metadata or {})},
        )
