"""Heterogeneous multi-hop neighbor sampling, fully jitted.

Rebuild of the reference's hetero path (neighbor_sampler.py:192-253 +
``CUDAHeteroInducer``, csrc/cuda/inducer.cu:208-345): the reference loops
``num_hops`` over edge types, sampling each type's frontier and deduping
per node type with one hash table per type.  Here the same structure is
traced into one XLA program: per-node-type cumulative unique buffers with
static per-hop widths derived from the fanout dict, per-edge-type sampling
kernels, and the same reversed-edge-type output convention
(neighbor_sampler.py:236-243).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph
from ..ops.negative_sample import sample_negative_edges, weighted_draw
from ..ops.neighbor_sample import sample_neighbors
from ..ops.unique import (
    dense_induce,
    dense_induce_final,
    dense_induce_init,
    dense_map_fits,
    unique_first_occurrence,
)
from ..typing import EdgeType, NodeType, PADDING_ID, reverse_edge_type
from ..ops.unique import relabel_by_reference
from .base import BaseSampler, HeteroSamplerOutput, NodeSamplerInput
from .neighbor_sampler import _pad_ids


def hetero_hop_widths(
    edge_types: Sequence[EdgeType],
    num_neighbors: Dict[EdgeType, List[int]],
    seed_widths: Dict[NodeType, int],
    num_hops: int,
    frontier_cap: Optional[int] = None,
) -> Tuple[List[Dict[NodeType, int]], Dict[NodeType, int]]:
    """Static frontier width per (hop, node type) + total capacity per type.

    Mirrors the implicit bound of the reference's hetero loop: the hop-``i``
    frontier of type ``t`` is every node of type ``t`` first discovered at
    hop ``i-1`` across all edge types ending in ``t``.  ``seed_widths``
    gives the hop-0 frontier per type (node sampling seeds one type; link
    sampling seeds the edge's endpoint types).

    ``frontier_cap`` bounds each (hop, type) frontier, exactly like the
    homo sampler's knob (neighbor_sampler.py ``hop_widths``): without it,
    widths multiply across edge types per hop and IGBH-scale fanouts
    explode trace-time capacities.  Newly-discovered nodes beyond the cap
    don't expand further hops (they stay in the node set).
    """
    ntypes = sorted({et[0] for et in edge_types} | {et[2] for et in edge_types}
                    | set(seed_widths))
    widths: List[Dict[NodeType, int]] = [
        {t: seed_widths.get(t, 0) for t in ntypes}]
    for hop in range(num_hops):
        nxt = {t: 0 for t in ntypes}
        for et in edge_types:
            fanouts = num_neighbors[et]
            if hop < len(fanouts) and fanouts[hop] > 0:
                nxt[et[2]] += widths[hop][et[0]] * fanouts[hop]
        if frontier_cap is not None:
            nxt = {t: min(w, frontier_cap) for t, w in nxt.items()}
        widths.append(nxt)
    capacity = {t: sum(w[t] for w in widths) for t in ntypes}
    return widths, capacity


def _node_mask(buf: jnp.ndarray, count: jnp.ndarray, fast) -> jnp.ndarray:
    """Validity mask for a per-type node buffer: compact prefix, or
    (interior prefix | leaf-region mask) when the final hop used the
    no-dedup leaf block."""
    idx = jnp.arange(buf.shape[0], dtype=jnp.int32)
    if fast is None:
        return idx < count
    leaf_off, leaf_region, interior = fast
    return (idx < jnp.minimum(interior, leaf_off)) | leaf_region


class HeteroNeighborSampler(BaseSampler):
    """Fixed-fanout hetero sampler over per-edge-type :class:`Graph` s.

    Args:
      graphs: dict ``EdgeType -> Graph`` (out-edge CSR per type).
      num_neighbors: per-hop fanouts — a list (applied to every edge type)
        or a dict keyed by edge type.
      input_type: node type of the seeds.
      batch_size: static seed width.
    """

    def __init__(
        self,
        graphs: Dict[EdgeType, Graph],
        num_neighbors,
        input_type: NodeType,
        batch_size: int = 512,
        frontier_cap: Optional[int] = None,
        seed: int = 0,
        last_hop_dedup: bool = True,
    ):
        self.graphs = graphs
        self.edge_types = sorted(graphs.keys())
        if isinstance(num_neighbors, dict):
            self.num_neighbors = {et: list(v)
                                  for et, v in num_neighbors.items()}
        else:
            self.num_neighbors = {et: list(num_neighbors)
                                  for et in self.edge_types}
        self.num_hops = max(len(v) for v in self.num_neighbors.values())
        self.input_type = input_type
        self.batch_size = int(batch_size)
        self.last_hop_dedup = bool(last_hop_dedup)
        self._base_key = jax.random.PRNGKey(seed)
        self._call_count = 0

        self.frontier_cap = frontier_cap
        self._widths, self._capacity = hetero_hop_widths(
            self.edge_types, self.num_neighbors,
            {input_type: self.batch_size}, self.num_hops,
            frontier_cap=frontier_cap)
        self.node_types = sorted(self._capacity.keys())
        # Per-type node counts for the dense inducer.  A type's id space
        # must cover BOTH roles: its CSR row count where it is a source
        # AND the max destination id arriving from other edge types
        # (CSRTopo derives num_nodes from one edge type's own ids, so a
        # source-only bound can undercount and silently drop neighbors).
        # Types with no evidence fall back to the sort-based inducer.
        self._num_nodes_by_type = {}
        for et, g in graphs.items():
            if g is None:
                continue
            src_t, _, dst_t = et
            self._num_nodes_by_type[src_t] = max(
                self._num_nodes_by_type.get(src_t, 0), g.num_nodes)
            idx = np.asarray(g.topo.indices)
            if idx.size:
                self._num_nodes_by_type[dst_t] = max(
                    self._num_nodes_by_type.get(dst_t, 0),
                    int(idx.max()) + 1)
        self._sample_jit = jax.jit(
            partial(self._sample_impl, self._widths, self._capacity))
        self._edges_jit = {}

    @property
    def node_capacity(self) -> Dict[NodeType, int]:
        """Static per-node-type unique-node capacity (mirrors the
        distributed sampler's property — shared by state initializers)."""
        return dict(self._capacity)

    @property
    def hop_widths(self) -> List[Dict[NodeType, int]]:
        """Per-hop per-node-type frontier widths (static trace shapes)."""
        return [dict(w) for w in self._widths]

    def _next_key(self) -> jax.Array:
        key = jax.random.fold_in(self._base_key, self._call_count)
        self._call_count += 1
        return key

    def _sample_impl(self, widths, cap, graph_arrays, seeds_dict, key,
                     one_hop=None):
        """graph_arrays: dict et -> (indptr, indices, edge_ids);
        seeds_dict: dict ntype -> padded seed ids (hop-0 frontiers);
        one_hop: optional override ``(et, arrays, frontier, fanout, key) ->
        NeighborOutput`` — the distributed sampler plugs its all-to-all
        exchange here, keeping this multi-hop body single-source."""
        node_types = sorted(cap.keys())

        # Per-type inducer choice: dense O(N_t) scatter map when the
        # type's node count is known and the map is small enough
        # (mirrors NeighborSampler's dedup='auto'); sort otherwise.
        dense_state = {}
        for t in node_types:
            n_t = self._num_nodes_by_type.get(t)
            if n_t is not None and dense_map_fits(n_t):
                dense_state[t] = dense_induce_init(n_t, max(cap[t], 1))

        node_buf = {
            t: (dense_state[t].node_buf[: max(cap[t], 1)]
                if t in dense_state
                else jnp.full((max(cap[t], 1),), PADDING_ID, jnp.int32))
            for t in node_types}
        count = {t: jnp.zeros((), jnp.int32) for t in node_types}
        frontier = {t: None for t in node_types}
        frontier_start = {t: jnp.zeros((), jnp.int32)
                          for t in node_types}

        for t0, seeds in seeds_dict.items():
            if t0 in dense_state:
                dense_state[t0], _ = dense_induce(dense_state[t0], seeds)
                buflen0 = node_buf[t0].shape[0]
                node_buf[t0] = dense_state[t0].node_buf[:buflen0]
                count[t0] = jnp.minimum(dense_state[t0].count, buflen0)
                frontier[t0] = node_buf[t0][: seeds.shape[0]]
            else:
                u0 = unique_first_occurrence(seeds)
                node_buf[t0] = (node_buf[t0].at[: seeds.shape[0]]
                                .set(u0.uniques))
                count[t0] = u0.count
                frontier[t0] = u0.uniques

        rows = {et: [] for et in self.edge_types}
        cols = {et: [] for et in self.edge_types}
        eids = {et: [] for et in self.edge_types}
        emasks = {et: [] for et in self.edge_types}
        counts_hist = {t: [count[t]] for t in node_types}
        # t -> (leaf_off, full-leaf-region validity mask, interior count)
        # for types whose final hop used the no-dedup leaf block.
        fast_leaf = {}
        # Worst-case interior uniques per type: seeds + every RAW
        # candidate of hops before the last.  With frontier_cap the
        # capacity budgets *capped* widths while the inducer inserts raw
        # candidates, so the interior can outgrow the leaf block — the
        # fast path must stay off for such types (exact mode masks
        # overflow into the buffer tail instead).
        raw_interior = {t: widths[0].get(t, 0) for t in node_types}
        for h in range(self.num_hops - 1):
            for et in self.edge_types:
                fo = self.num_neighbors[et]
                f = fo[h] if h < len(fo) else 0
                if f > 0:
                    raw_interior[et[2]] += widths[h][et[0]] * f

        keys = jax.random.split(key, self.num_hops * len(self.edge_types))

        for hop in range(self.num_hops):
            # 1) sample every active edge type from its src frontier
            hop_out = {}   # et -> (nbrs, eids, mask, src_local)
            for ei_idx, et in enumerate(self.edge_types):
                fanouts = self.num_neighbors[et]
                f = fanouts[hop] if hop < len(fanouts) else 0
                w = widths[hop][et[0]]
                if f <= 0 or w <= 0 or frontier[et[0]] is None:
                    continue
                hop_key = keys[hop * len(self.edge_types) + ei_idx]
                if one_hop is not None:
                    out = one_hop(et, graph_arrays[et], frontier[et[0]], f,
                                  hop_key)
                else:
                    indptr, indices, edge_ids = graph_arrays[et]
                    out = sample_neighbors(indptr, indices, frontier[et[0]],
                                           f, hop_key, edge_ids=edge_ids)
                src_local = (frontier_start[et[0]]
                             + jnp.arange(w, dtype=jnp.int32))
                src_local = jnp.where(frontier[et[0]] >= 0, src_local,
                                      PADDING_ID)
                hop_out[et] = (out, src_local, w, f)

            # 2) per dst type: merge all candidates into the unique buffer
            new_frontier = {}
            for t in node_types:
                ets = [et for et in hop_out if et[2] == t]
                if not ets:
                    continue
                cands = jnp.concatenate(
                    [hop_out[et][0].nbrs.ravel() for et in ets])
                buflen = node_buf[t].shape[0]
                total_wf = sum(hop_out[et][2] * hop_out[et][3] for et in ets)
                # Leaf-block fast path (see NeighborSampler.last_hop_dedup):
                # only when the final-hop width wasn't frontier_cap-capped
                # below the raw candidate count (a capped width can't hold
                # every candidate at a static offset) AND the worst-case
                # interior fits below the leaf block (it always does when
                # frontier_cap is None).
                if (hop + 1 == self.num_hops and not self.last_hop_dedup
                        and widths[hop + 1][t] >= total_wf
                        and raw_interior[t] <= buflen - widths[hop + 1][t]):
                    leaf_off = buflen - widths[hop + 1][t]
                    cmask = jnp.concatenate(
                        [hop_out[et][0].mask.ravel() for et in ets])
                    leaf_ids = jnp.where(cmask, cands, PADDING_ID)
                    uniques_src = jax.lax.dynamic_update_slice(
                        node_buf[t], leaf_ids, (leaf_off,))
                    merged_count = count[t] + jnp.sum(cmask.astype(jnp.int32))
                    inverse_tail = jnp.where(
                        cmask,
                        leaf_off + jnp.arange(total_wf, dtype=jnp.int32),
                        PADDING_ID)
                    off = 0
                    leaf_region = jnp.concatenate([
                        jnp.zeros((leaf_off,), bool), cmask,
                        jnp.zeros((buflen - leaf_off - total_wf,), bool)])
                    fast_leaf[t] = (leaf_off, leaf_region, count[t])
                elif t in dense_state:
                    # Final hop: nothing re-reads the id map afterwards,
                    # so skip the commit scatter (ops/unique.py).
                    induce = (dense_induce_final
                              if hop + 1 == self.num_hops else dense_induce)
                    dense_state[t], locs = induce(dense_state[t], cands)
                    uniques_src = dense_state[t].node_buf
                    merged_count = dense_state[t].count
                    inverse_tail = locs
                    off = 0
                else:
                    merged = unique_first_occurrence(
                        jnp.concatenate([node_buf[t], cands]))
                    uniques_src = merged.uniques
                    merged_count = merged.count
                    inverse_tail = merged.inverse
                    off = buflen
                # per-etype segments of the candidates' local ids
                for et in ets:
                    out, src_local, w, f = hop_out[et]
                    nbr_local = inverse_tail[off: off + w * f].reshape(w, f)
                    off += w * f
                    # With a frontier_cap the unique buffer can fill before
                    # every candidate lands; edges to dropped nodes must be
                    # masked, or nbr_local would index past the buffer.
                    ok = out.mask & (nbr_local >= 0) & (nbr_local < buflen)
                    nbr_local = jnp.where(ok, nbr_local, PADDING_ID)
                    # reversed edge type, transposed direction
                    rows[et].append(nbr_local.ravel())
                    cols[et].append(
                        jnp.broadcast_to(src_local[:, None], (w, f)).ravel())
                    eids[et].append(out.eids.ravel())
                    emasks[et].append(ok.ravel())

                old_count = count[t]
                nw = widths[hop + 1][t]
                if nw > 0 and hop + 1 < self.num_hops:
                    # Slice strictly within the buffer: overflowed nodes
                    # (and the dense dump slot) never become frontier.
                    new_frontier[t] = jax.lax.dynamic_slice(
                        jnp.concatenate(
                            [uniques_src[:buflen],
                             jnp.full((nw,), PADDING_ID, jnp.int32)]),
                        (jnp.clip(old_count, 0, buflen),),
                        (nw,))
                node_buf[t] = uniques_src[:buflen]
                count[t] = jnp.minimum(merged_count, buflen)
                frontier_start[t] = old_count

            for t in node_types:
                counts_hist[t].append(count[t])
                # the hop frontier is consumed; only newly discovered
                # nodes expand next hop
                frontier[t] = new_frontier.get(t)

        def cat_or_empty(lst, width_hint=1):
            if lst:
                return jnp.concatenate(lst)
            return jnp.full((0,), PADDING_ID, jnp.int32)

        rev = {et: reverse_edge_type(et) for et in self.edge_types}
        out = HeteroSamplerOutput(
            node={t: node_buf[t] for t in node_types},
            row={rev[et]: cat_or_empty(rows[et]) for et in self.edge_types},
            col={rev[et]: cat_or_empty(cols[et]) for et in self.edge_types},
            edge={rev[et]: cat_or_empty(eids[et]) for et in self.edge_types},
            batch=dict(seeds_dict),
            node_mask={t: _node_mask(node_buf[t], count[t],
                                     fast_leaf.get(t)) for t in node_types},
            edge_mask={rev[et]: (cat_or_empty(emasks[et]).astype(bool)
                                 if emasks[et] else
                                 jnp.zeros((0,), bool))
                       for et in self.edge_types},
            num_sampled_nodes={
                t: jnp.stack(
                    [counts_hist[t][0]]
                    + [counts_hist[t][i + 1] - counts_hist[t][i]
                       for i in range(len(counts_hist[t]) - 1)])
                for t in node_types},
            input_type=self.input_type,
        )
        return out

    def sample_from_nodes(self, inputs: NodeSamplerInput,
                          key: Optional[jax.Array] = None
                          ) -> HeteroSamplerOutput:
        seeds = _pad_ids(np.asarray(inputs.node), self.batch_size)
        if key is None:
            key = self._next_key()
        graph_arrays = {
            et: (g.indptr, g.indices, g.edge_ids)
            for et, g in self.graphs.items()}
        return self._sample_jit(graph_arrays,
                                {self.input_type: jnp.asarray(seeds)}, key)

    # -- hetero link path (cf. neighbor_sampler.py:255-381 hetero branch) --
    def sample_from_edges(self, inputs, key: Optional[jax.Array] = None
                          ) -> HeteroSamplerOutput:
        """Seed-edge sampling with optional binary/triplet negatives.

        Binary negatives are drawn **strict** — rejection-tested against
        the seed edge type's CSR via its sorted-column view, the hetero
        analog of the CUDA strict mode (random_negative_sampler.cu:37-54)
        — with the reference's non-strict padding fallback.  An optional
        ``NegativeSampling.weight`` biases negative draws over the
        destination node type.
        """
        et = inputs.input_type
        if et is None:
            raise ValueError("hetero EdgeSamplerInput needs input_type")
        src_t, _, dst_t = et
        neg = inputs.neg_sampling
        q = self.batch_size
        src = _pad_ids(np.asarray(inputs.row), q)
        dst = _pad_ids(np.asarray(inputs.col), q)
        if key is None:
            key = self._next_key()

        mode = None if neg is None else neg.mode
        amount = 0 if neg is None else int(round(neg.amount))
        cdf = None if neg is None else neg.cdf()
        fn = self._get_edges_jit(et, mode, amount, cdf is not None)
        graph_arrays = {
            e: (g.indptr, g.indices, g.edge_ids)
            for e, g in self.graphs.items()}
        seed_g = self.graphs[et]
        sorted_idx = (seed_g.sorted_indices if mode == "binary"
                      else seed_g.indices)
        out = fn(graph_arrays, sorted_idx, jnp.asarray(src),
                 jnp.asarray(dst),
                 jnp.zeros((1,), jnp.float32) if cdf is None else cdf, key)

        if mode == "binary":
            label = inputs.label
            pos_label = (jnp.ones((q,), jnp.int32) if label is None
                         else jnp.asarray(_pad_ids(label, q)) + 1)
            pos_label = jnp.where(jnp.asarray(src) >= 0, pos_label,
                                  PADDING_ID)
            out.metadata["edge_label"] = jnp.concatenate(
                [pos_label, jnp.zeros((q * amount,), jnp.int32)])
        elif mode is None and inputs.label is not None:
            label = jnp.asarray(_pad_ids(inputs.label, q))
            out.metadata["edge_label"] = jnp.where(
                jnp.asarray(src) >= 0, label, PADDING_ID)
        return out

    def _get_edges_jit(self, et, mode, amount, weighted: bool = False):
        k = (et, mode, amount, weighted)
        if k not in self._edges_jit:
            src_t, _, dst_t = et
            q = self.batch_size
            if mode == "binary":
                sw, dw = q * (1 + amount), q * (1 + amount)
            elif mode == "triplet":
                sw, dw = q, q * (1 + amount)
            else:
                sw, dw = q, q
            seed_widths = ({src_t: sw + dw} if src_t == dst_t
                           else {src_t: sw, dst_t: dw})
            widths, cap = hetero_hop_widths(
                self.edge_types, self.num_neighbors, seed_widths,
                self.num_hops, frontier_cap=self.frontier_cap)

            # Node counts are static: an edge type's CSR rows are its
            # source type's nodes.
            n_src = self.graphs[et].num_nodes
            dst_rows = [e for e in self.edge_types if e[0] == dst_t]
            if not dst_rows:
                raise ValueError(
                    f"cannot size negatives: no edge type has source type "
                    f"{dst_t!r} (needed for its node count)")
            n_dst = self.graphs[dst_rows[0]].num_nodes

            def impl(graph_arrays, sorted_idx, src, dst, cdf, key):
                kneg, ksample = jax.random.split(key)
                dst_cdf = cdf if weighted else None
                if mode == "binary":
                    # Strict rejection against the seed edge type's CSR
                    # (sorted-column binary search), weighted dst draws
                    # when NegativeSampling.weight is set.
                    et_indptr = graph_arrays[et][0]
                    negs = sample_negative_edges(
                        et_indptr, sorted_idx, q * amount, kneg, n_src,
                        num_dst_nodes=n_dst, dst_cdf=dst_cdf)
                    srcs = jnp.concatenate([src, negs.src])
                    dsts = jnp.concatenate([dst, negs.dst])
                elif mode == "triplet":
                    if weighted:
                        neg_dst = weighted_draw(kneg, cdf, (q * amount,))
                    else:
                        neg_dst = jax.random.randint(kneg, (q * amount,), 0,
                                                     n_dst, dtype=jnp.int32)
                    neg_dst = jnp.where(jnp.repeat(src >= 0, amount),
                                        neg_dst, PADDING_ID)
                    srcs, dsts = src, jnp.concatenate([dst, neg_dst])
                else:
                    srcs, dsts = src, dst

                if src_t == dst_t:
                    seeds_dict = {src_t: jnp.concatenate([srcs, dsts])}
                else:
                    seeds_dict = {src_t: srcs, dst_t: dsts}
                out = self._sample_impl(widths, cap, graph_arrays,
                                        seeds_dict, ksample)
                # Seed ids first-occur within the hop-0 prefix of their
                # type's node list; relabel against that slice only (the
                # no-dedup leaf block may hold duplicate seed copies).
                if src_t == dst_t:
                    src_ref = dst_ref = out.node[src_t][: sw + dw]
                else:
                    src_ref = out.node[src_t][:sw]
                    dst_ref = out.node[dst_t][:dw]
                meta = {}
                if mode == "binary":
                    meta["edge_label_index"] = jnp.stack([
                        relabel_by_reference(src_ref, srcs),
                        relabel_by_reference(dst_ref, dsts)])
                elif mode == "triplet":
                    meta["src_index"] = relabel_by_reference(src_ref, src)
                    meta["dst_pos_index"] = relabel_by_reference(
                        dst_ref, dst)
                    meta["dst_neg_index"] = relabel_by_reference(
                        dst_ref, neg_dst).reshape(q, amount)
                else:
                    meta["edge_label_index"] = jnp.stack([
                        relabel_by_reference(src_ref, src),
                        relabel_by_reference(dst_ref, dst)])
                out.metadata = meta
                return out

            self._edges_jit[k] = jax.jit(impl)
        return self._edges_jit[k]
