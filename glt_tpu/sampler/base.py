"""Sampler input/output containers and the abstract sampler interface.

Rebuild of the reference's ``graphlearn_torch/python/sampler/base.py`` —
``NodeSamplerInput`` (base.py:44), ``EdgeSamplerInput`` (:149),
``NegativeSampling`` (:84-145), ``SamplerOutput`` (:207),
``HeteroSamplerOutput`` (:243), ``SamplingConfig`` (:334), ``BaseSampler``
(:348) — re-expressed as JAX pytrees with **static shapes**: every array is
padded to a trace-time-constant size with PADDING_ID sentinels, and ragged
truths (how many nodes/edges were really sampled) travel as device scalars,
never forcing a host sync.
"""
from __future__ import annotations

import dataclasses
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..typing import EdgeType, NodeType


@dataclasses.dataclass
class NodeSamplerInput:
    """Seed nodes for node-based sampling (cf. sampler/base.py:44).

    ``node`` is a host numpy array of global node ids; ``input_type`` names
    the seed node type for heterogeneous graphs.
    """
    node: np.ndarray
    input_type: Optional[NodeType] = None

    def __len__(self) -> int:
        return int(self.node.shape[0])

    def __getitem__(self, index) -> "NodeSamplerInput":
        return NodeSamplerInput(self.node[index], self.input_type)

    def share_memory(self) -> "NodeSamplerInput":
        return self


class NegativeSampling:
    """Negative sampling spec (cf. sampler/base.py:84-145).

    mode 'binary': per positive edge, ``amount`` negative edges are drawn and
    labeled 0 (positives get 1).  mode 'triplet': per positive edge,
    ``amount`` negative *destination* nodes are drawn for each source.

    ``weight`` is an optional node-level vector biasing the negative node
    draws (need not sum to one; the reference's ``NegativeSampling.weight``,
    sampler/base.py:101-106).  Uniform when absent.  On hetero graphs the
    weight indexes the *destination* node type.
    """
    MODES = ("binary", "triplet")

    def __init__(self, mode: str = "binary", amount: float = 1,
                 weight=None):
        mode = mode.lower()
        if mode not in self.MODES:
            raise ValueError(f"mode must be one of {self.MODES}, got {mode!r}")
        self.mode = mode
        self.amount = amount
        self.weight = None if weight is None else np.asarray(weight,
                                                             np.float32)
        if self.weight is not None:
            if not np.isfinite(self.weight).all():
                raise ValueError("negative-sampling weight must be finite")
            if (self.weight < 0).any():
                raise ValueError("negative-sampling weight must be >= 0")
            if float(self.weight.sum()) <= 0.0:
                # An all-zero weight would make the CDF 0/0 = NaN and every
                # draw silently collapse to one node.
                raise ValueError("negative-sampling weight must have a "
                                 "positive sum")
        self._cdf = None

    def is_binary(self) -> bool:
        return self.mode == "binary"

    def is_triplet(self) -> bool:
        return self.mode == "triplet"

    def sample_count(self, num_pos: int) -> int:
        return int(round(num_pos * self.amount))

    def cdf(self):
        """Normalized cumulative weight (device array), or None."""
        if self.weight is None:
            return None
        if self._cdf is None:
            from ..ops.negative_sample import weight_to_cdf

            self._cdf = weight_to_cdf(self.weight)
        return self._cdf


@dataclasses.dataclass
class EdgeSamplerInput:
    """Seed edges for link-based sampling (cf. sampler/base.py:149)."""
    row: np.ndarray
    col: np.ndarray
    label: Optional[np.ndarray] = None
    input_type: Optional[EdgeType] = None
    neg_sampling: Optional[NegativeSampling] = None

    def __len__(self) -> int:
        return int(self.row.shape[0])

    def __getitem__(self, index) -> "EdgeSamplerInput":
        return EdgeSamplerInput(
            self.row[index],
            self.col[index],
            None if self.label is None else self.label[index],
            self.input_type,
            self.neg_sampling,
        )


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class SamplerOutput:
    """Sampled ego-subgraph in local (relabeled) COO form.

    Mirrors sampler/base.py:207, with the static-shape additions ``node_mask``
    / ``edge_mask`` / ``num_nodes`` / ``num_edges``:

    * ``node``: ``[max_nodes]`` global ids of batch-local nodes, in
      first-occurrence order (seeds first), -1 padded.
    * ``row`` / ``col``: ``[max_edges]`` local indices into ``node``; the
      edge direction is already transposed to PyG's dst<-src convention
      (row = neighbor, col = seed side), as in neighbor_sampler.py:159-165.
    * ``edge``: ``[max_edges]`` global edge ids, -1 padded.
    * ``batch``: ``[batch_size]`` the seed ids this batch was sampled for.
    * ``num_sampled_nodes`` / ``num_sampled_edges``: per-hop valid counts
      (device int32 vectors, lengths num_hops+1 / num_hops).
    * ``metadata``: dict of extra arrays (edge_label_index, labels, ...).

    Leaf-block layout caveat: with ``last_hop_dedup=False`` (see
    :class:`~glt_tpu.sampler.neighbor_sampler.NeighborSampler`) the
    final-hop nodes are stored in a *leaf block* at a static offset
    ``max_nodes - last_width * last_fanout``, not appended to the compact
    interior prefix.  Valid rows must then be selected with ``node_mask``
    — PyG-style ``cumsum(num_sampled_nodes)`` trimming over ``node`` would
    mis-slice.  Seed rows always stay in the compact hop-0 prefix.
    """
    node: jnp.ndarray
    row: jnp.ndarray
    col: jnp.ndarray
    edge: jnp.ndarray
    batch: Optional[jnp.ndarray] = None
    node_mask: Optional[jnp.ndarray] = None
    edge_mask: Optional[jnp.ndarray] = None
    num_sampled_nodes: Optional[jnp.ndarray] = None
    num_sampled_edges: Optional[jnp.ndarray] = None
    input_type: Optional[Any] = None
    metadata: Optional[Dict[str, Any]] = None

    def tree_flatten(self):
        children = (self.node, self.row, self.col, self.edge, self.batch,
                    self.node_mask, self.edge_mask, self.num_sampled_nodes,
                    self.num_sampled_edges, self.metadata)
        return children, (self.input_type,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (node, row, col, edge, batch, node_mask, edge_mask, nsn, nse,
         metadata) = children
        return cls(node, row, col, edge, batch, node_mask, edge_mask, nsn,
                   nse, aux[0], metadata)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class HeteroSamplerOutput:
    """Heterogeneous sampling result (cf. sampler/base.py:243).

    Dicts keyed by node type / edge type; values have the same static-shape
    semantics as :class:`SamplerOutput`.  Edge types in ``row``/``col``/
    ``edge`` are the *reversed* types (dst<-src), as the reference emits
    (neighbor_sampler.py:236-243).
    """
    node: Dict[NodeType, jnp.ndarray]
    row: Dict[EdgeType, jnp.ndarray]
    col: Dict[EdgeType, jnp.ndarray]
    edge: Dict[EdgeType, jnp.ndarray]
    batch: Optional[Dict[NodeType, jnp.ndarray]] = None
    node_mask: Optional[Dict[NodeType, jnp.ndarray]] = None
    edge_mask: Optional[Dict[EdgeType, jnp.ndarray]] = None
    num_sampled_nodes: Optional[Dict[NodeType, jnp.ndarray]] = None
    num_sampled_edges: Optional[Dict[EdgeType, jnp.ndarray]] = None
    input_type: Optional[Any] = None
    metadata: Optional[Dict[str, Any]] = None

    def tree_flatten(self):
        children = (self.node, self.row, self.col, self.edge, self.batch,
                    self.node_mask, self.edge_mask, self.num_sampled_nodes,
                    self.num_sampled_edges, self.metadata)
        return children, (self.input_type,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (node, row, col, edge, batch, node_mask, edge_mask, nsn, nse,
         metadata) = children
        return cls(node, row, col, edge, batch, node_mask, edge_mask, nsn,
                   nse, aux[0], metadata)


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Static sampling plan (cf. sampler/base.py:334 ``SamplingConfig``).

    Everything here is trace-time constant: it determines compiled shapes.
    ``max_nodes``/``max_edges`` cap the padded batch-subgraph size; ``None``
    means the exact worst-case bound batch * prod(fanouts) (mirroring
    ``_max_sampled_nodes``, neighbor_sampler.py:595-612), which is safe but
    can be lowered substantially for power-law graphs to save HBM.
    """
    num_neighbors: Any = None          # List[int] or Dict[EdgeType, List[int]]
    batch_size: int = 512
    with_edge: bool = True
    with_neg: bool = False
    with_weight: bool = False
    collect_features: bool = True
    max_nodes: Optional[int] = None
    max_edges: Optional[int] = None
    seed: int = 0


class BaseSampler(ABC):
    """Abstract sampler interface (cf. sampler/base.py:348)."""

    @abstractmethod
    def sample_from_nodes(self, inputs: NodeSamplerInput, **kwargs):
        raise NotImplementedError

    @abstractmethod
    def sample_from_edges(self, inputs: EdgeSamplerInput, **kwargs):
        raise NotImplementedError
