"""Profiling + throughput metering.

The reference has no profiler hooks (SURVEY §5 — benchmarks hand-roll
wall-clock + cuda sync).  On TPU the jax profiler is nearly free, so the
framework wires it in: ``trace()`` wraps a region for Perfetto/XPlane
capture, and :class:`ThroughputMeter` standardizes the metric definitions
the benchmarks print (sampled edges/s, feature GB/s, subgraphs/s).

These wrap the *XLA-level* profiler (device kernels, XPlane).  The
library-level instrument — host-side spans with device fencing, the
unified metrics namespace, the memcpy roofline — is
:mod:`glt_tpu.obs` (docs/observability.md); the two compose (an obs
span around a ``profile.trace`` region labels the XPlane capture).
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax profiler trace for the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Name a region inside a profiler trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


class ThroughputMeter:
    """Accumulate counts over wall-clock; report rates.

    >>> m = ThroughputMeter()
    >>> with m.measure():
    ...     run_epoch()           # call m.add(edges=..., batches=...) inside
    >>> m.rate("edges")           # edges/sec
    """

    def __init__(self):
        self._counts: Dict[str, float] = {}
        self._elapsed = 0.0
        self._t0: Optional[float] = None

    def add(self, **counts: float) -> None:
        for k, v in counts.items():
            self._counts[k] = self._counts.get(k, 0.0) + float(v)

    @contextlib.contextmanager
    def measure(self):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self._elapsed += time.perf_counter() - t0

    @property
    def elapsed(self) -> float:
        return self._elapsed

    def rate(self, key: str) -> float:
        if self._elapsed == 0:
            return 0.0
        return self._counts.get(key, 0.0) / self._elapsed

    def summary(self) -> Dict[str, float]:
        return {f"{k}_per_sec": self.rate(k) for k in self._counts}
