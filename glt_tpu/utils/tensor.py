"""Small array utilities shared across layers.

Mirrors the reference's ``graphlearn_torch/python/utils/tensor.py``
(``id2idx`` dense inverse maps, conversion helpers) in numpy/jnp form.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

ArrayLike = Union[np.ndarray, jnp.ndarray, Sequence[int]]


def id2idx(ids: ArrayLike, size: Optional[int] = None) -> np.ndarray:
    """Dense inverse map: ``out[ids[i]] = i`` (utils/tensor.py:30).

    Entries not present in ``ids`` map to 0, matching the reference's
    zero-initialised map; callers mask separately when absence matters.
    """
    ids = np.asarray(ids)
    if size is None:
        size = int(ids.max()) + 1 if ids.size else 0
    out = np.zeros(size, dtype=np.int64)
    out[ids] = np.arange(ids.shape[0], dtype=np.int64)
    return out


def ensure_numpy(x: ArrayLike) -> np.ndarray:
    if isinstance(x, np.ndarray):
        return x
    return np.asarray(x)


def ensure_device(x: ArrayLike, dtype=None) -> jnp.ndarray:
    return jnp.asarray(x, dtype=dtype)


def pad_to(x: np.ndarray, size: int, fill) -> np.ndarray:
    """Right-pad (or truncate) the leading axis of ``x`` to ``size``."""
    n = x.shape[0]
    if n == size:
        return x
    if n > size:
        return x[:size]
    pad_shape = (size - n,) + x.shape[1:]
    return np.concatenate([x, np.full(pad_shape, fill, dtype=x.dtype)], axis=0)


def next_power_of_two(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())
