"""Interpreter-exit flag for safe ``__del__`` cleanup.

Mirrors ``graphlearn_torch/python/utils/exit_status.py``: destructors that
touch shared resources (shm queues, sockets, subprocesses) check
:func:`is_exiting` to skip teardown the interpreter already tore down.
"""
from __future__ import annotations

import atexit

_EXITING = False


def _mark_exit() -> None:
    global _EXITING
    _EXITING = True


atexit.register(_mark_exit)


def is_exiting() -> bool:
    return _EXITING
