"""Checkpoint / resume for training state (legacy orbax wrapper).

The reference leaves checkpointing to user PyTorch code (SURVEY §5:
absent from the library); a complete TPU framework ships it: orbax-backed
save/restore of the :class:`~glt_tpu.models.train.TrainState` pytree plus
loader epoch/step bookkeeping, so long runs resume exactly.

.. note:: Prefer :mod:`glt_tpu.ckpt` — the engine-native, dependency-free
   checkpoint layer: atomic manifest+checksum store, whole-data-path
   capture (loader cursors, rng, feature cache, remote-client fences —
   not just the model pytree), corruption fallback, and the
   bit-identical-resume contract chaos-tested in
   tests/test_checkpoint.py.  This module survives for users already on
   orbax directories (``pip install glt-tpu[checkpoint]``).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, step: Optional[int] = None) -> str:
    """Save a pytree (e.g. TrainState) to ``path`` (or ``path/step_N``)."""
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    path = os.path.abspath(path)
    _checkpointer().save(path, jax.device_get(state), force=True)
    return path


def restore_checkpoint(path: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``template`` supplies structure/dtypes (pass an initialized state).
    """
    restored = _checkpointer().restore(os.path.abspath(path),
                                       item=jax.device_get(template))
    return restored


def latest_step(path: str) -> Optional[int]:
    """Newest ``step_N`` subdirectory under ``path``, or None."""
    if not os.path.isdir(path):
        return None
    steps = [int(d[len("step_"):]) for d in os.listdir(path)
             if d.startswith("step_") and d[len("step_"):].isdigit()]
    return max(steps) if steps else None
