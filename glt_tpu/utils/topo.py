"""Host-side topology conversions (COO <-> CSR/CSC).

Equivalent of the reference's ``graphlearn_torch/python/utils/topo.py``,
which routes through ``torch_sparse.SparseTensor``.  Here conversions are
plain numpy (graph construction is host-side prep work; the device only ever
sees the finished indptr/indices arrays).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def coo_to_csr(
    row: np.ndarray,
    col: np.ndarray,
    edge_ids: Optional[np.ndarray] = None,
    num_nodes: Optional[int] = None,
    return_perm: bool = False,
):
    """Convert a COO edge list to CSR ``(indptr, indices, edge_ids)``.

    Rows are grouped by ``row`` with a stable sort, so ties keep input order.
    ``edge_ids`` defaults to the input edge positions, matching the
    reference's implicit edge ids (utils/topo.py:29-53).  With
    ``return_perm`` the input->CSR edge permutation is also returned so
    callers can realign per-edge payloads (e.g. weights).
    """
    row = np.asarray(row, dtype=np.int64)
    col = np.asarray(col, dtype=np.int64)
    if row.shape != col.shape or row.ndim != 1:
        raise ValueError("row/col must be 1-D arrays of equal length")
    if edge_ids is None:
        edge_ids = np.arange(row.shape[0], dtype=np.int64)
    else:
        edge_ids = np.asarray(edge_ids, dtype=np.int64)
    if num_nodes is None:
        num_nodes = int(max(row.max(initial=-1), col.max(initial=-1)) + 1)

    perm = np.argsort(row, kind="stable")
    indices = col[perm]
    eids = edge_ids[perm]
    counts = np.bincount(row, minlength=num_nodes)
    indptr = np.zeros(num_nodes + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    if return_perm:
        return indptr, indices, eids, perm
    return indptr, indices, eids


def coo_to_csc(
    row: np.ndarray,
    col: np.ndarray,
    edge_ids: Optional[np.ndarray] = None,
    num_nodes: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSC is CSR of the transposed graph."""
    return coo_to_csr(col, row, edge_ids, num_nodes)


def csr_to_coo(
    indptr: np.ndarray, indices: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Expand CSR back to a COO (row, col) pair. Inverse of :func:`coo_to_csr`."""
    row = ptr2ind(indptr, indices.shape[0])
    return row, np.asarray(indices)


def ptr2ind(indptr: np.ndarray, num_edges: Optional[int] = None) -> np.ndarray:
    """Expand an indptr array to per-edge row indices (utils/topo.py:22)."""
    indptr = np.asarray(indptr)
    degrees = np.diff(indptr)
    return np.repeat(np.arange(indptr.shape[0] - 1), degrees)


def degrees_from_ptr(indptr: np.ndarray) -> np.ndarray:
    return np.diff(np.asarray(indptr))
