"""Byte-size parsing ('256MB' -> bytes). Mirrors utils/units.py:27."""
from __future__ import annotations

import re
from typing import Union

_UNITS = {
    "B": 1,
    "KB": 1024,
    "MB": 1024 ** 2,
    "GB": 1024 ** 3,
    "TB": 1024 ** 4,
}


def parse_size(size: Union[int, str]) -> int:
    """Parse a human-readable byte size like ``'1.5GB'`` into bytes."""
    if isinstance(size, (int, float)):
        return int(size)
    m = re.fullmatch(r"\s*([0-9]*\.?[0-9]+)\s*([KMGT]?B?)\s*", size.upper())
    if not m:
        raise ValueError(f"cannot parse size: {size!r}")
    value, unit = m.groups()
    unit = unit if unit.endswith("B") else unit + "B"
    if unit not in _UNITS:
        raise ValueError(f"unknown unit in size: {size!r}")
    return int(float(value) * _UNITS[unit])


def format_size(num_bytes: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(num_bytes) < 1024:
            return f"{num_bytes:.1f}{unit}" if unit != "B" else f"{num_bytes}B"
        num_bytes /= 1024
    return f"{num_bytes:.1f}TB"
