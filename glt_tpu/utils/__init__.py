from .tensor import ensure_device, ensure_numpy, id2idx, next_power_of_two, pad_to
from .topo import coo_to_csc, coo_to_csr, csr_to_coo, degrees_from_ptr, ptr2ind
from .units import format_size, parse_size

__all__ = [
    "ensure_device", "ensure_numpy", "id2idx", "next_power_of_two", "pad_to",
    "coo_to_csc", "coo_to_csr", "csr_to_coo", "degrees_from_ptr", "ptr2ind",
    "format_size", "parse_size",
]
