"""Hetero sampler-output merge/format helpers.

Mirrors ``graphlearn_torch/python/utils/common.py:65-110``
(``merge_hetero_sampler_output`` / ``format_hetero_sampler_output``): used
when per-edge-type partial results (e.g. from distributed hetero sampling)
must be combined into one :class:`HeteroSamplerOutput`.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from ..sampler.base import HeteroSamplerOutput
from ..typing import PADDING_ID


def _cat(a: Optional[jnp.ndarray], b: Optional[jnp.ndarray]):
    if a is None:
        return b
    if b is None:
        return a
    return jnp.concatenate([a, b])


def merge_hetero_sampler_output(
    a: HeteroSamplerOutput, b: HeteroSamplerOutput) -> HeteroSamplerOutput:
    """Concatenate two hetero outputs type-wise (edges keep -1 locality
    within each source output; callers re-relabel when node lists merge —
    same contract as the reference's merge)."""
    def md(da, db):
        if da is None:
            return db
        if db is None:
            return da
        out = dict(da)
        for k, v in db.items():
            out[k] = _cat(out.get(k), v)
        return out

    return HeteroSamplerOutput(
        node=md(a.node, b.node),
        row=md(a.row, b.row),
        col=md(a.col, b.col),
        edge=md(a.edge, b.edge),
        batch=md(a.batch, b.batch),
        node_mask=md(a.node_mask, b.node_mask),
        edge_mask=md(a.edge_mask, b.edge_mask),
        input_type=a.input_type or b.input_type,
        metadata=a.metadata or b.metadata,
    )


def format_hetero_sampler_output(
    out: HeteroSamplerOutput) -> HeteroSamplerOutput:
    """Drop empty edge-type entries (zero-width arrays), the reference's
    output tidy-up before building HeteroData."""
    keep = [et for et, r in out.row.items() if r.shape[0] > 0]
    pick = lambda d: None if d is None else {k: d[k] for k in keep if k in d}
    return HeteroSamplerOutput(
        node=out.node,
        row=pick(out.row),
        col=pick(out.col),
        edge=pick(out.edge),
        batch=out.batch,
        node_mask=out.node_mask,
        edge_mask=pick(out.edge_mask),
        num_sampled_nodes=out.num_sampled_nodes,
        num_sampled_edges=pick(out.num_sampled_edges),
        input_type=out.input_type,
        metadata=out.metadata,
    )
