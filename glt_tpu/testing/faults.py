"""Deterministic fault injection for the remote sampling protocol.

A :class:`FaultPlan` is a reproducible schedule of failures — *the Nth
frame write fails*, *every connection drops after K frames*, *the 3rd
frame is delayed past the RPC timeout*, *the producer thread dies after
2 batches* — injectable into both socket endpoints
(:class:`~glt_tpu.distributed.dist_server.DistServer` wraps accepted
connections, :class:`~glt_tpu.distributed.dist_client.RemoteServerConnection`
wraps its outbound socket) and into the server-side ``_Producer`` epoch
thread.  ``tests/test_fault_tolerance.py`` drives one plan per failure
class and asserts exactly-once delivery (or a bounded, structured error)
under each.

Everything is counter-based and lock-protected: the same plan against the
same workload injects at the same protocol step every run — no sleeps
racing the scheduler, no flaky "usually drops around batch 3".
"""
from __future__ import annotations

import dataclasses
import os
import signal
import socket
import struct
import threading
import time
from typing import Optional, Tuple

# Length written into a corrupted frame header: far above any configured
# frame bound, so the receiver rejects it before allocating.
_CORRUPT_LEN = 1 << 62


class ProducerKilled(BaseException):
    """Simulated crash of a server-side sampling thread.

    Deliberately a ``BaseException``: the producer's relay-to-client
    ``except Exception`` must NOT turn this into a clean error message —
    the thread has to die the way a real crash kills it (no relay, no
    cleanup), so the fetch path's liveness recheck is what surfaces it.
    """


class SimulatedPreemption(BaseException):
    """In-process stand-in for a SIGKILL at a train-step boundary.

    A ``BaseException`` for the same reason as :class:`ProducerKilled`:
    no ``except Exception`` recovery path may see it — the training
    process is "gone" from this point, and only a from-scratch rebuild +
    ``resume()`` (tests/test_checkpoint.py) continues the run.  The
    real-signal variant (``kill_at_train_step``) SIGKILLs the actual
    process; this one exists so the kill-at-every-k sweep can run in one
    pytest process.
    """


@dataclasses.dataclass
class FaultPlan:
    """One deterministic fault schedule.

    Frame indices are 1-based and count frame *writes* through faulty
    endpoints, globally across connections (``fail_nth_frame``,
    ``corrupt_length_frame``, ``delay_frames``) or per connection
    (``drop_after_frames``).  A plan is mutable shared state: hand the
    same instance to the endpoint under test and read the ``injected_*``
    counters back in assertions.
    """

    # Close the transport once this many frames were carried by a
    # connection — the K+1th write finds a dead socket (ECONNRESET-class).
    drop_after_frames: Optional[int] = None
    # Raise ``fail_exc`` instead of performing the Nth frame write.
    fail_nth_frame: Optional[int] = None
    fail_exc: type = ConnectionResetError
    # Sleep ``delay_secs`` before each listed frame write (simulates a
    # stall long enough to trip the peer's rpc_timeout).
    delay_frames: Tuple[int, ...] = ()
    delay_secs: float = 0.0
    # Overwrite the u64 length field of the Nth frame write with a huge
    # value — the hostile/corrupt-header case recv_frame must reject.
    corrupt_length_frame: Optional[int] = None
    # Kill the server-side producer epoch thread after this many buffer
    # puts (via ProducerKilled, so it dies unrelayed).
    kill_producer_after_puts: Optional[int] = None
    # SIGKILL THIS PROCESS after its Nth completed train step (1-based;
    # the ckpt.driver.TrainLoop fires on_train_step once per block,
    # after any due checkpoint save) — the chaos suite's counter-exact
    # preemption point.  SIGKILL is unhandleable by design: no atexit,
    # no flush, exactly what a preempted TPU host looks like.
    kill_at_train_step: Optional[int] = None
    # Same point, but raise SimulatedPreemption instead of dying — the
    # in-process variant for the kill-at-every-k resume sweep.
    preempt_at_train_step: Optional[int] = None
    # Fail the Nth serving micro-batch dispatch (1-based): the serving
    # front's dispatcher sees an engine exception exactly when that
    # coalesced batch would run, and must degrade to structured errors
    # for THAT batch's requests only (no poisoning of later batches).
    fail_serving_batch: Optional[int] = None
    # Kill the whole replica after this many serving micro-batches
    # (1-based): fires ``replica_kill_hook`` — a test-supplied closure,
    # typically spawning a thread that calls ``DistServer.kill()`` so
    # the replica dies abruptly mid-load (the fleet chaos scenario).
    # The hook runs at most once and must not block the dispatcher.
    kill_replica_after_serving_batches: Optional[int] = None
    replica_kill_hook: Optional[object] = None
    # Only the first N accepted/established connections are faulty;
    # later ones run clean (lets a test end the weather deterministically).
    max_faulty_conns: Optional[int] = None
    # Disk-tier chaos (glt_tpu.store): chunk reads through a faulty
    # DiskFeatureStore count 1-based, globally across threads.  The Nth
    # read raises ``disk_fail_exc`` (an OSError — the EIO class the
    # store path must surface structurally); reads listed in
    # ``delay_disk_read`` sleep ``disk_delay_secs`` first (a stalled
    # staging thread / slow device — the degraded-mode trigger).
    fail_disk_read_at: Optional[int] = None
    disk_fail_exc: type = OSError
    delay_disk_read: Tuple[int, ...] = ()
    disk_delay_secs: float = 0.0

    def __post_init__(self):
        self._lock = threading.Lock()
        self._frames_total = 0
        self._conns = 0
        self._puts = 0
        self._train_steps = 0
        self._serving_batches = 0
        self._disk_reads = 0
        self.injected_drops = 0
        self.injected_failures = 0
        self.injected_corruptions = 0
        self.injected_delays = 0
        self.injected_preemptions = 0
        self.injected_serving_failures = 0
        self.injected_replica_kills = 0
        self.injected_disk_failures = 0
        self.injected_disk_delays = 0

    # -- endpoint hooks ----------------------------------------------------
    def wrap(self, sock: socket.socket):
        """Wrap one endpoint's socket; returns it unwrapped once
        ``max_faulty_conns`` connections have been made faulty."""
        with self._lock:
            self._conns += 1
            idx = self._conns
        if self.max_faulty_conns is not None and idx > self.max_faulty_conns:
            return sock
        return FaultyConnection(sock, self, idx)

    def on_producer_put(self) -> None:
        """Called by the producer epoch thread after each buffer put."""
        if self.kill_producer_after_puts is None:
            return
        with self._lock:
            self._puts += 1
            fire = self._puts == self.kill_producer_after_puts
        if fire:
            raise ProducerKilled(
                f"fault injection: producer thread killed after "
                f"{self.kill_producer_after_puts} puts")

    def on_train_step(self) -> None:
        """Called by the training loop after each completed step/block
        (and after any checkpoint due at that step) — the counter-exact
        preemption point for ``kill_at_train_step`` /
        ``preempt_at_train_step``."""
        if (self.kill_at_train_step is None
                and self.preempt_at_train_step is None):
            return
        with self._lock:
            self._train_steps += 1
            n = self._train_steps
        if self.kill_at_train_step is not None \
                and n == self.kill_at_train_step:
            os.kill(os.getpid(), signal.SIGKILL)   # never returns
        if self.preempt_at_train_step is not None \
                and n == self.preempt_at_train_step:
            with self._lock:
                self.injected_preemptions += 1
            raise SimulatedPreemption(
                f"fault injection: process preempted after {n} "
                f"train steps")

    def on_serving_batch(self) -> None:
        """Called by the serving dispatcher before each micro-batch
        (``fail_serving_batch`` raises a plain RuntimeError — the
        engine-crash class the front must contain to the one batch;
        ``kill_replica_after_serving_batches`` fires the replica kill
        hook exactly once — whole-replica death under load)."""
        if (self.fail_serving_batch is None
                and self.kill_replica_after_serving_batches is None):
            return
        with self._lock:
            self._serving_batches += 1
            n = self._serving_batches
            fire = n == self.fail_serving_batch
            kill = (n == self.kill_replica_after_serving_batches
                    and self.replica_kill_hook is not None)
            if fire:
                self.injected_serving_failures += 1
            if kill:
                self.injected_replica_kills += 1
        if kill:
            self.replica_kill_hook()
        if fire:
            raise RuntimeError(
                f"fault injection: serving engine crashed on micro-batch "
                f"{self.fail_serving_batch}")

    def on_disk_read(self) -> None:
        """Called by :meth:`glt_tpu.store.disk.DiskFeatureStore.
        _read_chunk` before every chunk read (``fail_disk_read_at`` /
        ``delay_disk_read``).  A delay sleeps on the READING thread —
        stage-ahead workers stall exactly like a slow device; the serve
        path must degrade around them, never wait on them."""
        if self.fail_disk_read_at is None and not self.delay_disk_read:
            return
        with self._lock:
            self._disk_reads += 1
            n = self._disk_reads
            fail = n == self.fail_disk_read_at
            delay = n in self.delay_disk_read
            if fail:
                self.injected_disk_failures += 1
            if delay:
                self.injected_disk_delays += 1
        if delay:
            time.sleep(self.disk_delay_secs)
        if fail:
            raise self.disk_fail_exc(
                f"fault injection: disk read {n} failed")

    @property
    def connections(self) -> int:
        with self._lock:
            return self._conns

    # -- internal ----------------------------------------------------------
    def _frame_action(self, conn: "FaultyConnection") -> Optional[str]:
        with self._lock:
            self._frames_total += 1
            n = self._frames_total
            if (self.drop_after_frames is not None
                    and conn._frames >= self.drop_after_frames):
                self.injected_drops += 1
                return "drop"
            if self.fail_nth_frame is not None and n == self.fail_nth_frame:
                self.injected_failures += 1
                return "fail"
            if (self.corrupt_length_frame is not None
                    and n == self.corrupt_length_frame):
                self.injected_corruptions += 1
                return "corrupt"
            if n in self.delay_frames:
                self.injected_delays += 1
                return "delay"
        return None


class FaultyConnection:
    """Socket wrapper injecting a :class:`FaultPlan` at frame writes.

    Duck-types the subset of the socket API the framed protocol uses
    (``sendall``/``recv``/``settimeout``/``close``); everything else
    delegates.  Faults act on writes because both protocol directions
    have a writer — wrap the client to perturb requests, the server to
    perturb responses — and a dropped/failed write is observed by the
    peer as EOF mid-frame, the same desync real network failures cause.
    """

    def __init__(self, sock: socket.socket, plan: FaultPlan,
                 conn_index: int):
        self._sock = sock
        self._plan = plan
        self.conn_index = conn_index
        self._frames = 0

    def sendall(self, data: bytes) -> None:
        action = self._plan._frame_action(self)
        if action == "drop":
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()
            raise ConnectionResetError(
                "fault injection: connection dropped")
        if action == "fail":
            raise self._plan.fail_exc("fault injection: frame write failed")
        if action == "delay":
            time.sleep(self._plan.delay_secs)
        elif action == "corrupt":
            data = bytes(data[:4]) + struct.pack("<Q", _CORRUPT_LEN) \
                + bytes(data[12:])
        self._frames += 1
        self._sock.sendall(data)

    def recv(self, n: int) -> bytes:
        return self._sock.recv(n)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def close(self) -> None:
        self._sock.close()

    def __getattr__(self, name):
        return getattr(self._sock, name)
