"""Deterministic test harnesses shipped with the library.

``glt_tpu.testing.faults`` drives the fault-tolerance chaos suite: a
:class:`~glt_tpu.testing.faults.FaultPlan` injects socket drops, delayed
or corrupted frames, and producer-thread deaths into the remote sampling
protocol at exact, reproducible points — every recovery path in
``glt_tpu/distributed`` is testable without flaky sleeps or real network
weather.
"""
from .faults import FaultPlan, FaultyConnection, ProducerKilled

__all__ = ["FaultPlan", "FaultyConnection", "ProducerKilled"]
