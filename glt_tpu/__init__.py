"""glt_tpu — a TPU-native graph-learning data engine.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of
GraphLearn-for-PyTorch (graph storage, GPU-speed neighbor sampling, tiered
feature lookup, loaders, partitioning, and distributed sampling), designed
for TPU: static shapes, counter-based RNG, sort-based dedup instead of hash
tables, and mesh collectives instead of RPC.
"""

__version__ = "0.1.0"

from . import typing  # noqa: F401
