"""glt_tpu — a TPU-native graph-learning data engine.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of
GraphLearn-for-PyTorch (graph storage, accelerator-speed neighbor
sampling, tiered feature lookup, loaders, partitioning, and distributed
sampling), designed for TPU: static shapes, counter-based RNG, sort-based
dedup instead of hash tables, and mesh collectives instead of RPC.

Subpackages:
  data       CSRTopo, Graph, Feature, Dataset, reorder, TableDataset
  ops        sampling/dedup/negative/subgraph/stitch/gather kernels
  sampler    NeighborSampler, HeteroNeighborSampler, I/O dataclasses
  loader     Node/Neighbor/Link/SubGraph/Hetero loaders, Batch pytrees
  models     SAGE/GAT/RGAT + jitted train steps (flax)
  parallel   mesh sharding, all-to-all/ring distributed sampling, fused
             distributed train step
  partition  random/frequency/distributed partitioners + contiguous bridge
  distributed  host-side deployment: mp producers, shm channel loader,
             TCP server-client
  channel    SampleMessage serialization + native shm ring queue
  ckpt       durable data-path checkpoints + bit-identical resume
  obs        tracing (Chrome-trace spans), metrics registry, roofline
  serving    low-latency inference serving: cross-request micro-batching,
             admission control, InferenceClient
  utils      topo/tensor helpers, profiler, checkpointing
  testing    deterministic fault injection for chaos tests
"""

__version__ = "0.1.0"

from . import typing  # noqa: F401
from .typing import EdgeType, NodeType, PADDING_ID  # noqa: F401

# Subpackages import jax/flax; keep them lazy so `import glt_tpu` is cheap
# and usable for pure-host tooling (partitioning scripts etc.).
_SUBMODULES = ("data", "ops", "sampler", "loader", "models", "parallel",
               "partition", "distributed", "channel", "ckpt", "obs",
               "refresh", "serving", "store", "utils", "testing")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
