"""glt_tpu.ckpt — durable data-path checkpoints + bit-identical resume.

The preemption-safety layer (docs/distributed.md "Checkpoint & resume"):
every stateful data-path component — loader epoch cursor + shuffle rng,
``FeatureCacheState``, the remote client's seq/ack/epoch accounting,
model/optimizer pytrees — captures to plain dicts of scalars + arrays,
serialized atomically (tmp + ``os.replace``, manifest + sha256) into a
checkpoint directory, and restores **bit-exactly**: a SIGKILLed run
resumed from its last checkpoint replays the remaining batch stream and
losses identically to an uninterrupted run.

Layers (inner to outer):
  store   write_checkpoint/read_checkpoint/latest_step — atomic dirs
  state   capture/restore for pytrees, np Generators, PRNG keys
  driver  Checkpointer (cadence/retention/resume) + TrainLoop (the
          preemption-safe scanned-epoch driver, supervisor-aware)
"""
from .driver import Checkpointer, Snapshot, TrainLoop  # noqa: F401
from .state import (  # noqa: F401
    capture_key,
    capture_pytree,
    capture_rng,
    load_rng,
    restore_key,
    restore_pytree,
    restore_rng,
)
from .store import (  # noqa: F401
    CheckpointCorruptError,
    CheckpointError,
    latest_step,
    list_steps,
    read_checkpoint,
    write_checkpoint,
)

__all__ = [
    "Checkpointer",
    "Snapshot",
    "TrainLoop",
    "CheckpointError",
    "CheckpointCorruptError",
    "write_checkpoint",
    "read_checkpoint",
    "latest_step",
    "list_steps",
    "capture_pytree",
    "restore_pytree",
    "capture_rng",
    "restore_rng",
    "load_rng",
    "capture_key",
    "restore_key",
]
