"""Checkpoint driver: ``save_checkpoint``/``resume`` + a resumable loop.

Two layers:

* :class:`Checkpointer` — policy (cadence, retention) over the atomic
  store: ``save(step, components)`` snapshots any mix of objects
  implementing ``state_dict()`` and pre-captured dicts; ``resume()``
  loads the newest intact checkpoint (falling back past a corrupt one)
  and pushes state into objects implementing ``load_state_dict``.
* :class:`TrainLoop` — a preemption-safe multi-epoch driver over the
  scanned train step (:func:`~glt_tpu.models.train.
  make_scanned_node_train_step`): the loop cursor is ``(epoch, block)``,
  the epoch's shuffle rng is captured *before* the permutation draw, and
  every save lands at a block boundary — so a process SIGKILLed at any
  point resumes from its last checkpoint with the **remaining batch
  stream and losses bit-identical** to an uninterrupted run
  (tests/test_checkpoint.py kills at every block of a small epoch and
  asserts exactly that).

A :class:`~glt_tpu.distributed.supervisor.Supervisor` plugs into the
loop: peer death or a barrier timeout ends the run with an *emergency
checkpoint* + flushed traces + a structured
:class:`~glt_tpu.distributed.supervisor.SupervisedExit` — never a hang.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Mapping, NamedTuple, Optional

import numpy as np

from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs.trace import span as _span
from . import state as _state
from . import store as _store
from .store import CheckpointCorruptError, CheckpointError

_M_SAVES = _metrics.counter(
    "glt.ckpt.saves", "checkpoints published (atomic dir renames)")
_M_RESUMES = _metrics.counter(
    "glt.ckpt.resumes", "runs resumed from a checkpoint")
_M_SAVE_MS = _metrics.histogram(
    "glt.ckpt.save_ms", "wall per checkpoint save (capture + publish)")
_M_RESUME_MS = _metrics.histogram(
    "glt.ckpt.resume_ms", "wall per resume (read + verify + restore)")


class Snapshot(NamedTuple):
    """One loaded checkpoint: the step it was taken at, the raw captured
    component dicts, and the manifest extras (e.g. an exit reason)."""
    step: int
    components: Dict[str, Any]
    extras: Dict[str, Any]


def _capture(value: Any) -> Any:
    """Normalize one component for the store: ``state_dict()`` objects
    are snapshotted; captured dicts/arrays pass through."""
    sd = getattr(value, "state_dict", None)
    if callable(sd):
        return sd()
    return value


class Checkpointer:
    """Cadenced, retained checkpoints under one root directory.

    Args:
      root: checkpoint directory (created on first save).
      every_n_steps: ``due(step)`` cadence; 0 disables cadenced saves
        (explicit ``save`` calls still work — e.g. the supervisor's
        emergency save).
      keep: retained step count (older dirs pruned after each save).
    """

    def __init__(self, root: str, every_n_steps: int = 0, keep: int = 2):
        self.root = str(root)
        self.every_n_steps = int(every_n_steps)
        self.keep = max(1, int(keep))

    def due(self, step: int) -> bool:
        return self.every_n_steps > 0 and step > 0 \
            and step % self.every_n_steps == 0

    def latest_step(self) -> Optional[int]:
        return _store.latest_step(self.root)

    def save(self, step: int, components: Mapping[str, Any],
             extras: Optional[Dict[str, Any]] = None) -> str:
        """Capture + atomically publish one checkpoint; returns its dir."""
        t0 = time.perf_counter()
        with _span("ckpt.save", step=int(step)):
            captured = {name: _capture(v) for name, v in components.items()}
            path = _store.write_checkpoint(self.root, int(step), captured,
                                           extras=extras)
            _store.prune(self.root, self.keep)
        _M_SAVES.inc()
        _M_SAVE_MS.observe((time.perf_counter() - t0) * 1e3)
        _flight.record("ckpt.save", step=int(step), path=str(path))
        return path

    def resume(self, components: Mapping[str, Any] = (),
               step: Optional[int] = None) -> Optional[Snapshot]:
        """Load the newest intact checkpoint (or ``step``); None if none.

        Objects in ``components`` implementing ``load_state_dict``
        receive their captured dict; everything is also returned raw in
        the :class:`Snapshot` so functional states (pytrees, rng) can be
        restored by the caller.  A corrupt newest checkpoint (torn disk)
        is skipped with a fallback to the previous retained step.
        """
        t0 = time.perf_counter()
        with _span("ckpt.resume"):
            snap = self._read_newest_intact(step)
            if snap is None:
                return None
            for name, obj in dict(components).items():
                loader = getattr(obj, "load_state_dict", None)
                if callable(loader) and name in snap.components:
                    loader(snap.components[name])
        _M_RESUMES.inc()
        _M_RESUME_MS.observe((time.perf_counter() - t0) * 1e3)
        _flight.record("ckpt.resume", step=int(snap.step))
        return snap

    def _read_newest_intact(self, step: Optional[int]) -> Optional[Snapshot]:
        if step is not None:
            s, comps, extras = _store.read_checkpoint(self.root, step)
            return Snapshot(s, comps, extras)
        candidates = _store.list_steps(self.root)
        if not candidates:
            return None
        for s in reversed(candidates):
            try:
                got, comps, extras = _store.read_checkpoint(self.root, s)
                return Snapshot(got, comps, extras)
            except CheckpointCorruptError:
                continue    # torn on disk: fall back one retained step
        raise CheckpointError(
            f"every retained checkpoint under {self.root!r} is corrupt")


class TrainLoop:
    """Preemption-safe multi-epoch driver over a scanned node train step.

    One *step* of the loop is one scanned block (``group`` batches).  The
    global step counter, losses, and checkpoint cadence all count blocks.

    Bit-identical resume rests on three invariants:

    1. the epoch's shuffle rng is captured **before** the permutation is
       drawn, so a resumed epoch regenerates the identical seed blocks;
    2. per-block PRNG keys derive by ``fold_in(fold_in(base_key, epoch),
       block)`` — pure functions of the cursor;
    3. saves land **after** a block completes, capturing the post-block
       ``TrainState`` exactly (device -> host -> device round trips are
       bit-exact), so replaying from any checkpoint re-dispatches the
       same program on the same inputs.

    Args:
      step: a ``step(state, seeds_blk, key)`` scanned train step.
      state: initial :class:`~glt_tpu.models.train.TrainState`
        (also the restore template on resume).
      rng: the seed-shuffle ``np.random.Generator`` (captured/restored).
      checkpointer: optional :class:`Checkpointer`; ``every_n_steps``
        gives the cadence.  ``extra_components`` (name -> object with
        ``state_dict``/``load_state_dict``, e.g. a loader or remote
        client) ride along in every save.
      supervisor: optional
        :class:`~glt_tpu.distributed.supervisor.Supervisor`; checked at
        every block boundary — a dead peer triggers an emergency
        checkpoint + trace flush + structured
        :class:`~glt_tpu.distributed.supervisor.SupervisedExit`.
      fault_plan: optional :class:`~glt_tpu.testing.faults.FaultPlan`;
        its ``on_train_step`` hook fires after each block (and after any
        due save), giving the chaos suite counter-exact SIGKILL points.
    """

    def __init__(self, step: Callable, state: Any, train_idx, batch_size: int,
                 group: int, epochs: int, rng: np.random.Generator,
                 base_key, checkpointer: Optional[Checkpointer] = None,
                 extra_components: Optional[Mapping[str, Any]] = None,
                 supervisor=None, fault_plan=None):
        self.step = step
        self.state = state
        self.train_idx = np.asarray(train_idx)
        self.batch_size = int(batch_size)
        self.group = int(group)
        self.epochs = int(epochs)
        self.rng = rng
        self.base_key = base_key
        self.checkpointer = checkpointer
        self.extra = dict(extra_components or {})
        self.supervisor = supervisor
        self.fault_plan = fault_plan
        self.global_step = 0          # completed blocks across epochs
        self.epoch = 0
        self.next_block = 0
        self.losses: List[float] = []  # per-batch, from resume point on
        self.start_step = 0            # global step the losses start at

    # -- state-capture protocol ------------------------------------------
    def _loop_state(self, rng_at_epoch_start: Dict[str, Any],
                    epoch: int, next_block: int) -> Dict[str, Any]:
        return {
            "epoch": int(epoch),
            "next_block": int(next_block),
            "global_step": int(self.global_step),
            "rng_at_epoch_start": rng_at_epoch_start,
            "base_key": _state.capture_key(self.base_key),
        }

    def _components(self, rng_at_epoch_start, epoch, next_block
                    ) -> Dict[str, Any]:
        comps = {
            "train_state": _state.capture_pytree(self.state),
            "loop": self._loop_state(rng_at_epoch_start, epoch, next_block),
        }
        cache = self._live_cache()
        if cache is not None:
            # The cross-block HBM feature cache is semantics-preserving
            # (x stays bit-identical with or without it), but capturing
            # it keeps a resumed run's cache warm AND its hit-rate
            # stats/insert cursor deterministic vs the uninterrupted run.
            comps["feature_cache"] = _state.capture_pytree(cache)
        for name, obj in self.extra.items():
            comps[name] = _capture(obj)
        return comps

    def _live_cache(self):
        getter = getattr(self.step, "feature_cache", None)
        return getter() if callable(getter) else None

    def _restore(self, snap: Snapshot) -> None:
        loop = snap.components["loop"]
        self.state = _state.restore_pytree(snap.components["train_state"],
                                           like=self.state)
        self.base_key = _state.restore_key(loop["base_key"])
        # Rewind the stream to the interrupted epoch's start; the
        # permutation redraw below regenerates its exact seed blocks.
        _state.load_rng(self.rng, loop["rng_at_epoch_start"])
        self.epoch = int(loop["epoch"])
        self.next_block = int(loop["next_block"])
        self.global_step = int(loop["global_step"])
        self.start_step = self.global_step
        cache = self._live_cache()
        if cache is not None and "feature_cache" in snap.components:
            setter = getattr(self.step, "set_feature_cache", None)
            if callable(setter):
                setter(_state.restore_pytree(
                    snap.components["feature_cache"], like=cache))
        for name, obj in self.extra.items():
            loader = getattr(obj, "load_state_dict", None)
            if callable(loader) and name in snap.components:
                loader(snap.components[name])

    def resume(self) -> Optional[Snapshot]:
        """Restore from the newest intact checkpoint (None = fresh run)."""
        if self.checkpointer is None:
            return None
        snap = self.checkpointer.resume()
        if snap is not None:
            self._restore(snap)
        return snap

    # -- the loop ---------------------------------------------------------
    def run(self) -> Any:
        """Run (or continue) to completion; returns the final TrainState.

        Per-batch losses from the resume point on accumulate in
        ``self.losses`` (host floats, fetched once per epoch).
        """
        import jax

        from ..models.train import run_scanned_epoch

        while self.epoch < self.epochs:
            e = self.epoch
            rng_at_epoch_start = _state.capture_rng(self.rng)
            key_e = jax.random.fold_in(self.base_key, e)
            start_block = self.next_block

            def on_block(state_now, block_idx, _e=e,
                         _rng0=rng_at_epoch_start):
                self.state = state_now
                self.global_step += 1
                if self.checkpointer is not None \
                        and self.checkpointer.due(self.global_step):
                    self.checkpointer.save(
                        self.global_step,
                        self._components(_rng0, _e, block_idx + 1))
                if self.fault_plan is not None:
                    self.fault_plan.on_train_step()
                if self.supervisor is not None:
                    self._check_supervisor(_rng0, _e, block_idx + 1)

            self.state, losses, _accs, _ovf = run_scanned_epoch(
                self.step, self.state, self.train_idx, self.batch_size,
                self.group, self.rng, key_e, start_block=start_block,
                on_block=on_block)
            self.losses.extend(float(x) for x in np.asarray(losses))
            self.epoch += 1
            self.next_block = 0
        return self.state

    def _check_supervisor(self, rng0, epoch: int, next_block: int) -> None:
        from ..distributed.supervisor import SupervisedExit

        try:
            self.supervisor.raise_if_dead()
        except Exception as err:
            reason = getattr(err, "report", {"reason": "peer_dead",
                                             "detail": str(err)})
            path = None
            if self.checkpointer is not None:
                path = self.checkpointer.save(
                    self.global_step,
                    self._components(rng0, epoch, next_block),
                    extras={"exit_reason": reason})
            from ..obs import trace as _trace

            _trace.flush_exports(reason=reason.get("reason"))
            _flight.record("train.supervised_exit",
                           reason=reason.get("reason"),
                           step=self.global_step,
                           checkpoint_path=path)
            fpath = _flight.dump_now(
                "supervised_exit:%s" % reason.get("reason"))
            if fpath:
                reason = dict(reason)
                reason["flight_dump"] = fpath
            raise SupervisedExit(reason, step=self.global_step,
                                 checkpoint_path=path) from err
