"""Durable on-disk checkpoint store: manifest + checksums, atomic publish.

A checkpoint is a DIRECTORY ``<root>/step_<N>/`` holding two files:

  * ``arrays.npz``    — every numpy/jax array leaf of every component,
    keyed ``a0, a1, ...`` in capture order;
  * ``manifest.json`` — the JSON skeleton of the components (arrays
    replaced by ``{"__a__": i}`` markers), the step number, a format
    version, and the sha256 of ``arrays.npz``.

Atomicity is the PR-5 publish discipline (``channel/native.py``): the
directory is fully written under a private ``.tmp-*`` name and published
with ONE ``os.replace`` — a process SIGKILLed mid-save leaves only a
``.tmp-*`` directory, which readers ignore and later writers sweep.  The
``LATEST`` pointer file is republished the same way, so "the newest
complete checkpoint" is always well-defined: either the old pointer or
the new one, never a torn in-between.  Torn *disk* state (a bit flipped
after publish) is caught by the checksum at read time
(:class:`CheckpointCorruptError`) — callers fall back to the previous
step (see :meth:`~glt_tpu.ckpt.driver.Checkpointer.resume`).

Everything here is host-side stdlib + numpy; jax arrays are accepted and
fetched to host at capture (``glt_tpu.ckpt.state``), so the store can be
read by processes with no accelerator at all (a resume orchestrator, a
checkpoint inspector).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

FORMAT_VERSION = 1

_ARRAY_KEY = "__a__"
_STEP_RE = re.compile(r"^step_(\d{8})$")

#: numpy dtype kinds that round-trip through ``np.savez`` verbatim.
#: Anything else (ml_dtypes bfloat16/fp8 — jax's low-precision params)
#: is stored as its raw bytes (uint8) plus a dtype tag in the skeleton,
#: which is bit-exact by construction.
_SAFE_KINDS = frozenset("biufc")


class CheckpointError(RuntimeError):
    """Checkpoint read/write failed (missing, malformed, incompatible)."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint file failed its manifest checksum: torn or bit-rotted
    on disk.  Resume falls back to the previous retained step."""


def _step_dirname(step: int) -> str:
    return f"step_{int(step):08d}"


def _to_host(leaf: Any) -> Any:
    """jax array -> numpy (host fetch); numpy passes through."""
    if isinstance(leaf, np.ndarray):
        return leaf
    # Duck-typed jax.Array (works without importing jax here): anything
    # with __array__ lands as numpy.  ml_dtypes survive np.asarray.
    if hasattr(leaf, "__array__") and hasattr(leaf, "dtype"):
        import jax

        return np.asarray(jax.device_get(leaf))
    return leaf


def _strip_arrays(obj: Any, arrays: List[np.ndarray]) -> Any:
    """Replace array leaves with ``{"__a__": i}`` markers, appending the
    arrays (bytes-encoded when their dtype is not npz-safe)."""
    obj = _to_host(obj)
    if isinstance(obj, np.ndarray):
        idx = len(arrays)
        if obj.dtype.kind in _SAFE_KINDS or obj.dtype == np.bool_:
            arrays.append(obj)
            return {_ARRAY_KEY: idx}
        # Exotic dtype (bfloat16, float8_*): raw bytes + tag.
        arrays.append(np.frombuffer(obj.tobytes(), np.uint8))
        return {_ARRAY_KEY: idx, "dtype": str(obj.dtype),
                "shape": list(obj.shape)}
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, dict):
        if _ARRAY_KEY in obj:
            raise CheckpointError(
                f"component dicts may not use the reserved key "
                f"{_ARRAY_KEY!r}")
        return {str(k): _strip_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_strip_arrays(v, arrays) for v in obj]
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    raise CheckpointError(
        f"unserializable checkpoint leaf of type {type(obj).__name__}; "
        f"capture it first (glt_tpu.ckpt.state) or reduce it to "
        f"scalars/arrays")


def _fill_arrays(obj: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(obj, dict):
        if _ARRAY_KEY in obj:
            arr = arrays[f"a{obj[_ARRAY_KEY]}"]
            if "dtype" in obj:
                import jax.numpy as jnp

                dt = jnp.dtype(obj["dtype"])
                arr = np.frombuffer(arr.tobytes(), dt).reshape(obj["shape"])
            return arr
        return {k: _fill_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_fill_arrays(v, arrays) for v in obj]
    return obj


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    # Directory fsync makes the rename itself durable; some filesystems
    # (and test tmpfs) refuse O_RDONLY dir fds — best-effort.
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def sweep_tmp(root: str) -> int:
    """Remove leftover ``.tmp-*`` directories of crashed writers.

    Only entries older than a minute are swept, so a concurrent writer's
    in-progress tmp dir is never pulled out from under it.  Returns the
    number removed.
    """
    removed = 0
    now = time.time()
    try:
        entries = os.listdir(root)
    except OSError:
        return 0
    for name in entries:
        if not name.startswith(".tmp-"):
            continue
        p = os.path.join(root, name)
        try:
            if now - os.path.getmtime(p) > 60.0:
                shutil.rmtree(p, ignore_errors=True)
                removed += 1
        except OSError:
            pass
    return removed


def write_checkpoint(root: str, step: int,
                     components: Dict[str, Any],
                     extras: Optional[Dict[str, Any]] = None) -> str:
    """Write one checkpoint atomically; returns the published directory.

    ``components``: name -> captured state (nested dicts/lists of JSON
    scalars and numpy/jax arrays — see :mod:`glt_tpu.ckpt.state`).
    ``extras``: small JSON-only metadata recorded in the manifest (e.g.
    the supervisor's structured exit reason).
    """
    os.makedirs(root, exist_ok=True)
    sweep_tmp(root)
    arrays: List[np.ndarray] = []
    skeleton = {name: _strip_arrays(comp, arrays)
                for name, comp in components.items()}
    final = os.path.join(root, _step_dirname(step))
    tmp = os.path.join(root, f".tmp-{_step_dirname(step)}-{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    try:
        arrays_path = os.path.join(tmp, "arrays.npz")
        np.savez(arrays_path, **{f"a{i}": a for i, a in enumerate(arrays)})
        manifest = {
            "format": FORMAT_VERSION,
            "step": int(step),
            "components": skeleton,
            "files": {"arrays.npz": _sha256(arrays_path)},
            "written_unix": time.time(),
        }
        if extras:
            manifest["extras"] = extras
        man_path = os.path.join(tmp, "manifest.json")
        with open(man_path, "w") as fh:
            json.dump(manifest, fh)
            fh.flush()
            os.fsync(fh.fileno())
        # Publish: one rename.  A pre-existing dir for this step (a rerun
        # over the same root) is moved aside first, then dropped — at no
        # point is the step name bound to a partially-written directory.
        aside = None
        if os.path.exists(final):
            aside = os.path.join(root, f".tmp-old-{_step_dirname(step)}"
                                       f"-{os.getpid()}")
            shutil.rmtree(aside, ignore_errors=True)
            os.replace(final, aside)
        os.replace(tmp, final)
        _fsync_dir(root)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    # LATEST pointer: same tmp + replace discipline (a one-line file).
    ptr_tmp = os.path.join(root, f".tmp-LATEST-{os.getpid()}")
    with open(ptr_tmp, "w") as fh:
        fh.write(_step_dirname(step) + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(ptr_tmp, os.path.join(root, "LATEST"))
    _fsync_dir(root)
    return final


def list_steps(root: str) -> List[int]:
    """Completed (published) checkpoint steps under ``root``, ascending."""
    try:
        entries = os.listdir(root)
    except OSError:
        return []
    steps = []
    for name in entries:
        m = _STEP_RE.match(name)
        if m and os.path.isfile(os.path.join(root, name, "manifest.json")):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    """Newest complete step — the LATEST pointer when it names a live
    directory, else the newest published step dir (pointer write lost)."""
    try:
        with open(os.path.join(root, "LATEST")) as fh:
            name = fh.read().strip()
        m = _STEP_RE.match(name)
        if m and os.path.isfile(os.path.join(root, name, "manifest.json")):
            return int(m.group(1))
    except OSError:
        pass
    steps = list_steps(root)
    return steps[-1] if steps else None


def read_checkpoint(root: str, step: Optional[int] = None
                    ) -> Tuple[int, Dict[str, Any], Dict[str, Any]]:
    """Load one checkpoint; returns ``(step, components, extras)``.

    ``step=None`` reads the latest.  Checksums are verified before any
    component is materialised — a torn/bit-rotted ``arrays.npz`` raises
    :class:`CheckpointCorruptError` (callers fall back a step).
    """
    if step is None:
        step = latest_step(root)
        if step is None:
            raise CheckpointError(f"no checkpoint under {root!r}")
    d = os.path.join(root, _step_dirname(step))
    try:
        with open(os.path.join(d, "manifest.json")) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable manifest in {d!r}: {e}") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint format {manifest.get('format')!r} in {d!r} "
            f"(this build reads format {FORMAT_VERSION})")
    arrays_path = os.path.join(d, "arrays.npz")
    want = manifest.get("files", {}).get("arrays.npz")
    if want is not None:
        got = _sha256(arrays_path)
        if got != want:
            raise CheckpointCorruptError(
                f"{arrays_path} checksum mismatch (manifest {want[:12]}.., "
                f"file {got[:12]}..): torn or corrupted checkpoint")
    with np.load(arrays_path) as npz:
        arrays = {k: npz[k] for k in npz.files}
    components = _fill_arrays(manifest["components"], arrays)
    return int(manifest["step"]), components, manifest.get("extras", {})


def prune(root: str, keep: int) -> List[int]:
    """Drop all but the newest ``keep`` published steps; returns removed.

    Never touches the step named by ``LATEST`` regardless of ``keep``.
    """
    steps = list_steps(root)
    latest = latest_step(root)
    doomed = [s for s in steps[:-keep] if keep > 0 and s != latest] \
        if len(steps) > keep else []
    for s in doomed:
        shutil.rmtree(os.path.join(root, _step_dirname(s)),
                      ignore_errors=True)
    return doomed
