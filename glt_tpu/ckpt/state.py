"""Functional state capture: pytrees, numpy Generators, PRNG keys.

The codebase keeps its data-path state functional (``FeatureCacheState``
pytrees threaded through scans, ``TrainState`` NamedTuples, caller-owned
``np.random.Generator`` objects), so the capture protocol has two halves:

* **Stateful hosts objects** (loaders, the remote client) implement
  ``state_dict() -> dict`` / ``load_state_dict(d)`` directly — the
  torch-familiar spelling, returning plain dicts of scalars + arrays.
* **Functional states** go through the free functions here:
  :func:`capture_pytree` / :func:`restore_pytree` for any jax pytree
  (TrainState, optimizer state, FeatureCacheState) and
  :func:`capture_rng` / :func:`restore_rng` for numpy Generators.

Restores are **bit-exact**: arrays round-trip through host numpy with
their dtype preserved (exotic dtypes ride raw bytes — see
``glt_tpu.ckpt.store``), and a Generator restored from its captured
bit-generator state continues the identical stream.  Restore validates
leaf count, shape, and dtype against a caller-supplied template of the
same structure, so a checkpoint from a different model/config fails
loudly instead of training on garbage.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .store import CheckpointError

_PYTREE_KIND = "pytree"
_RNG_KIND = "np_generator"


def capture_pytree(tree: Any) -> Dict[str, Any]:
    """Snapshot any jax pytree as a serializable dict (host arrays).

    This is a SYNC POINT: every device leaf is fetched to host.  Call it
    at step boundaries (the epoch drivers' ``on_block``/``on_step``
    hooks), never inside a jitted function.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    host = [np.asarray(jax.device_get(leaf)) if _is_arrayish(leaf)
            else leaf for leaf in leaves]
    return {
        "kind": _PYTREE_KIND,
        "leaves": [_leaf_entry(leaf) for leaf in host],
        # Debugging aid only — restore validates leaf-by-leaf against the
        # template (treedef reprs are not stable across jax versions).
        "structure": str(treedef),
    }


def _is_arrayish(leaf: Any) -> bool:
    return hasattr(leaf, "dtype") and hasattr(leaf, "shape")


def _leaf_entry(leaf: Any) -> Any:
    if isinstance(leaf, np.ndarray):
        return {"v": leaf}
    if isinstance(leaf, (bool, int, float, str)) or leaf is None:
        return {"v": leaf}
    if isinstance(leaf, np.generic):
        return {"v": leaf.item()}
    raise CheckpointError(
        f"pytree leaf of type {type(leaf).__name__} is not capturable")


def restore_pytree(snapshot: Dict[str, Any], like: Any) -> Any:
    """Rebuild a pytree captured by :func:`capture_pytree`.

    ``like`` supplies the structure and per-leaf placement: jax-array
    leaves come back as device arrays (``jnp.asarray``), numpy leaves as
    numpy, Python scalars as their original type.  Leaf count / shape /
    dtype mismatches raise :class:`~glt_tpu.ckpt.store.CheckpointError`
    naming the offending leaf path.
    """
    import jax
    import jax.numpy as jnp

    if snapshot.get("kind") != _PYTREE_KIND:
        raise CheckpointError(
            f"snapshot kind {snapshot.get('kind')!r} is not a pytree")
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(like)
    saved = snapshot["leaves"]
    if len(saved) != len(paths_leaves):
        raise CheckpointError(
            f"checkpoint has {len(saved)} pytree leaves, template has "
            f"{len(paths_leaves)} — different model/optimizer config?")
    out = []
    for entry, (path, tmpl) in zip(saved, paths_leaves):
        val = entry["v"]
        if _is_arrayish(tmpl):
            if not isinstance(val, np.ndarray):
                val = np.asarray(val, dtype=np.asarray(tmpl).dtype)
            if tuple(val.shape) != tuple(tmpl.shape) \
                    or np.dtype(val.dtype) != np.dtype(tmpl.dtype):
                raise CheckpointError(
                    f"leaf {jax.tree_util.keystr(path)}: checkpoint "
                    f"{val.dtype}{list(val.shape)} vs template "
                    f"{np.dtype(tmpl.dtype)}{list(tmpl.shape)}")
            out.append(jnp.asarray(val) if not isinstance(tmpl, np.ndarray)
                       else val)
        elif isinstance(tmpl, (bool, int, float, str)) or tmpl is None:
            out.append(val if tmpl is None else type(tmpl)(val))
        else:
            raise CheckpointError(
                f"template leaf {jax.tree_util.keystr(path)} of type "
                f"{type(tmpl).__name__} is not restorable")
    return jax.tree_util.tree_unflatten(treedef, out)


def capture_rng(rng: np.random.Generator) -> Dict[str, Any]:
    """Snapshot a numpy Generator (the loaders' / ``split_seeds``' rng).

    The bit-generator state dict is JSON-able (Python ints carry the
    128-bit PCG64 state exactly); restoring it continues the identical
    stream — the property the bit-identical-resume contract rests on.
    """
    state = rng.bit_generator.state
    return {"kind": _RNG_KIND, "state": _jsonify(state)}


def restore_rng(snapshot: Dict[str, Any]) -> np.random.Generator:
    """A fresh Generator continuing the captured stream."""
    if snapshot.get("kind") != _RNG_KIND:
        raise CheckpointError(
            f"snapshot kind {snapshot.get('kind')!r} is not a Generator")
    state = snapshot["state"]
    name = state.get("bit_generator", "PCG64")
    cls = getattr(np.random, name, None)
    if cls is None:
        raise CheckpointError(f"unknown bit generator {name!r}")
    bg = cls()
    bg.state = state
    return np.random.Generator(bg)


def load_rng(rng: np.random.Generator, snapshot: Dict[str, Any]) -> None:
    """Restore a captured stream INTO an existing Generator (in place) —
    for objects that hold their rng privately (loaders)."""
    if snapshot.get("kind") != _RNG_KIND:
        raise CheckpointError(
            f"snapshot kind {snapshot.get('kind')!r} is not a Generator")
    rng.bit_generator.state = snapshot["state"]


def capture_key(key: Any) -> np.ndarray:
    """jax PRNG key -> host array (fold_in/split reproduce exactly)."""
    import jax

    return np.asarray(jax.device_get(key))


def restore_key(arr: Any) -> Any:
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(arr))


def _jsonify(obj: Any) -> Any:
    """bit_generator.state contains numpy ints/arrays; make it JSON-safe
    while keeping exact values (Python ints are arbitrary precision)."""
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return obj          # store layer serializes arrays losslessly
    if isinstance(obj, np.generic):
        return obj.item()
    return obj
