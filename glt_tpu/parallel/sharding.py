"""Graph + feature sharding across a device mesh.

TPU-native replacement for the reference's partitioned distributed dataset
(distributed/dist_dataset.py, dist_graph.py): there, each machine owns a
graph partition plus a dense partition book and routes per-id requests over
RPC.  Here each **mesh device** owns a contiguous node range; the "partition
book" degenerates to arithmetic (``owner = id // nodes_per_shard``), and the
padded per-shard CSR blocks are plain jax Arrays sharded over the mesh axis,
so routing happens with ``lax.all_to_all`` inside one jitted program (see
:mod:`glt_tpu.parallel.dist_sampler`).

General (non-contiguous) partitions from :mod:`glt_tpu.partition` are
supported by relabeling ids so each partition is contiguous — the partitioner
emits that relabeling; sharding here stays arithmetic.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..data.topology import CSRTopo


class ShardedGraph(NamedTuple):
    """Padded per-shard CSR blocks; leading axis = shard.

    ``indptr``: ``[S, max_nodes_per_shard + 1]`` local row pointers
    (0-based within shard); ``indices``: ``[S, max_edges_per_shard]`` global
    neighbor ids (-1 padded); ``edge_ids``: same shape, global edge ids.
    """
    indptr: jnp.ndarray
    indices: jnp.ndarray
    edge_ids: jnp.ndarray
    nodes_per_shard: int
    num_nodes: int
    num_shards: int

    def owner_of(self, ids: jnp.ndarray) -> jnp.ndarray:
        """Partition-book lookup, arithmetic form (cf. dist_graph.py:88)."""
        return jnp.where(ids >= 0, ids // self.nodes_per_shard, -1)


class ShardedFeature(NamedTuple):
    """Per-shard feature blocks: ``[S, nodes_per_shard, d]``."""
    rows: jnp.ndarray
    nodes_per_shard: int
    num_shards: int


def shard_bounds(topo: CSRTopo, num_shards: int):
    """Per-shard node/edge ranges of the contiguous split.

    Returns ``(c, bounds, max_e)``: nodes per shard, a list of
    ``(lo, hi, e0, e1)`` per shard, and the max per-shard edge count (the
    rectangular padding width).  Cheap — touches only ``indptr``.
    """
    n = topo.num_nodes
    c = -(-n // num_shards)  # ceil
    indptr = topo.indptr
    max_e = 0
    bounds = []
    for s in range(num_shards):
        lo, hi = min(s * c, n), min((s + 1) * c, n)
        e0, e1 = int(indptr[lo]), int(indptr[hi])
        bounds.append((lo, hi, e0, e1))
        max_e = max(max_e, e1 - e0)
    return c, bounds, max_e


def shard_graph_blocks(topo: CSRTopo, num_shards: int,
                       shard_range: Optional[range] = None,
                       pad_edges: Optional[int] = None):
    """Host-side numpy CSR blocks for ``shard_range`` (default: all).

    Returns ``(ip, ix, ei, c)`` with leading axis ``len(shard_range)``.
    ``pad_edges`` overrides the edge padding width (multi-host callers pass
    the globally-agreed max so every process's blocks stack congruently).
    """
    n = topo.num_nodes
    c, bounds, max_e = shard_bounds(topo, num_shards)
    if pad_edges is not None:
        if pad_edges < max_e:
            raise ValueError(f"pad_edges {pad_edges} < local max {max_e}")
        max_e = pad_edges
    if shard_range is None:
        shard_range = range(num_shards)
    indptr = topo.indptr.astype(np.int64)
    indices = topo.indices.astype(np.int32)
    edge_ids = topo.edge_ids.astype(np.int32)

    k = len(shard_range)
    ip = np.zeros((k, c + 1), np.int32)
    ix = np.full((k, max_e), -1, np.int32)
    ei = np.full((k, max_e), -1, np.int32)
    for j, s in enumerate(shard_range):
        lo, hi, e0, e1 = bounds[s]
        local = (indptr[lo: hi + 1] - indptr[lo]).astype(np.int32)
        ip[j, : hi - lo + 1] = local
        ip[j, hi - lo + 1:] = local[-1] if local.size else 0
        ix[j, : e1 - e0] = indices[e0:e1]
        ei[j, : e1 - e0] = edge_ids[e0:e1]
    return ip, ix, ei, c


def shard_graph(topo: CSRTopo, num_shards: int) -> ShardedGraph:
    """Split a CSR topology into contiguous per-shard blocks (host-side).

    Nodes ``[s * c, (s+1) * c)`` go to shard ``s`` where
    ``c = ceil(N / num_shards)``; edge blocks are padded to the max shard
    edge count so the result stacks into rectangular arrays that
    ``jax.device_put`` can shard along axis 0.
    """
    ip, ix, ei, c = shard_graph_blocks(topo, num_shards)
    return ShardedGraph(
        indptr=jnp.asarray(ip), indices=jnp.asarray(ix),
        edge_ids=jnp.asarray(ei), nodes_per_shard=c,
        num_nodes=topo.num_nodes, num_shards=num_shards)


def shard_feature(feature: np.ndarray, num_shards: int,
                  dtype=None) -> ShardedFeature:
    """Split ``[N, d]`` features into ``[S, c, d]`` blocks (zero padded)."""
    feature = np.asarray(feature)
    n, d = feature.shape
    c = -(-n // num_shards)
    rows = np.zeros((num_shards, c, d), feature.dtype)
    for s in range(num_shards):
        lo, hi = min(s * c, n), min((s + 1) * c, n)
        rows[s, : hi - lo] = feature[lo:hi]
    arr = jnp.asarray(rows) if dtype is None else jnp.asarray(rows, dtype)
    return ShardedFeature(rows=arr, nodes_per_shard=c, num_shards=num_shards)


def put_sharded(sharded, mesh: jax.sharding.Mesh, axis: str):
    """Place the leading (shard) axis of every array field on ``axis``."""
    spec = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(axis))

    def place(x):
        if isinstance(x, jnp.ndarray) and x.ndim >= 1:
            return jax.device_put(x, spec)
        return x

    return type(sharded)(*[place(v) if isinstance(v, jnp.ndarray) else v
                           for v in sharded])
