"""Distributed neighbor sampling: all-to-all id exchange inside shard_map.

TPU-native replacement for the reference's distributed sampling engine
(distributed/dist_neighbor_sampler.py:542-598): there, each hop partitions
seed ids by the partition book, samples locally, RPC-fans-out remote ids to
owner workers, awaits, and stitches results back into seed order with a CUDA
kernel (stitch_sample_results.cu).  Here the same dataflow is **three
collectives inside one jitted shard_map program**:

  1. bucket seeds by owner shard (sort-based, static capacity);
  2. ``lax.all_to_all`` the request buckets;
  3. every shard samples its requests from its local CSR block;
  4. ``lax.all_to_all`` the neighbor/edge blocks back;
  5. unscatter into original seed order (the stitch, now a pure gather).

No RPC, no event loop, no serialization: the exchange rides ICI, and the
multi-hop loop + dedup runs per shard exactly like the single-device
sampler.  Each device doubles as a trainer (the reference's
worker-mode collocated layout, dist_loader.py:142-186).
"""
from __future__ import annotations

import os
import time
from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import metrics as _metrics
from ..obs.trace import span as _span
from ..ops.neighbor_sample import _row_offsets_and_degrees, sample_neighbors
from ..ops.unique import (
    dense_induce,
    dense_induce_final,
    dense_induce_init,
    dense_map_fits,
    relabel_by_reference,
    unique_first_occurrence,
)
from ..sampler.base import NegativeSampling, SamplerOutput
from ..sampler.neighbor_sampler import hop_widths, max_sampled_nodes
from ..typing import PADDING_ID

# Host-boundary instrumentation; the shard_map program itself is traced
# code and stays span-free (gltlint GLT010).
_M_DIST_BATCHES = _metrics.counter(
    "glt.dist.sample_batches", "distributed sample programs dispatched")
_M_DIST_SAMPLE_MS = _metrics.histogram(
    "glt.dist.sample_dispatch_ms",
    "dist sampler shard_map dispatch wall per batch")
_M_ROUTE_AUTOTUNE = _metrics.counter(
    "glt.dist.route_autotune_runs", "routing A/B warmups",
)


def bounded_remote_cap(width: int, load_factor: float,
                       num_shards: int) -> int:
    """Per-owner request-bucket capacity for the bounded exchange:
    ``ceil(load_factor * width / num_shards)``, clamped to ``[1, width]``."""
    return min(width,
               max(1, -(-int(round(load_factor * width)) // num_shards)))


def resolve_mesh_axes(mesh: Mesh, axis_name=None):
    """Resolve a sampler/step ``axis_name`` argument against its mesh:
    ``None`` derives the mesh's own axes (the axis name for a 1-D mesh,
    the full name tuple for a 2-D ``(host, chip)`` mesh); an explicit
    value passes through untouched (backward compat)."""
    if axis_name is not None:
        return axis_name
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def mesh_axis_sizes(mesh: Mesh, axis_name):
    """``(num_hosts, chips_per_host)`` for a 2-D axis tuple, else None
    (1-D meshes have no topology choice to parameterize)."""
    if isinstance(axis_name, str):
        return None
    return tuple(int(mesh.shape[a]) for a in axis_name)


class Routing(NamedTuple):
    """Owner-bucketed routing plan for one frontier (see
    :func:`build_routing`): everything an exchange needs to scatter ids
    into per-owner request buckets and unscatter the responses.  Build it
    ONCE per hop frontier and thread it through every exchange over that
    frontier (neighbors, features, labels) — the plan depends only on
    ``(ids, nodes_per_shard, num_shards, cap)``, not on the payload.
    """
    buckets: jnp.ndarray   # [S * cap] ids grouped by owner, -1 padded
    slot: jnp.ndarray      # [B] bucket slot each input id landed in
    valid: jnp.ndarray     # [B] input validity (overflowed ids excluded)
    dropped: jnp.ndarray   # [] int32: ids beyond an owner's cap


# Backward-compat alias (pre-routing-layer name).
_Routing = Routing

# Decision table for route='auto': (b, num_shards, cap) -> 'onepass' |
# 'sort', filled by autotune_routing at warmup.  Without an entry the
# heuristic prefers the one-pass cumulative-mask path up to
# _ONEPASS_MAX_SHARDS (its [B, S] rank matrix is O(B*S) elementwise work
# vs the sort's O(B log B) — a clear win at small shard counts, a wash
# and then a loss as S grows past the sort's log factor).
_ROUTE_AUTO: dict = {}
_ONEPASS_MAX_SHARDS = 16


def _route_choice(b: int, num_shards: int, cap: int, route: str) -> str:
    """Resolve the bucketing implementation at trace time.

    Priority: ``GLT_ROUTE_FORCE`` env var > explicit ``route`` argument >
    autotuned decision table > shard-count heuristic — the same seam
    shape as ``gather_rows(force=)``/``GLT_GATHER_FORCE``.
    """
    env = os.environ.get("GLT_ROUTE_FORCE")
    if env in ("sort", "onepass"):
        return env
    if route in ("sort", "onepass"):
        return route
    hit = _ROUTE_AUTO.get((int(b), int(num_shards), int(cap)))
    if hit is not None:
        return hit
    return "onepass" if num_shards <= _ONEPASS_MAX_SHARDS else "sort"


def _use_fused(fused: Optional[bool]) -> bool:
    """Resolve the collective-fusion seam at trace time (default: fused).

    ``GLT_COLLECTIVE_FORCE`` ('fused'|'split') overrides the argument —
    the A/B escape hatch for the packed-payload collectives.
    """
    env = os.environ.get("GLT_COLLECTIVE_FORCE")
    if env in ("fused", "split"):
        return env == "fused"
    return True if fused is None else bool(fused)


def _bucket_by_owner_sort(ids: jnp.ndarray, owner: jnp.ndarray,
                          num_shards: int, cap: int) -> Routing:
    """Sort-based bucketing (the fallback path; see `_bucket_by_owner`).

    Stable argsort by owner, then segment starts straight off the sorted
    owner keys — O(S log B) searchsorted instead of a dense [B, S+1]
    one-hot count, which at hop-2 frontier widths (50k+) dominated the
    exchange prologue.
    """
    b = ids.shape[0]
    valid = ids >= 0
    owner_key = jnp.where(valid, owner, num_shards)  # padding sorts last
    order = jnp.argsort(owner_key, stable=True)
    sorted_ids = ids[order]
    sorted_owner = owner_key[order]

    starts = jnp.searchsorted(
        sorted_owner, jnp.arange(num_shards + 1, dtype=sorted_owner.dtype)
    ).astype(jnp.int32)
    rank = jnp.arange(b, dtype=jnp.int32) - starts[sorted_owner]
    fits = rank < cap
    sorted_slot = jnp.where((sorted_owner < num_shards) & fits,
                            sorted_owner * cap + jnp.minimum(rank, cap - 1),
                            num_shards * cap)

    buckets = jnp.full((num_shards * cap + 1,), PADDING_ID, jnp.int32)
    buckets = buckets.at[sorted_slot].set(sorted_ids)[:-1]

    slot = jnp.zeros((b,), jnp.int32).at[order].set(sorted_slot)
    slot_valid = jnp.zeros((b,), bool).at[order].set(
        fits & (sorted_owner < num_shards))
    dropped = jnp.sum(((sorted_owner < num_shards) & ~fits)
                      .astype(jnp.int32))
    return Routing(buckets=buckets, slot=jnp.minimum(slot, num_shards * cap - 1),
                   valid=valid & slot_valid, dropped=dropped)


def _bucket_by_owner_onepass(ids: jnp.ndarray, owner: jnp.ndarray,
                             num_shards: int, cap: int) -> Routing:
    """Sort-free bucketing: one-pass per-owner rank via cumulative masks.

    The stable sort's only job is the rank-within-owner; a [B, S] one-hot
    cumsum computes the identical rank directly (input order within each
    owner is preserved by construction), so every field is bit-identical
    to :func:`_bucket_by_owner_sort` — O(B*S) elementwise work, no sort.
    """
    b = ids.shape[0]
    valid = ids >= 0
    owner_key = jnp.where(valid, owner, num_shards).astype(jnp.int32)
    onehot = owner_key[:, None] == jnp.arange(num_shards,
                                              dtype=jnp.int32)[None, :]
    rank_m = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1
    rank = jnp.sum(jnp.where(onehot, rank_m, 0), axis=1)
    in_range = owner_key < num_shards
    fits = rank < cap
    slot = jnp.where(in_range & fits,
                     owner_key * cap + jnp.minimum(rank, cap - 1),
                     num_shards * cap)
    buckets = jnp.full((num_shards * cap + 1,), PADDING_ID, jnp.int32)
    buckets = buckets.at[slot].set(ids)[:-1]
    dropped = jnp.sum((in_range & ~fits).astype(jnp.int32))
    return Routing(buckets=buckets,
                   slot=jnp.minimum(slot, num_shards * cap - 1),
                   valid=valid & in_range & fits, dropped=dropped)


def _bucket_by_owner(ids: jnp.ndarray, owner: jnp.ndarray, num_shards: int,
                     cap: int, route: str = "auto") -> Routing:
    """Group ids into per-owner rows of a static ``[S, cap]`` buffer.

    The scatter order is stable (input order within each owner), so every
    valid id gets slot ``owner * cap + rank-within-owner``.  With ``cap =
    len(ids)`` overflow is impossible (the reference-exact default);
    smaller capacity-bounded buffers (see :func:`exchange_one_hop`'s
    ``remote_cap``) route ids past an owner's cap to the trash slot, mark
    them invalid, and count them in ``dropped`` so callers can observe
    the loss.

    ``route`` selects the rank computation ('onepass' cumulative masks vs
    'sort' stable argsort — bit-identical outputs; see
    :func:`_route_choice` for the 'auto' resolution order).
    """
    if _route_choice(ids.shape[0], num_shards, cap, route) == "onepass":
        return _bucket_by_owner_onepass(ids, owner, num_shards, cap)
    return _bucket_by_owner_sort(ids, owner, num_shards, cap)


def build_routing(ids: jnp.ndarray, nodes_per_shard: int, num_shards: int,
                  cap: Optional[int] = None,
                  route: str = "auto") -> Routing:
    """Build the owner-bucketed routing plan for a frontier of global ids.

    Call inside ``shard_map``, ONCE per hop frontier, and thread the
    result through every exchange over that frontier
    (:func:`exchange_one_hop`,
    :func:`~glt_tpu.parallel.dist_feature.exchange_gather`,
    :func:`~glt_tpu.parallel.dist_feature.exchange_gather_hot`,
    :func:`~glt_tpu.parallel.dist_feature.route_cold_requests`) — the
    plan depends only on the ids and the contiguous partition geometry,
    so rebuilding it per exchange (as the pre-routing-layer train step
    did, 3x per batch) is pure waste.

    Args:
      ids: ``[B]`` global node ids, -1 padded.
      cap: per-owner bucket capacity; ``None`` -> ``B`` (overflow-free).
      route: 'auto' | 'onepass' | 'sort' (see :func:`_route_choice`).
    """
    owner = jnp.where(ids >= 0, ids // nodes_per_shard, -1)
    return _bucket_by_owner(ids, owner, num_shards,
                            ids.shape[0] if cap is None else int(cap),
                            route=route)


def autotune_routing(b: int, num_shards: int, cap: Optional[int] = None,
                     iters: int = 3, seed: int = 0,
                     mesh_shape: Optional[tuple] = None) -> str:
    """Measure sort vs one-pass bucketing for this (B, S, cap) and
    memoize the winner for ``route='auto'``.

    Call EAGERLY at warmup (sampler construction) — never from inside a
    trace.  Timing is fetch-synced (see bench.py: a host scalar fetch is
    the only sync that provably waits under the axon tunnel).  Off-TPU
    backends pin the shard-count heuristic without timing.

    With ``mesh_shape=(H, C)`` (a 2-D mesh) the sweep also covers the
    flat-vs-hier topology choice (memoized in the ``_TOPO_AUTO`` table
    consumed by :func:`_topology_choice`): hier's extra cost is the
    per-dest-host dedup (the legs are bandwidth, not compute), so on TPU
    we time the vmapped ``unique_first_occurrence`` over the ``[H,
    C*cap]`` slab against the flat bucketing it augments and keep hier
    unless the dedup alone dwarfs the plan build; off-TPU the shape
    heuristic (hier iff both axes > 1) is pinned without timing.  1-D
    meshes never consult the table — :func:`_topology_choice` pins
    'flat' before reaching it.
    """
    cap = b if cap is None else int(cap)
    if mesh_shape is not None:
        _autotune_topology(b, mesh_shape, cap, iters=iters, seed=seed)
    key = (int(b), int(num_shards), cap)
    if key in _ROUTE_AUTO:
        return _ROUTE_AUTO[key]
    choice = "onepass" if num_shards <= _ONEPASS_MAX_SHARDS else "sort"
    if jax.default_backend() == "tpu":
        try:
            rng = np.random.default_rng(seed)
            ids = jnp.asarray(rng.integers(
                0, num_shards * max(b, 1), size=b).astype(np.int32))
            owner = jnp.asarray(rng.integers(
                0, num_shards, size=b).astype(np.int32))

            def timed(fn):
                f = jax.jit(partial(fn, num_shards=num_shards, cap=cap))
                int(f(ids, owner).dropped)   # compile + warm
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = f(ids, owner)
                int(out.dropped)             # fetch = true sync
                return time.perf_counter() - t0

            t_sort = timed(_bucket_by_owner_sort)
            t_one = timed(_bucket_by_owner_onepass)
            choice = "onepass" if t_one < t_sort else "sort"
        except Exception:  # pragma: no cover - backend quirk: keep fallback
            choice = "sort"
    _ROUTE_AUTO[key] = choice
    _M_ROUTE_AUTOTUNE.inc()
    _metrics.gauge("glt.dist.route_onepass_selected",
                   "1 if the last routing autotune picked one-pass",
                   ).set(1.0 if choice == "onepass" else 0.0)
    return choice


def _autotune_topology(b: int, mesh_shape, cap: int,
                       iters: int = 3, seed: int = 0) -> str:
    """Fill the flat-vs-hier decision table for one (H, C) grid."""
    h, c = int(mesh_shape[0]), int(mesh_shape[1])
    tkey = (h, c)
    if tkey in _TOPO_AUTO:
        return _TOPO_AUTO[tkey]
    choice = "hier" if (h > 1 and c > 1) else "flat"
    if choice == "hier" and jax.default_backend() == "tpu":
        try:
            rng = np.random.default_rng(seed)
            num_shards = h * c
            ids = jnp.asarray(rng.integers(
                0, num_shards * max(b, 1), size=b).astype(np.int32))
            owner = jnp.asarray(rng.integers(
                0, num_shards, size=b).astype(np.int32))
            slab = jnp.asarray(rng.integers(
                -1, max(b, 2), size=(h, c * cap)).astype(np.int32))

            def timed(f, *args):
                g = jax.jit(f)
                jax.block_until_ready(g(*args))    # compile + warm
                t0 = time.perf_counter()
                out = None
                for _ in range(iters):
                    out = g(*args)
                jax.block_until_ready(out)         # fetch = true sync
                return time.perf_counter() - t0

            t_flat = timed(partial(_bucket_by_owner_sort,
                                   num_shards=num_shards, cap=cap),
                           ids, owner)
            t_dedup = timed(jax.vmap(unique_first_occurrence), slab)
            # The dedup is pure overhead vs flat; the DCN bytes it saves
            # are shape-static (exchange_byte_model) and DCN is orders
            # of magnitude slower than ICI, so keep hier unless the
            # dedup dominates the whole plan build.
            choice = "hier" if t_dedup < 8.0 * max(t_flat, 1e-9) \
                else "flat"
        except Exception:  # pragma: no cover - backend quirk
            pass
    _TOPO_AUTO[tkey] = choice
    _M_ROUTE_AUTOTUNE.inc()
    _metrics.gauge("glt.dist.route_hier_selected",
                   "1 if the last topology autotune picked hierarchical",
                   ).set(1.0 if choice == "hier" else 0.0)
    return choice


def _bucket_payload(routing: Routing, payload: jnp.ndarray,
                    num_shards: int, cap: int) -> jnp.ndarray:
    """Scatter a payload array into the same bucket slots as its ids."""
    buckets = jnp.full((num_shards * cap + 1,), PADDING_ID, jnp.int32)
    slot = jnp.where(routing.valid, routing.slot, num_shards * cap)
    return buckets.at[slot].set(payload)[:-1]


# -- hierarchical (two-level ICI/DCN) routing ------------------------------
#
# On a 2-D (host, chip) mesh (multihost.global_mesh_2d) the flat plan
# wastes the slow fabric: a frontier id that every chip of one host wants
# crosses DCN once PER CHIP.  The hierarchical plan dedups within the
# host first:
#
#   per-chip owner bucketing            [S*cap] viewed [H, C, cap]
#     -> intra-host all_to_all (ICI, chip axis, split/concat dim 1)
#   per-dest-host slab                  [H, C*cap] on the owner-chip column
#     -> vmapped unique_first_occurrence per dest-host row
#   host-unique ids + inverse           uniq [H, hier_cap], inv [H, C*cap]
#     -> cross-host all_to_all (DCN, host axis) of ONLY uniq
#   owner serves each unique id once    [H*hier_cap] -> payload
#     -> DCN back, expand via inv (take_along_axis; inv never crossed DCN)
#     -> ICI back (chip axis), landing in the flat bucket order
#   flat unscatter                      resp[base.slot] masked by base.valid
#
# The response retraces the request legs in reverse, so the final scatter
# is the unmodified flat epilogue.  Bit-identity with the flat path holds
# because on 2-D meshes draws are keyed per (key, id) — layout-invariant
# — so serving a deduped id once and broadcasting the answer equals
# serving every duplicate slot (ops/neighbor_sample.draw_positions).

#: Decision table for the 2-D topology choice: (H, C, b, cap) -> 'flat' |
#: 'hier', filled by autotune_routing when given a mesh_shape.
_TOPO_AUTO: dict = {}


class HierGeom(NamedTuple):
    """Static geometry of a hierarchical plan (never crosses a jit
    boundary — built and consumed inside one shard_map body)."""
    num_hosts: int
    chips_per_host: int
    host_axis: str
    chip_axis: str
    cap: int        # per-owner bucket capacity of the flat base plan
    hier_cap: int   # per-dest-host unique-request capacity (DCN leg width)


class HierarchicalRouting(NamedTuple):
    """Two-level routing plan for one frontier on a 2-D mesh (see
    :func:`build_hier_routing`).  Wraps the flat :class:`Routing` (whose
    ``slot``/``valid`` still drive the final unscatter) plus the per-host
    dedup state the DCN legs ride on.  Like :class:`Routing`: build ONCE
    per hop frontier, thread through every exchange over that frontier.
    """
    base: Routing
    uniq: jnp.ndarray          # [H, hier_cap] host-unique ids, -1 padded
    inv: jnp.ndarray           # [H, C*cap] index into uniq row, -1 = pad/drop
    hier_dropped: jnp.ndarray  # [] int32: unique ids beyond hier_cap
    geom: HierGeom


def hier_request_cap(cap: int, chips_per_host: int, nodes_per_shard: int,
                     hier_load_factor: Optional[float] = None) -> int:
    """DCN-leg width per dest host: how many host-unique ids one device
    forwards to each remote host.

    The lossless bound is ``min(C*cap, nodes_per_shard)`` — a dest-host
    slab has ``C*cap`` slots, and its uniques are all owned by ONE shard
    so there can never be more than ``nodes_per_shard`` of them.  An
    explicit ``hier_load_factor`` (α) bounds the buffer at
    ``ceil(α * C * cap)`` like ``exchange_load_factor`` does for the flat
    buckets: overflow is dropped (masked padding, counted), and the DCN
    bytes shrink by ~1/α.
    """
    lossless = min(int(chips_per_host) * int(cap),
                   max(1, int(nodes_per_shard)))
    if hier_load_factor is None:
        return lossless
    bounded = max(1, int(np.ceil(float(hier_load_factor)
                                 * chips_per_host * cap)))
    return min(lossless, bounded)


def _topology_choice(route: str, axis_name,
                     mesh_shape: Optional[tuple] = None) -> str:
    """Resolve the routing topology ('flat' | 'hier') at trace time.

    Priority: ``GLT_ROUTE_FORCE`` env ('flat'/'hier') > explicit
    ``route`` argument > 1-D meshes pin 'flat' > autotuned decision table
    > default ('hier' on a mesh with both axes > 1, else 'flat').  The
    same env var keeps carrying the bucketing values ('sort'/'onepass');
    the two sub-seams are orthogonal and each ignores the other's tokens.
    """
    env = os.environ.get("GLT_ROUTE_FORCE")
    forced = env if env in ("flat", "hier") else (
        route if route in ("flat", "hier") else None)
    if isinstance(axis_name, str) or len(tuple(axis_name)) < 2:
        return "flat"          # 1-D meshes pin flat, even when forced
    if forced is not None:
        return forced
    if mesh_shape is None:
        return "flat"
    h, c = int(mesh_shape[0]), int(mesh_shape[1])
    if h < 2 or c < 2:
        return "flat"          # degenerate grid: nothing to dedup over
    hit = _TOPO_AUTO.get((h, c))
    return hit if hit is not None else "hier"


def build_hier_routing(
    ids: jnp.ndarray,
    nodes_per_shard: int,
    num_hosts: int,
    chips_per_host: int,
    host_axis: str,
    chip_axis: str,
    cap: Optional[int] = None,
    hier_load_factor: Optional[float] = None,
    route: str = "auto",
    base: Optional[Routing] = None,
) -> HierarchicalRouting:
    """Build the two-level routing plan for a frontier; call inside
    ``shard_map`` over the 2-D mesh, ONCE per hop frontier.

    Runs the ICI request leg and the per-dest-host dedup eagerly (they
    are part of the plan — every exchange over this frontier reuses the
    same ``uniq``/``inv``); the DCN legs run per exchange.  ``inv`` stays
    device-local: only the host-unique ids ever cross DCN.

    Args:
      ids: ``[B]`` global node ids, -1 padded.
      cap: per-owner bucket capacity; ``None`` -> ``B`` (overflow-free).
      hier_load_factor: DCN buffer bound (see :func:`hier_request_cap`).
      base: pre-built flat :class:`Routing` over ``ids`` with this
        ``cap``, if the caller already has one.
    """
    b = ids.shape[0]
    cap = b if cap is None else int(cap)
    h, c = int(num_hosts), int(chips_per_host)
    num_shards = h * c
    if base is None:
        owner = jnp.where(ids >= 0, ids // nodes_per_shard, -1)
        base = _bucket_by_owner(ids, owner, num_shards, cap=cap,
                                route=route)
    # ICI leg: land every local chip's bucket for owner (oh, my_chip) on
    # this device — slab[oh, q*cap + j] = chip q's j-th request for that
    # owner.
    slab = lax.all_to_all(base.buckets.reshape(h, c, cap), chip_axis,
                          1, 1, tiled=False).reshape(h, c * cap)
    u = jax.vmap(unique_first_occurrence)(slab)
    hc = hier_request_cap(cap, c, nodes_per_shard, hier_load_factor)
    uniq = u.uniques[:, :hc]
    inv = jnp.where((u.inverse >= 0) & (u.inverse < hc), u.inverse, -1)
    hier_dropped = jnp.sum(jnp.maximum(u.count - hc, 0)).astype(jnp.int32)
    return HierarchicalRouting(
        base=base, uniq=uniq, inv=inv, hier_dropped=hier_dropped,
        geom=HierGeom(num_hosts=h, chips_per_host=c, host_axis=host_axis,
                      chip_axis=chip_axis, cap=cap, hier_cap=hc))


def hier_requests(hr: HierarchicalRouting) -> jnp.ndarray:
    """DCN request leg: ``[H * hier_cap]`` host-unique ids addressed to
    this device (row ``qh`` came from host ``qh``'s same-chip peer)."""
    g = hr.geom
    return lax.all_to_all(hr.uniq, g.host_axis, 0, 0,
                          tiled=False).reshape(g.num_hosts * g.hier_cap)


def hier_response(hr: HierarchicalRouting, payload: jnp.ndarray,
                  fill) -> jnp.ndarray:
    """Retrace the request legs in reverse: per-unique-request payload
    ``[H * hier_cap, W]`` -> ``[S * cap, W]`` in flat bucket order.

    DCN back (host axis), expand each dest-host row through ``inv``
    (duplicates get copies of the one served answer; dropped/padding
    slots get ``fill``), then ICI back (chip axis) to the requesting
    chip.  The result unscatters with the unmodified flat epilogue
    ``payload[base.slot]`` under ``base.valid``.
    """
    g = hr.geom
    w = payload.shape[-1]
    resp = lax.all_to_all(payload.reshape(g.num_hosts, g.hier_cap, w),
                          g.host_axis, 0, 0, tiled=False)
    safe = jnp.clip(hr.inv, 0, g.hier_cap - 1)
    full = jnp.take_along_axis(resp, safe[..., None], axis=1)
    full = jnp.where((hr.inv >= 0)[..., None], full, fill)
    back = lax.all_to_all(
        full.reshape(g.num_hosts, g.chips_per_host, g.cap, w),
        g.chip_axis, 1, 1, tiled=False)
    return back.reshape(g.num_hosts * g.chips_per_host * g.cap, w)


def exchange_byte_model(topology: str, num_hosts: int, chips_per_host: int,
                        cap: int, payload_elems: int,
                        hier_cap: Optional[int] = None,
                        elem_bytes: int = 4):
    """Per-device ``(ici_bytes, dcn_bytes)`` for one request+response
    round trip, from static plan shapes (what the
    ``glt.dist.collective_bytes{axis=}`` counters accumulate).

    Flat on ``[H, C]``: each device sends ``cap`` ids (+ ``payload_elems``
    response elems per slot) to all ``S-1`` peers — ``C-1`` of them over
    ICI, ``(H-1)*C`` over DCN.  Hier: the ICI legs move the full
    ``[H, C, cap]`` bucket block minus the self column; only
    ``(H-1) * hier_cap`` slots cross DCN.
    """
    h, c = int(num_hosts), int(chips_per_host)
    per_slot = (1 + int(payload_elems)) * int(elem_bytes)
    if topology == "flat":
        ici = (c - 1) * cap * per_slot
        dcn = (h - 1) * c * cap * per_slot
    elif topology == "hier":
        hc = c * cap if hier_cap is None else int(hier_cap)
        ici = (c - 1) * h * cap * per_slot
        dcn = (h - 1) * hc * per_slot
    else:
        raise ValueError(f"topology must be 'flat' or 'hier', "
                         f"got {topology!r}")
    return int(ici), int(dcn)


def build_sorted_edge_view(indptr: jnp.ndarray, indices: jnp.ndarray):
    """Per-shard (row, dst) pairs lex-sorted for binary search; call inside
    ``shard_map`` (or on a single shard's block).

    The distributed analog of the column-sorted auxiliary view the Graph
    class keeps for `edge_in_csr` (random_negative_sampler.cu:37-54) —
    here the whole local edge block is sorted by (local row, global dst)
    so membership is one lexicographic ``lower_bound``.  Two int32 keys
    instead of one packed int64 key: x64 stays off.
    """
    max_e = indices.shape[0]
    c = indptr.shape[0] - 1
    pos = jnp.arange(max_e, dtype=jnp.int32)
    row = jnp.searchsorted(indptr.astype(jnp.int32), pos,
                           side="right").astype(jnp.int32) - 1
    n_edges = indptr[c].astype(jnp.int32)
    valid = pos < n_edges
    big = jnp.int32(2**31 - 1)
    row = jnp.where(valid, row, big)
    dst = jnp.where(valid, indices, big)
    order = jnp.lexsort((dst, row))
    return row[order], dst[order]


def _pair_exists(rows_s: jnp.ndarray, dsts_s: jnp.ndarray,
                 r: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """Branchless lexicographic lower_bound over the sorted edge view."""
    e = rows_s.shape[0]
    last = e - 1
    lo = jnp.zeros_like(r)
    hi = jnp.full_like(r, e)
    for _ in range(32):
        cond = lo < hi
        mid = lo + (hi - lo) // 2
        mc = jnp.clip(mid, 0, last)
        mr, md = rows_s[mc], dsts_s[mc]
        less = (mr < r) | ((mr == r) & (md < d))
        lo = jnp.where(cond & less, mid + 1, lo)
        hi = jnp.where(cond & ~less, mid, hi)
    lc = jnp.clip(lo, 0, last)
    return (lo < e) & (rows_s[lc] == r) & (dsts_s[lc] == d)


def dist_edge_exists(
    rows_s: jnp.ndarray,
    dsts_s: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    nodes_per_shard: int,
    num_shards: int,
    axis_name: str,
    route: str = "auto",
    fused: Optional[bool] = None,
) -> jnp.ndarray:
    """Global membership test for (src, dst) pairs; call inside shard_map.

    Routes each candidate pair to the shard owning ``src`` (one fused
    id+payload all-to-all), runs the local sorted-view lookup there, and
    routes the verdicts back — the collective rebuild of the reference's
    strict negative check, which it *skips* in distributed mode
    (dist_neighbor_sampler.py:327-453 uses non-strict draws).  Returns
    ``[B]`` bool (False for padding slots).
    """
    b = src.shape[0]
    my_rank = lax.axis_index(axis_name)
    owner = jnp.where(src >= 0, src // nodes_per_shard, -1)
    routing = _bucket_by_owner(src, owner, num_shards, cap=b, route=route)
    dst_buckets = _bucket_payload(routing, dst, num_shards, b)

    if _use_fused(fused):
        # src ids and dst payload ride ONE collective as a packed [.., 2]
        # block — all_to_all moves axis-0 blocks, so the trailing pack
        # axis is inert and the unpacked halves are bit-identical to the
        # split path's two launches.
        pair = jnp.stack([routing.buckets, dst_buckets], axis=-1)
        req = lax.all_to_all(pair.reshape(num_shards, b, 2), axis_name,
                             0, 0, tiled=False).reshape(num_shards * b, 2)
        req_s, req_d = req[:, 0], req[:, 1]
    else:
        req_s = lax.all_to_all(routing.buckets.reshape(num_shards, b),
                               axis_name, 0, 0, tiled=False).reshape(-1)
        req_d = lax.all_to_all(dst_buckets.reshape(num_shards, b),
                               axis_name, 0, 0, tiled=False).reshape(-1)

    local = req_s - my_rank * nodes_per_shard
    ok = (req_s >= 0) & (local >= 0) & (local < nodes_per_shard)
    exists = _pair_exists(rows_s, dsts_s,
                          jnp.where(ok, local, 0).astype(jnp.int32),
                          jnp.where(ok, req_d, 0).astype(jnp.int32))
    exists = (exists & ok).astype(jnp.int32)

    resp = lax.all_to_all(exists.reshape(num_shards, b), axis_name, 0, 0,
                          tiled=False).reshape(-1)
    return jnp.where(routing.valid, resp[routing.slot] > 0, False)


def exchange_one_hop(
    seeds: jnp.ndarray,
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    edge_ids: jnp.ndarray,
    nodes_per_shard: int,
    num_shards: int,
    fanout: int,
    key: jax.Array,
    axis_name: str,
    remote_cap: Optional[int] = None,
    route: str = "auto",
    fused: Optional[bool] = None,
    routing=None,
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
):
    """One distributed sampling hop; call inside ``shard_map``.

    Args:
      seeds: ``[B]`` global seed ids on this shard (-1 padded).
      indptr/indices/edge_ids: this shard's local CSR block
        (:class:`~glt_tpu.parallel.sharding.ShardedGraph` fields with the
        leading shard axis already consumed by shard_map).
      key: per-shard PRNG key (fold in the axis index for decorrelation).
      axis_name: the mesh axis (str) or axis tuple — a 2-D
        ``("host", "chip")`` mesh passes the tuple; the flat topology
        then addresses the combined axis (host-major, identical to the
        1-D flat order) and the hier topology splits the legs per axis.
      remote_cap: capacity-bounded exchange (VERDICT r3 #3).  ``None``
        reproduces the reference-exact worst-case buffers (every shard
        reserves the full frontier width ``B`` for every destination, so
        each hop moves ``S*B`` ids — the exact-size-message analog of
        dist_neighbor_sampler.py:542-598 padded to worst case).  With a
        cap, **locally-owned seeds never enter the collective at all**
        (they are sampled straight from the local CSR block — on
        contiguous partitions hop 0 of a shard-local seed batch is
        exchange-free) and only remote ids ride per-owner buckets of
        width ``remote_cap``, shrinking exchange bytes by ``S*B /
        (S*remote_cap)``.  Ids past an owner's cap are dropped (masked
        padding, never garbage) and counted.
      route / fused: routing-path and collective-fusion seams (see
        :func:`_route_choice` / :func:`_use_fused`); ``route`` also
        carries the topology tokens 'flat'/'hier' (see
        :func:`_topology_choice`).
      routing: pre-built :class:`Routing` (flat) or
        :class:`HierarchicalRouting` for ``seeds`` — only honored when
        ``remote_cap`` is None (the capped path buckets the
        remote-masked subset, a different plan).  A hierarchical plan
        forces the hier transport regardless of ``route``.
      mesh_shape: ``(num_hosts, chips_per_host)`` of the 2-D mesh —
        required for the hier topology when ``routing`` is not prebuilt.
      hier_load_factor: DCN-leg buffer bound (see
        :func:`hier_request_cap`); None = lossless.

    Returns:
      ``(nbrs, eids, mask, dropped)``; first three ``[B, fanout]`` in seed
      order, ``dropped`` a scalar int32 (always 0 when ``remote_cap`` is
      None and the hier DCN buffer is lossless).
    """
    b = seeds.shape[0]
    my_rank = lax.axis_index(axis_name)
    owner = jnp.where(seeds >= 0, seeds // nodes_per_shard, -1)
    # `hier` reads ONLY the incoming argument and the static topology
    # seam — never the rebuilt plan below — so the branch predicate is
    # provably uniform across shards (GLT020's taint chain stops at the
    # parameter).  The plan gets its own name for the same reason.
    hier = isinstance(routing, HierarchicalRouting) or (
        routing is None
        and _topology_choice(route, axis_name, mesh_shape) == "hier")
    plan = routing
    # 2-D meshes key draws per (key, id) so the flat and hier transports
    # are bit-identical (dedup serves each id once); 1-D meshes keep the
    # historical per-slot stream.
    key_by = "slot" if isinstance(axis_name, str) else "id"

    if remote_cap is None:
        cap = b
        local_nbrs = local_eids = None
        if hier and not isinstance(plan, HierarchicalRouting):
            plan = build_hier_routing(
                seeds, nodes_per_shard, mesh_shape[0], mesh_shape[1],
                axis_name[0], axis_name[1], cap=b,
                hier_load_factor=hier_load_factor, route=route,
                base=plan)
        elif plan is None:
            plan = _bucket_by_owner(seeds, owner, num_shards, cap=b,
                                    route=route)
    else:
        cap = int(remote_cap)
        # Local split: owner == my shard -> direct sample, no collective.
        is_local = owner == my_rank
        local_ids = jnp.where(is_local, seeds - my_rank * nodes_per_shard,
                              -1)
        lout = sample_neighbors(indptr, indices, local_ids, fanout, key,
                                edge_ids=edge_ids, key_by=key_by)
        local_nbrs, local_eids = lout.nbrs, lout.eids
        remote_ids = jnp.where(is_local, PADDING_ID, seeds)
        if hier:
            plan = build_hier_routing(
                remote_ids, nodes_per_shard, mesh_shape[0], mesh_shape[1],
                axis_name[0], axis_name[1], cap=cap,
                hier_load_factor=hier_load_factor, route=route)
        else:
            plan = _bucket_by_owner(remote_ids, owner, num_shards,
                                    cap=cap, route=route)

    flat_plan = plan.base if hier else plan

    # Request exchange: the ids this shard must serve.  Flat: row q =
    # ids wanted by shard q from us.  Hier: row qh = host qh's unique
    # wants from us (DCN leg; the ICI leg already ran in the plan build).
    if hier:
        requests = hier_requests(plan)
    else:
        requests = lax.all_to_all(
            plan.buckets.reshape(num_shards, cap), axis_name, 0, 0,
            tiled=False).reshape(num_shards * cap)

    # Sample requested ids from the local CSR block (global -> local row).
    local = jnp.where(requests >= 0,
                      requests - my_rank * nodes_per_shard, -1)
    local = jnp.where((local >= 0) & (local < nodes_per_shard), local, -1)
    out = sample_neighbors(indptr, indices, local, fanout,
                           jax.random.fold_in(key, 1), edge_ids=edge_ids,
                           key_by=key_by)

    # Response exchange + unscatter (the stitch, stitch_sample_results.cu:57).
    if hier:
        # The hier transport always packs neighbors + edge ids into one
        # payload (its legs are shared infrastructure); `fused` only
        # selects the flat path's collective shape.
        resp = hier_response(
            plan, jnp.concatenate([out.nbrs, out.eids], axis=-1),
            fill=PADDING_ID)
        resp_nbrs, resp_eids = resp[:, :fanout], resp[:, fanout:]
    elif _use_fused(fused):
        # Neighbors and edge ids ride ONE [S, cap, 2*fanout] collective
        # (half the per-hop launches); the halves split back bit-exact.
        resp = lax.all_to_all(
            jnp.concatenate([out.nbrs, out.eids], axis=-1)
            .reshape(num_shards, cap, 2 * fanout), axis_name, 0, 0,
            tiled=False).reshape(num_shards * cap, 2 * fanout)
        resp_nbrs, resp_eids = resp[:, :fanout], resp[:, fanout:]
    else:
        resp_nbrs = lax.all_to_all(
            out.nbrs.reshape(num_shards, cap, fanout), axis_name, 0, 0,
            tiled=False).reshape(num_shards * cap, fanout)
        resp_eids = lax.all_to_all(
            out.eids.reshape(num_shards, cap, fanout), axis_name, 0, 0,
            tiled=False).reshape(num_shards * cap, fanout)

    nbrs = jnp.where(flat_plan.valid[:, None],
                     resp_nbrs[flat_plan.slot], PADDING_ID)
    eids = jnp.where(flat_plan.valid[:, None],
                     resp_eids[flat_plan.slot], PADDING_ID)
    if local_nbrs is not None:
        sel = is_local[:, None]
        nbrs = jnp.where(sel, local_nbrs, nbrs)
        eids = jnp.where(sel, local_eids, eids)
    dropped = (flat_plan.dropped + plan.hier_dropped if hier
               else plan.dropped)
    return nbrs, eids, nbrs >= 0, dropped


def exchange_one_hop_ring(
    seeds: jnp.ndarray,
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    edge_ids: jnp.ndarray,
    nodes_per_shard: int,
    num_shards: int,
    fanout: int,
    key: jax.Array,
    axis_name: str,
    remote_cap: Optional[int] = None,
    route: str = "auto",
    fused: Optional[bool] = None,
    routing: Optional[Routing] = None,
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
):
    """Ring-pipelined variant of :func:`exchange_one_hop`.

    Instead of one all-to-all burst, request buckets rotate around the ring
    with ``lax.ppermute`` (the ring-attention software-pipeline pattern):
    at step ``k`` each shard samples the requests of the shard ``k`` hops
    upstream while the next buckets are in flight.  Same result, different
    collective shape — preferable when the mesh axis spans DCN links or
    when overlapping sampling compute with transfers matters more than
    burst bandwidth.  ``remote_cap`` bounds the travelling matrix exactly
    as in :func:`exchange_one_hop` (local seeds never enter the ring).
    With ``fused`` the neighbor/edge-id answer buffers travel as one
    packed block, cutting the per-step ppermute launches from 3 to 2.
    The ring is a flat topology by construction — ``mesh_shape`` /
    ``hier_load_factor`` are accepted for signature parity with
    :func:`exchange_one_hop` and ignored (on a 2-D mesh the ring rotates
    the combined axis; draws keep the 2-D per-id keying so it stays
    comparable with the all-to-all paths).
    """
    del mesh_shape, hier_load_factor  # flat-only transport
    b = seeds.shape[0]
    my = lax.axis_index(axis_name)
    owner = jnp.where(seeds >= 0, seeds // nodes_per_shard, -1)
    key_by = "slot" if isinstance(axis_name, str) else "id"

    def local_sample(ids, k):
        local = jnp.where(ids >= 0, ids - my * nodes_per_shard, -1)
        local = jnp.where((local >= 0) & (local < nodes_per_shard), local, -1)
        return sample_neighbors(indptr, indices, local, fanout,
                                jax.random.fold_in(key, k),
                                edge_ids=edge_ids, key_by=key_by)

    if remote_cap is None:
        cap = b
        if routing is None:
            routing = _bucket_by_owner(seeds, owner, num_shards, cap=cap,
                                       route=route)
        local_nbrs = local_eids = is_local = None
    else:
        cap = int(remote_cap)
        is_local = owner == my
        lout = local_sample(jnp.where(is_local, seeds, PADDING_ID),
                            num_shards)
        local_nbrs, local_eids = lout.nbrs, lout.eids
        routing = _bucket_by_owner(
            jnp.where(is_local, PADDING_ID, seeds), owner, num_shards,
            cap=cap, route=route)

    right = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    fuse = _use_fused(fused)

    # The request matrix and its answer buffers travel the ring together:
    # after k rotations shard i holds the matrix that originated at shard
    # i-k and serves ITS row i (the requests shard i-k addressed to i).
    # After a final rotation (num_shards total) every matrix is home with
    # all rows answered — one serve + one hop per step, fully pipelined.
    reqs = routing.buckets.reshape(num_shards, cap)
    if fuse:
        ans = jnp.full((num_shards, cap, 2 * fanout), PADDING_ID,
                       jnp.int32)

        def serve(reqs, ans, k):
            o = local_sample(jnp.take(reqs, my, axis=0), k)
            return ans.at[my].set(
                jnp.concatenate([o.nbrs, o.eids], axis=-1))

        ans = serve(reqs, ans, 0)
        for k in range(1, num_shards):
            reqs = lax.ppermute(reqs, axis_name, right)
            ans = lax.ppermute(ans, axis_name, right)
            ans = serve(reqs, ans, k)
        if num_shards > 1:
            ans = lax.ppermute(ans, axis_name, right)
        ans = ans.reshape(num_shards * cap, 2 * fanout)
        resp_nbrs, resp_eids = ans[:, :fanout], ans[:, fanout:]
    else:
        ans_n = jnp.full((num_shards, cap, fanout), PADDING_ID, jnp.int32)
        ans_e = jnp.full((num_shards, cap, fanout), PADDING_ID, jnp.int32)

        def serve(reqs, ans_n, ans_e, k):
            incoming = jnp.take(reqs, my, axis=0)
            o = local_sample(incoming, k)
            return ans_n.at[my].set(o.nbrs), ans_e.at[my].set(o.eids)

        ans_n, ans_e = serve(reqs, ans_n, ans_e, 0)
        for k in range(1, num_shards):
            reqs = lax.ppermute(reqs, axis_name, right)
            ans_n = lax.ppermute(ans_n, axis_name, right)
            ans_e = lax.ppermute(ans_e, axis_name, right)
            ans_n, ans_e = serve(reqs, ans_n, ans_e, k)
        if num_shards > 1:
            ans_n = lax.ppermute(ans_n, axis_name, right)
            ans_e = lax.ppermute(ans_e, axis_name, right)

        resp_nbrs = ans_n.reshape(num_shards * cap, fanout)
        resp_eids = ans_e.reshape(num_shards * cap, fanout)
    nbrs = jnp.where(routing.valid[:, None], resp_nbrs[routing.slot],
                     PADDING_ID)
    eids = jnp.where(routing.valid[:, None], resp_eids[routing.slot],
                     PADDING_ID)
    if local_nbrs is not None:
        sel = is_local[:, None]
        nbrs = jnp.where(sel, local_nbrs, nbrs)
        eids = jnp.where(sel, local_eids, eids)
    return nbrs, eids, nbrs >= 0, routing.dropped


def dist_sample_multi_hop(
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    edge_ids: jnp.ndarray,
    seeds: jnp.ndarray,
    key: jax.Array,
    num_neighbors: Sequence[int],
    nodes_per_shard: int,
    num_shards: int,
    axis_name: str,
    frontier_cap: Optional[int] = None,
    collective: str = "all_to_all",
    dedup: str = "auto",
    last_hop_dedup: bool = True,
    exchange_load_factor: Optional[float] = None,
    route: str = "auto",
    fused: Optional[bool] = None,
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
) -> SamplerOutput:
    """Per-shard multi-hop sampling body; call inside ``shard_map``.

    Identical structure to the single-device
    ``NeighborSampler._sample_impl`` — frontier, cumulative
    first-occurrence dedup, relabeled COO — with
    :func:`exchange_one_hop` (or its ring variant, ``collective='ring'``)
    as the one-hop primitive.  ``dedup`` selects the inducer like the
    single-device sampler: 'dense' keeps a per-shard O(N_global) id map
    (4B per global node per shard — measured ~4x cheaper than the
    argsorts at wide frontiers), 'sort' the growing argsort buffer;
    'auto' prefers dense up to a ~1GB map.

    ``exchange_load_factor`` (α) opts into capacity-bounded exchanges:
    each hop's per-owner request buckets hold ``ceil(α * width /
    num_shards)`` remote ids instead of the full frontier width, cutting
    per-hop exchange bytes ~``num_shards/α``x; locally-owned frontier ids
    bypass the collective entirely.  Overflowed (dropped) request counts
    are surfaced in ``metadata['exchange_dropped']`` — with contiguous
    partitions and shard-local seeds α≈2 makes drops rare; monitor the
    counter and raise α (or use None = exact) if it is ever nonzero.

    ``route`` / ``fused`` select the bucketing implementation and the
    packed response collective (see :func:`_route_choice` /
    :func:`_use_fused`); on the exact (uncapped) path each hop's routing
    plan is built ONCE via :func:`build_routing` (or
    :func:`build_hier_routing` when the topology resolves hierarchical
    on a 2-D mesh — ``mesh_shape``/``hier_load_factor`` parameterize the
    two-level plan) and threaded into the exchange.
    """
    exchange = (exchange_one_hop if collective == "all_to_all"
                else exchange_one_hop_ring)
    topo = ("flat" if collective != "all_to_all"
            else _topology_choice(route, axis_name, mesh_shape))
    fanouts = list(num_neighbors)
    widths = hop_widths(seeds.shape[0], fanouts, frontier_cap)
    cap = max_sampled_nodes(seeds.shape[0], fanouts, frontier_cap)
    num_global = nodes_per_shard * num_shards
    if dedup == "auto":
        dedup = "dense" if dense_map_fits(num_global) else "sort"
    dense = dedup == "dense"

    if dense:
        state = dense_induce_init(num_global, cap)
        state, _ = dense_induce(state, seeds)
        node_buf = state.node_buf
        count = state.count
        frontier = node_buf[: widths[0]]
    else:
        u0 = unique_first_occurrence(seeds)
        # Growing unique buffer (see NeighborSampler._sample_impl): hop i
        # only sorts what can exist by hop i.
        node_buf = u0.uniques
        count = u0.count
        frontier = u0.uniques
    frontier_start = jnp.zeros((), jnp.int32)

    rows, cols, eids_out, emasks = [], [], [], []
    counts_per_hop = [count]
    edges_per_hop = []
    keys = jax.random.split(key, len(fanouts))
    leaf_off = cap - widths[-1] * fanouts[-1]
    leaf_mask = None

    dropped_total = jnp.zeros((), jnp.int32)
    for i, f in enumerate(fanouts):
        w = widths[i]
        last = i + 1 == len(fanouts)
        remote_cap = (None if exchange_load_factor is None
                      else bounded_remote_cap(w, exchange_load_factor,
                                              num_shards))
        # One routing plan per hop frontier (exact path); the capped
        # path buckets only the remote-masked subset inside the
        # exchange, a different plan per construction.
        if remote_cap is not None:
            hop_routing = None
        elif topo == "hier":
            hop_routing = build_hier_routing(
                frontier, nodes_per_shard, mesh_shape[0], mesh_shape[1],
                axis_name[0], axis_name[1],
                hier_load_factor=hier_load_factor, route=route)
        else:
            hop_routing = build_routing(frontier, nodes_per_shard,
                                        num_shards, route=route)
        nbrs, eids, mask, dropped = exchange(
            frontier, indptr, indices, edge_ids, nodes_per_shard,
            num_shards, f, keys[i], axis_name, remote_cap=remote_cap,
            route=route, fused=fused, routing=hop_routing,
            mesh_shape=mesh_shape, hier_load_factor=hier_load_factor)
        dropped_total = dropped_total + dropped

        src_local = frontier_start + jnp.arange(w, dtype=jnp.int32)
        src_local = jnp.where(frontier >= 0, src_local, PADDING_ID)

        if last and not last_hop_dedup:
            # Leaf block (see NeighborSampler.last_hop_dedup): zero map
            # ops at the widest frontier, one contiguous store.
            leaf_mask = mask.ravel()
            leaf_ids = jnp.where(leaf_mask, nbrs.ravel(), PADDING_ID)
            nbr_local = (leaf_off + jnp.arange(w * f, dtype=jnp.int32)
                         ).reshape(w, f)
            if dense:
                node_buf = lax.dynamic_update_slice(node_buf, leaf_ids,
                                                    (leaf_off,))
            else:
                node_buf = jnp.concatenate([node_buf, leaf_ids])
            new_count = count + jnp.sum(leaf_mask.astype(jnp.int32))
        elif dense:
            # The final hop never re-reads the id map: dense_induce_final
            # drops the dead commit scatter (see ops/unique.py).
            induce = dense_induce_final if last else dense_induce
            state, nbr_local = induce(state, nbrs.ravel())
            node_buf = state.node_buf
            new_count = state.count
            nbr_local = nbr_local.reshape(w, f)
        else:
            buflen = node_buf.shape[0]
            merged = unique_first_occurrence(
                jnp.concatenate([node_buf, nbrs.ravel()]))
            node_buf = merged.uniques
            new_count = merged.count
            nbr_local = merged.inverse[buflen:].reshape(w, f)
        nbr_local = jnp.where(mask, nbr_local, PADDING_ID)

        rows.append(nbr_local.ravel())
        cols.append(jnp.broadcast_to(src_local[:, None], (w, f)).ravel())
        eids_out.append(eids.ravel())
        emasks.append(mask.ravel())
        edges_per_hop.append(jnp.sum(mask.astype(jnp.int32)))

        if i + 1 < len(fanouts):
            nw = widths[i + 1]
            frontier = lax.dynamic_slice(
                jnp.concatenate(
                    [node_buf, jnp.full((nw,), PADDING_ID, jnp.int32)]),
                (jnp.clip(count, 0, node_buf.shape[0]),), (nw,))
            frontier_start = count
        count = new_count
        counts_per_hop.append(count)

    if node_buf.shape[0] < cap:
        node_buf = jnp.concatenate(
            [node_buf,
             jnp.full((cap - node_buf.shape[0],), PADDING_ID, jnp.int32)])
    node_buf = node_buf[:cap]
    count = jnp.minimum(count, cap)
    if leaf_mask is None:
        node_mask = jnp.arange(cap, dtype=jnp.int32) < count
    else:
        interior = jnp.minimum(count - edges_per_hop[-1], leaf_off)
        node_mask = (jnp.arange(cap, dtype=jnp.int32) < interior) | (
            jnp.concatenate([jnp.zeros((leaf_off,), bool), leaf_mask]))

    num_sampled_nodes = jnp.stack(
        [counts_per_hop[0]]
        + [counts_per_hop[i + 1] - counts_per_hop[i]
           for i in range(len(fanouts))])
    return SamplerOutput(
        node=node_buf,
        row=jnp.concatenate(rows),
        col=jnp.concatenate(cols),
        edge=jnp.concatenate(eids_out),
        batch=seeds,
        node_mask=node_mask,
        edge_mask=jnp.concatenate(emasks),
        num_sampled_nodes=num_sampled_nodes,
        num_sampled_edges=jnp.stack(edges_per_hop),
        metadata=(None
                  if exchange_load_factor is None
                  and hier_load_factor is None
                  else {"exchange_dropped": dropped_total}),
    )


def dist_node_subgraph(
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    edge_ids: jnp.ndarray,
    nodes: jnp.ndarray,
    max_degree: int,
    nodes_per_shard: int,
    num_shards: int,
    axis_name: str,
    route: str = "auto",
    fused: Optional[bool] = None,
):
    """Distributed induced-subgraph extraction; call inside ``shard_map``.

    TPU rebuild of the reference's distributed subgraph path
    (dist_neighbor_sampler.py:456-516): there, node-set rows are fetched
    from owner workers over RPC and the CUDA SubGraphOp filters them.  Here
    each node's CSR row (capped at ``max_degree``) comes back through one
    all-to-all round trip, and membership filtering is the same sorted
    lookup the single-device op uses (ops/subgraph.py).

    Args:
      nodes: ``[B]`` unique global node ids (-1 padded).

    Returns ``(rows, cols, eids, mask)`` of shape ``[B * max_degree]`` —
    local indices into ``nodes``, matching
    :class:`~glt_tpu.ops.subgraph.SubGraphOutput`.
    """
    b = nodes.shape[0]
    routing = build_routing(nodes, nodes_per_shard, num_shards,
                            route=route)

    requests = lax.all_to_all(
        routing.buckets.reshape(num_shards, b), axis_name, 0, 0,
        tiled=False).reshape(num_shards * b)

    my_rank = lax.axis_index(axis_name)
    local = jnp.where(requests >= 0,
                      requests - my_rank * nodes_per_shard, -1)
    local = jnp.where((local >= 0) & (local < nodes_per_shard), local, -1)
    start, deg = _row_offsets_and_degrees(indptr, local.astype(jnp.int32))
    start = start.astype(jnp.int32)
    offs = jnp.arange(max_degree, dtype=jnp.int32)[None, :]
    in_row = (offs < deg[:, None]) & (local >= 0)[:, None]
    flat = start[:, None] + jnp.where(in_row, offs, 0)
    nbrs = jnp.where(in_row, indices[flat], PADDING_ID).astype(jnp.int32)
    eids = jnp.where(in_row, edge_ids[flat], PADDING_ID).astype(jnp.int32)

    if _use_fused(fused):
        resp = lax.all_to_all(
            jnp.concatenate([nbrs, eids], axis=-1)
            .reshape(num_shards, b, 2 * max_degree), axis_name, 0, 0,
            tiled=False).reshape(num_shards * b, 2 * max_degree)
        resp_nbrs, resp_eids = resp[:, :max_degree], resp[:, max_degree:]
    else:
        resp_nbrs = lax.all_to_all(
            nbrs.reshape(num_shards, b, max_degree), axis_name, 0, 0,
            tiled=False).reshape(num_shards * b, max_degree)
        resp_eids = lax.all_to_all(
            eids.reshape(num_shards, b, max_degree), axis_name, 0, 0,
            tiled=False).reshape(num_shards * b, max_degree)
    nbrs = jnp.where(routing.valid[:, None], resp_nbrs[routing.slot],
                     PADDING_ID)
    eids = jnp.where(routing.valid[:, None], resp_eids[routing.slot],
                     PADDING_ID)

    # Membership + relabel (ops/subgraph.py:56-63 semantics).
    local_dst = relabel_by_reference(nodes, nbrs.ravel()).reshape(
        b, max_degree)
    keep = (nbrs >= 0) & (local_dst >= 0)
    local_src = jnp.broadcast_to(
        jnp.arange(b, dtype=jnp.int32)[:, None], (b, max_degree))
    rows = jnp.where(keep, local_src, PADDING_ID).ravel()
    cols = jnp.where(keep, local_dst, PADDING_ID).ravel()
    eids = jnp.where(keep, eids, PADDING_ID).ravel()
    return rows, cols, eids, keep.ravel()


class DistNeighborSampler:
    """Multi-hop distributed sampler over a :class:`ShardedGraph`.

    The multi-hop structure (frontier, cumulative first-occurrence dedup,
    relabeled COO) is identical to the single-device
    :class:`~glt_tpu.sampler.neighbor_sampler.NeighborSampler`; only the
    one-hop primitive is the all-to-all exchange.  ``sample`` returns a
    per-shard :class:`SamplerOutput` (leading axis = shard) — each shard's
    batch is its own ego-subgraph, ready for data-parallel training.
    """

    def __init__(self, sharded_graph, mesh: Mesh,
                 axis_name: Optional[str] = None,
                 num_neighbors: Sequence[int] = (15, 10, 5),
                 batch_size: int = 512,
                 frontier_cap: Optional[int] = None,
                 collective: str = "all_to_all",
                 valid_per_shard: Optional[np.ndarray] = None,
                 seed: int = 0,
                 last_hop_dedup: bool = True,
                 exchange_load_factor: Optional[float] = None,
                 route: str = "auto",
                 fused: Optional[bool] = None,
                 hier_load_factor: Optional[float] = None):
        self.collective = collective
        self.valid_per_shard = valid_per_shard
        self.last_hop_dedup = bool(last_hop_dedup)
        self.exchange_load_factor = exchange_load_factor
        self.fused = fused
        self.hier_load_factor = hier_load_factor
        self._edges_fns = {}
        self._subgraph_fns = {}
        self.g = sharded_graph
        self.mesh = mesh
        self.axis_name = resolve_mesh_axes(mesh, axis_name)
        axis_name = self.axis_name
        self.mesh_shape = mesh_axis_sizes(mesh, self.axis_name)
        self.num_neighbors = list(num_neighbors)
        self.batch_size = int(batch_size)
        self.frontier_cap = frontier_cap
        self._base_key = jax.random.PRNGKey(seed)
        self._call_count = 0
        self._widths = hop_widths(self.batch_size, self.num_neighbors,
                                  frontier_cap)
        # Routing A/B seam: 'auto' autotunes sort vs one-pass at the
        # dominant (widest-frontier) shape on TPU; elsewhere the
        # shard-count heuristic picks (env GLT_ROUTE_FORCE still wins at
        # trace time — see _route_choice).  On a 2-D mesh the same sweep
        # also fills the flat-vs-hier topology table; the topology token
        # itself resolves at trace time (_topology_choice) so the
        # resolved bucketing choice stored here never erases it.
        self.route = route
        if route == "auto":
            self.route = autotune_routing(max(self._widths),
                                          self.g.num_shards,
                                          mesh_shape=self.mesh_shape)
        self.node_capacity = max_sampled_nodes(self.batch_size,
                                               self.num_neighbors,
                                               frontier_cap)

        g = self.g
        gspec = P(axis_name)
        self._shard_fn = jax.jit(
            jax.shard_map(
                self._sample_local,
                mesh=mesh,
                in_specs=(gspec, gspec, gspec, gspec, P()),
                out_specs=gspec,
                check_vma=False,
            ))

    def _next_key(self) -> jax.Array:
        key = jax.random.fold_in(self._base_key, self._call_count)
        self._call_count += 1
        return key

    def _sample_local(self, indptr_blk, indices_blk, eids_blk, seeds_blk,
                      key):
        """Per-shard body (shapes carry a leading singleton shard axis)."""
        key = jax.random.fold_in(key, lax.axis_index(self.axis_name))
        out = dist_sample_multi_hop(
            indptr_blk[0], indices_blk[0], eids_blk[0], seeds_blk[0], key,
            self.num_neighbors, self.g.nodes_per_shard, self.g.num_shards,
            self.axis_name, self.frontier_cap, self.collective,
            last_hop_dedup=self.last_hop_dedup,
            exchange_load_factor=self.exchange_load_factor,
            route=self.route, fused=self.fused,
            mesh_shape=self.mesh_shape,
            hier_load_factor=self.hier_load_factor)
        # Re-add the shard axis for shard_map's out_specs.
        return jax.tree.map(lambda x: x[None], out)

    def sample_from_nodes(self, seeds_per_shard: jnp.ndarray,
                          key: Optional[jax.Array] = None) -> SamplerOutput:
        """``seeds_per_shard``: ``[S, batch_size]`` global ids, -1 padded."""
        if key is None:
            key = self._next_key()
        g = self.g
        # Host dispatch boundary of the whole shard_map program (routing
        # + collectives + local sampling run device-side inside it) —
        # span measures enqueue only, the consumer's sync sees the rest.
        with _span("dist.sample_dispatch", route=self.route), \
                _M_DIST_SAMPLE_MS.time():
            out = self._shard_fn(g.indptr, g.indices, g.edge_ids,
                                 seeds_per_shard, key)
        _M_DIST_BATCHES.inc()
        return out

    # -- distributed link path (cf. dist_neighbor_sampler.py:327-453) ------
    def _valid_per_shard(self) -> jnp.ndarray:
        """Valid-node count per shard, for uniform negative draws."""
        if self.valid_per_shard is not None:
            return jnp.asarray(self.valid_per_shard, jnp.int32)
        g = self.g
        counts = np.clip(g.num_nodes - np.arange(g.num_shards)
                         * g.nodes_per_shard, 0, g.nodes_per_shard)
        return jnp.asarray(counts, jnp.int32)

    def _sorted_edge_view(self):
        """Per-shard lex-sorted (row, dst) view for strict negative
        checks; built once, cached (device arrays, sharded)."""
        if getattr(self, "_sorted_view", None) is None:
            gspec = P(self.axis_name)
            fn = jax.jit(jax.shard_map(
                lambda ip, ix: tuple(
                    a[None] for a in build_sorted_edge_view(ip[0], ix[0])),
                mesh=self.mesh, in_specs=(gspec, gspec),
                out_specs=(gspec, gspec), check_vma=False))
            self._sorted_view = fn(self.g.indptr, self.g.indices)
        return self._sorted_view

    def sample_from_edges(self, src: jnp.ndarray, dst: jnp.ndarray,
                          neg_sampling: Optional[NegativeSampling] = None,
                          key: Optional[jax.Array] = None,
                          strict: bool = False,
                          trials: int = 4) -> SamplerOutput:
        """Distributed seed-edge sampling; negatives non-strict by default.

        ``src`` / ``dst``: ``[S, B]`` global endpoint ids per shard (-1
        padded).  The reference's distributed engine is always non-strict
        (dist_neighbor_sampler.py:327-453: "we use non-strict negative
        sampling in distributed mode"); here ``strict=True`` goes beyond
        it: candidate pairs are routed to the shard owning the source and
        checked against its CSR block (:func:`dist_edge_exists`) over
        ``trials`` rejection rounds, with the reference's non-strict
        padding pass for slots that never clear
        (random_negative_sampler.cu:153-160).  Returns a per-shard
        :class:`SamplerOutput` whose metadata carries ``edge_label_index``
        + ``edge_label`` (binary/None) or the triplet indices.
        """
        if key is None:
            key = self._next_key()
        mode = None if neg_sampling is None else neg_sampling.mode
        amount = (0 if neg_sampling is None
                  else int(round(neg_sampling.amount)))
        strict = bool(strict) and mode is not None
        fn = self._get_edges_fn(mode, amount, int(src.shape[1]), strict,
                                trials)
        g = self.g
        if strict:
            rows_s, dsts_s = self._sorted_edge_view()
            return fn(g.indptr, g.indices, g.edge_ids, rows_s, dsts_s,
                      src, dst, key)
        return fn(g.indptr, g.indices, g.edge_ids, src, dst, key)

    def _get_edges_fn(self, mode, amount, q, strict=False, trials=4):
        k = (mode, amount, q, strict, trials)
        if k not in self._edges_fns:
            gspec = P(self.axis_name)

            if strict:
                def local(indptr, indices, eids, rows_s, dsts_s, src, dst,
                          key):
                    out = self._edges_body(
                        mode, amount, q, indptr[0], indices[0], eids[0],
                        src[0], dst[0], key,
                        strict_view=(rows_s[0], dsts_s[0]), trials=trials)
                    return jax.tree.map(lambda x: x[None], out)

                specs = (gspec,) * 7 + (P(),)
            else:
                def local(indptr, indices, eids, src, dst, key):
                    out = self._edges_body(mode, amount, q, indptr[0],
                                           indices[0], eids[0], src[0],
                                           dst[0], key)
                    return jax.tree.map(lambda x: x[None], out)

                specs = (gspec,) * 5 + (P(),)

            self._edges_fns[k] = jax.jit(jax.shard_map(
                local, mesh=self.mesh, in_specs=specs,
                out_specs=gspec, check_vma=False))
        return self._edges_fns[k]

    def _edges_body(self, mode, amount, q, indptr, indices, eids, src, dst,
                    key, strict_view=None, trials=4):
        key = jax.random.fold_in(key, lax.axis_index(self.axis_name))
        kneg, ksample = jax.random.split(key)
        counts = self._valid_per_shard()
        c = self.g.nodes_per_shard
        s_count = self.g.num_shards

        def uniform_ids(k, n):
            """Uniform over valid (relabeled) ids: pick a shard, then a
            row modulo that shard's valid count."""
            ks, ku = jax.random.split(k)
            sh = jax.random.randint(ks, (n,), 0, s_count, dtype=jnp.int32)
            # Draw over the full int31 range before the modulo so the bias
            # toward low rows is O(count / 2^31) instead of O(count / c).
            u = jax.random.randint(ku, (n,), 0, jnp.int32(2**31 - 1),
                                   dtype=jnp.int32)
            return sh * c + u % jnp.maximum(counts[sh], 1)

        def strict_pairs(k, n, valid, fixed_src=None):
            """``trials`` routed rejection rounds + non-strict padding."""
            rows_s, dsts_s = strict_view
            best_s = jnp.full((n,), PADDING_ID, jnp.int32)
            best_d = jnp.full((n,), PADDING_ID, jnp.int32)
            found = jnp.zeros((n,), bool)
            last_s = last_d = None
            for t in range(trials):
                ks_, kd_ = jax.random.split(jax.random.fold_in(k, t))
                s = (fixed_src if fixed_src is not None
                     else uniform_ids(ks_, n))
                d = uniform_ids(kd_, n)
                ex = dist_edge_exists(
                    rows_s, dsts_s, jnp.where(valid, s, PADDING_ID), d,
                    c, s_count, self.axis_name, route=self.route,
                    fused=self.fused)
                take = valid & ~found & ~ex
                best_s = jnp.where(take, s, best_s)
                best_d = jnp.where(take, d, best_d)
                found = found | take
                last_s, last_d = s, d
            # Padding pass: never-cleared slots keep their last draw
            # (possibly positive) so the output is always full width.
            pad = valid & ~found
            best_s = jnp.where(pad, last_s, best_s)
            best_d = jnp.where(pad, last_d, best_d)
            return best_s, best_d

        if mode == "binary":
            rep = jnp.repeat(src >= 0, amount)
            if strict_view is not None:
                neg_src, neg_dst = strict_pairs(kneg, q * amount, rep)
            else:
                ks, kd = jax.random.split(kneg)
                neg_src = uniform_ids(ks, q * amount)
                neg_dst = uniform_ids(kd, q * amount)
            neg_src = jnp.where(rep, neg_src, PADDING_ID)
            neg_dst = jnp.where(rep, neg_dst, PADDING_ID)
            seeds = jnp.concatenate([src, dst, neg_src, neg_dst])
        elif mode == "triplet":
            rep = jnp.repeat(src >= 0, amount)
            if strict_view is not None:
                src_rep = jnp.repeat(src, amount)
                _, neg_dst = strict_pairs(kneg, q * amount, rep,
                                          fixed_src=src_rep)
            else:
                neg_dst = uniform_ids(kneg, q * amount)
            neg_dst = jnp.where(rep, neg_dst, PADDING_ID)
            seeds = jnp.concatenate([src, dst, neg_dst])
        else:
            seeds = jnp.concatenate([src, dst])

        out = dist_sample_multi_hop(
            indptr, indices, eids, seeds, ksample, self.num_neighbors,
            c, s_count, self.axis_name, self.frontier_cap, self.collective,
            last_hop_dedup=self.last_hop_dedup,
            exchange_load_factor=self.exchange_load_factor,
            route=self.route, fused=self.fused)

        # Seed ids first-occur in the hop-0 prefix; relabel against that
        # slice only (the no-dedup leaf block may repeat seed ids).
        ref = out.node[: seeds.shape[0]]
        meta = dict(out.metadata or {})
        if mode == "binary":
            all_src = jnp.concatenate([src, neg_src])
            all_dst = jnp.concatenate([dst, neg_dst])
            meta["edge_label_index"] = jnp.stack([
                relabel_by_reference(ref, all_src),
                relabel_by_reference(ref, all_dst)])
            pos_label = jnp.where(src >= 0, 1, PADDING_ID)
            meta["edge_label"] = jnp.concatenate(
                [pos_label, jnp.zeros((q * amount,), jnp.int32)])
        elif mode == "triplet":
            meta["src_index"] = relabel_by_reference(ref, src)
            meta["dst_pos_index"] = relabel_by_reference(ref, dst)
            meta["dst_neg_index"] = relabel_by_reference(
                ref, neg_dst).reshape(q, amount)
        else:
            meta["edge_label_index"] = jnp.stack([
                relabel_by_reference(ref, src),
                relabel_by_reference(ref, dst)])
        out.metadata = meta
        return out

    # -- distributed subgraph (cf. dist_neighbor_sampler.py:456-516) -------
    def subgraph(self, seeds_per_shard: jnp.ndarray, max_degree: int = 64,
                 key: Optional[jax.Array] = None) -> SamplerOutput:
        """Hop expansion + distributed induced-subgraph extraction.

        Each shard's node set is collected by the multi-hop exchange, then
        every member's (capped) adjacency row is fetched from its owner
        shard and filtered to the set — all inside one jitted program.
        """
        if key is None:
            key = self._next_key()
        fn = self._get_subgraph_fn(int(max_degree))
        g = self.g
        return fn(g.indptr, g.indices, g.edge_ids, seeds_per_shard, key)

    def _get_subgraph_fn(self, max_degree):
        if max_degree not in self._subgraph_fns:
            gspec = P(self.axis_name)

            def local(indptr, indices, eids, seeds, key):
                key = jax.random.fold_in(key, lax.axis_index(self.axis_name))
                # Always exact dedup here: the induced extract relabels
                # against a unique node set (cf. NeighborSampler.subgraph).
                base = dist_sample_multi_hop(
                    indptr[0], indices[0], eids[0], seeds[0], key,
                    self.num_neighbors, self.g.nodes_per_shard,
                    self.g.num_shards, self.axis_name, self.frontier_cap,
                    self.collective, last_hop_dedup=True,
                    route=self.route, fused=self.fused)
                rows, cols, se, mask = dist_node_subgraph(
                    indptr[0], indices[0], eids[0], base.node, max_degree,
                    self.g.nodes_per_shard, self.g.num_shards,
                    self.axis_name, route=self.route, fused=self.fused)
                out = SamplerOutput(
                    node=base.node, row=rows, col=cols, edge=se,
                    batch=seeds[0], node_mask=base.node_mask,
                    edge_mask=mask,
                    num_sampled_nodes=base.num_sampled_nodes,
                    metadata={"mapping": jnp.arange(self.batch_size,
                                                    dtype=jnp.int32)})
                return jax.tree.map(lambda x: x[None], out)

            self._subgraph_fns[max_degree] = jax.jit(jax.shard_map(
                local, mesh=self.mesh,
                in_specs=(gspec, gspec, gspec, gspec, P()),
                out_specs=gspec, check_vma=False))
        return self._subgraph_fns[max_degree]
