"""Multi-host execution: process-spanning mesh + per-host array feeding.

TPU-native replacement for the reference's cross-machine plane.  There,
every process joins a torch-RPC universe: ``init_rpc`` all-gathers
``(role, world_size, rank)`` tuples from every process to build name
tables (distributed/rpc.py:236-292), with rendezvous via the
``MASTER_ADDR``/``MASTER_PORT`` env convention
(distributed/dist_options.py:75-100), and every cross-host sample/feature
request is an RPC.

On TPU none of that machinery survives: the cross-host plane is
``jax.distributed`` — one coordinator process, every process contributes
its local chips to ONE global :class:`~jax.sharding.Mesh`, and the
collectives inside the jitted programs (`dist_sampler`, `dist_feature`,
`dist_train`) ride ICI within a host and DCN between hosts, routed by XLA
from the same sharding annotations that drove the single-process path.
The "name table" is the device mesh; the "partition book" stays
arithmetic.  What this module adds is the *host-side seam*:

* :func:`initialize` — rendezvous (env-var conventions kept from the
  reference: ``MASTER_ADDR``/``MASTER_PORT``, plus ``GLT_*`` overrides);
* :func:`global_mesh` — a mesh over every process's devices;
* per-host **global array assembly** — each process feeds only the shard
  blocks it owns (graph CSR blocks, feature rows, labels, seed batches)
  via ``jax.make_array_from_process_local_data``, so no host ever
  materialises another host's partition.

Single-process meshes are the degenerate case: every helper works
unchanged when ``jax.process_count() == 1``, so the training-step
builders in :mod:`~glt_tpu.parallel.dist_train` need no changes at all —
the same jitted program runs on a laptop mesh, a v5e-8, or a multi-host
v5e-16 (4 processes x 4 chips).

Emulation without a pod (the reference's single-host multi-process test
strategy, SURVEY §4): spawn N processes with
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=K``
and a localhost coordinator; collectives cross process boundaries over
gloo.  See tests/test_multihost.py.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.topology import CSRTopo
from .sharding import ShardedFeature, ShardedGraph, shard_graph_blocks


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (idempotent).

    Defaults come from the environment, keeping the reference's rendezvous
    convention (dist_options.py:75-100): ``MASTER_ADDR``/``MASTER_PORT``
    form the coordinator address, ``WORLD_SIZE``/``RANK`` (or the
    explicit ``GLT_NUM_PROCESSES``/``GLT_PROCESS_ID``) give the fleet
    shape.  On Cloud TPU pods with no env set, ``jax.distributed``
    auto-detects all three from the TPU metadata server.
    """
    # NOTE: must not touch the backend (jax.devices / process_count)
    # before jax.distributed.initialize — only the client handle check
    # below is safe.
    if _initialized():
        return
    # Ambient TPU-tunnel hooks (sitecustomize) may pin
    # jax.config.jax_platforms at interpreter start, which outranks the
    # JAX_PLATFORMS env var; restore the env var's intent so CPU-fleet
    # emulation works under those hooks.
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)
    if coordinator_address is None:
        addr = os.environ.get("GLT_COORDINATOR_ADDR")
        if addr is None:
            host = os.environ.get("MASTER_ADDR")
            port = os.environ.get("MASTER_PORT")
            addr = f"{host}:{port}" if host and port else None
        coordinator_address = addr
    if num_processes is None:
        n = os.environ.get("GLT_NUM_PROCESSES",
                           os.environ.get("WORLD_SIZE"))
        num_processes = int(n) if n is not None else None
    if process_id is None:
        r = os.environ.get("GLT_PROCESS_ID", os.environ.get("RANK"))
        process_id = int(r) if r is not None else None
    # A multi-process CPU fleet needs a cross-process collectives
    # implementation — without one XLA rejects the first process-spanning
    # computation ("Multiprocess computations aren't implemented on the
    # CPU backend").  Gloo ships in jaxlib; select it before the backend
    # client is created.  TPU/GPU fleets ignore this knob.
    if "cpu" in (os.environ.get("JAX_PLATFORMS")
                 or jax.config.jax_platforms or ""):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # older/newer jax spellings
            pass
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _initialized() -> bool:
    state = getattr(jax.distributed, "global_state", None)
    return state is not None and state.client is not None


def shutdown() -> None:
    if _initialized():
        jax.distributed.shutdown()


# -- deadline-bounded collectives ------------------------------------------
#
# The characteristic multihost failure mode is the forever-hang: one
# preempted host leaves every surviving peer blocked inside a collective
# with no exception and no timeout.  With GLT_MULTIHOST_TIMEOUT_S set,
# every host-side collective in this module runs under the supervisor's
# deadline wrapper and a dead/straggling peer surfaces as a structured
# BarrierTimeoutError the training loop converts into a
# checkpoint-and-exit (docs/distributed.md "Fleet supervision").  Unset
# (the default), behavior is exactly as before — zero wrapper overhead.

#: Env var: seconds a multihost barrier/collective may block before a
#: structured BarrierTimeoutError; 0/unset = unbounded (legacy).
TIMEOUT_ENV = "GLT_MULTIHOST_TIMEOUT_S"


def collective_deadline_secs() -> float:
    """The configured collective deadline (0.0 = unbounded)."""
    try:
        return float(os.environ.get(TIMEOUT_ENV, "0") or 0.0)
    except ValueError:
        return 0.0


def _bounded(fn, what: str):
    """Run a host-side collective under the configured deadline."""
    deadline = collective_deadline_secs()
    if deadline <= 0:
        return fn()
    from ..distributed.supervisor import run_with_deadline

    return run_with_deadline(fn, deadline, what=what)


def barrier(name: str, timeout_s: Optional[float] = None) -> None:
    """A named cross-process barrier that cannot hang forever.

    Single-process: immediate no-op.  Fleet: ``sync_global_devices``
    under ``timeout_s`` (default: the :data:`TIMEOUT_ENV` deadline;
    unbounded when neither is set).  Raises
    :class:`~glt_tpu.distributed.supervisor.BarrierTimeoutError` on
    expiry — the caller checkpoints and exits (TrainLoop does both).
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    def sync():
        multihost_utils.sync_global_devices(name)

    if timeout_s is None:
        _bounded(sync, what=f"barrier {name!r}")
    else:
        from ..distributed.supervisor import run_with_deadline

        run_with_deadline(sync, float(timeout_s),
                          what=f"barrier {name!r}")


def global_mesh(axis_name: str = "shard") -> Mesh:
    """One-axis mesh over every device of every process.

    ``jax.devices()`` orders devices so each process's block is
    contiguous, so shard ``s`` of any array sharded on ``axis_name`` is
    addressable exactly by the process owning device ``s``.
    """
    return Mesh(np.array(jax.devices()), (axis_name,))


def global_mesh_2d(host_axis: str = "host", chip_axis: str = "chip",
                   num_hosts: Optional[int] = None) -> Mesh:
    """Two-axis ``(host, chip)`` mesh: devices reshaped
    ``[n_hosts, chips_per_host]`` in flat device order.

    The hierarchical router (:class:`~glt_tpu.parallel.dist_sampler.
    HierarchicalRouting`) reads the fabric off the axis names: the
    ``chip_axis`` rows ride ICI, the ``host_axis`` columns ride DCN.
    Because the grid is a row-major reshape of ``jax.devices()``, shard
    ``s`` of a dim-0-sharded array lands on grid cell
    ``(s // chips_per_host, s % chips_per_host)`` — flat-path code
    addressing the combined ``(host_axis, chip_axis)`` axis sees exactly
    the 1-D :func:`global_mesh` device order.

    Args:
      num_hosts: mesh rows; defaults to ``jax.process_count()`` (one row
        per process — the physical layout).  Override to emulate a pod
        shape, e.g. a single 8-device process testing a 2x4 mesh.

    Raises:
      ValueError: device count not divisible by ``num_hosts``, or a
        process's devices straddle a host-row boundary without covering
        whole rows (per-axis contiguity — required so per-host feeding
        keeps addressing contiguous flat shard ranges).
    """
    devs = np.array(jax.devices())
    n = devs.size
    h = jax.process_count() if num_hosts is None else int(num_hosts)
    if h <= 0 or n % h:
        raise ValueError(
            f"cannot reshape {n} devices onto {h} mesh rows "
            f"({host_axis!r} axis): not divisible")
    c = n // h
    grid = devs.reshape(h, c)
    # Per-axis contiguity: every host row must be a union of whole
    # process blocks, or every process block a union of whole rows —
    # otherwise some process would own a non-contiguous slice of a row
    # and the arithmetic partition book breaks down.
    for r in range(h):
        procs = {d.process_index for d in grid[r]}
        if len(procs) > 1:
            for p in procs:
                owned = [i for i, d in enumerate(devs)
                         if d.process_index == p]
                row_slice = set(range(r * c, (r + 1) * c))
                if not row_slice.issuperset(owned) and \
                        not row_slice.issubset(owned):
                    raise ValueError(
                        f"process {p} devices straddle mesh row {r} of "
                        f"axes ({host_axis!r}, {chip_axis!r}): it owns "
                        f"flat device slots {owned}, row {r} spans "
                        f"{sorted(row_slice)}; pick num_hosts so host "
                        f"rows align with process boundaries")
    return Mesh(grid, (host_axis, chip_axis))


def mesh_axes(mesh: Mesh):
    """The dim-0 sharding spec for ``mesh``: its axis name (1-D) or the
    full axis-name tuple (N-D, sharding dim 0 over all axes row-major).

    This is what makes every helper below 2-D-aware: a
    ``(host, chip)`` mesh shards dim 0 over both axes in flat device
    order, so per-host feeding and shard arithmetic are unchanged.
    """
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def _dim0_spec(mesh: Mesh, axis_name):
    """Resolve a (possibly stale 1-D) ``axis_name`` against ``mesh``."""
    names = tuple(mesh.axis_names)
    if isinstance(axis_name, str) and len(names) == 1 \
            and axis_name in names:
        return axis_name
    if isinstance(axis_name, tuple) and tuple(axis_name) == names:
        return axis_name
    return mesh_axes(mesh)


def local_shard_range(mesh: Mesh, axis_name: str = "shard") -> range:
    """Global shard indices whose device lives in this process.

    The per-host feeding helpers build host data only for this range (the
    reference's "each machine loads its own partition",
    dist_dataset.py:77-164).  Raises if the local block is not contiguous
    — the contiguous-ownership invariant the arithmetic partition book
    depends on.
    """
    devs = mesh.devices.reshape(-1)
    mine = [i for i, d in enumerate(devs)
            if d.process_index == jax.process_index()]
    if not mine:
        return range(0)
    lo, hi = min(mine), max(mine) + 1
    if mine != list(range(lo, hi)):
        axes = tuple(mesh.axis_names)
        offending = [getattr(devs[i], "id", i) for i in mine]
        raise ValueError(
            f"local devices are not contiguous on mesh axes {axes!r} "
            f"(shape {tuple(mesh.devices.shape)}): process "
            f"{jax.process_index()} owns flat shard slots {mine} "
            f"(device ids {offending}), expected one contiguous run — "
            f"rebuild the mesh with global_mesh/global_mesh_2d so each "
            f"process's devices form a contiguous block in flat "
            f"(row-major) device order")
    return range(lo, hi)


def assemble_global(local_block: np.ndarray, mesh: Mesh,
                    axis_name: str = "shard") -> jax.Array:
    """Per-process ``[S_local, ...]`` block -> global ``[S, ...]`` array.

    Every process calls this with its own shards' slab; the result is one
    logical array sharded over ``axis_name`` whose device-local data never
    crossed hosts.  On a multi-axis mesh, dim 0 is sharded over *all*
    axes in row-major order (see :func:`mesh_axes`), so the flat shard
    numbering is identical to the 1-D case.
    """
    sharding = NamedSharding(mesh, P(_dim0_spec(mesh, axis_name)))
    num_shards = mesh.devices.size
    global_shape = (num_shards,) + tuple(local_block.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_block), global_shape)


def agree_max(value: int) -> int:
    """Max of a host-side int across processes (single-process: identity).

    Used to agree on padding widths (e.g. the per-shard edge-block width)
    when each host computed its own from local partitions only.
    """
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    all_vals = _bounded(
        lambda: multihost_utils.process_allgather(
            np.asarray([value], np.int64)),
        what="agree_max allgather")
    return int(np.max(all_vals))


def agree_sum(arr: np.ndarray) -> np.ndarray:
    """Elementwise sum of a host array across processes.

    Used for global statistics assembled from per-partition data (e.g.
    in-degree hotness when each host holds only its partitions' edges).
    O(N * num_processes) gather — pass precomputed global stats instead
    when N is huge.
    """
    arr = np.asarray(arr)
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    return np.sum(_bounded(
        lambda: multihost_utils.process_allgather(arr),
        what="agree_sum allgather"), axis=0)


# -- per-host sharded construction ----------------------------------------

def shard_graph_global(topo: CSRTopo, mesh: Mesh,
                       axis_name: str = "shard") -> ShardedGraph:
    """Full-topology convenience: every host holds ``topo`` but builds and
    feeds only its own shards' CSR blocks.

    For hosts that hold only their partitions' edges, build local blocks
    with :func:`~glt_tpu.parallel.sharding.shard_graph_blocks` +
    :func:`agree_max` and assemble with :func:`assemble_global` (that is
    what :meth:`DistDataset.load <glt_tpu.distributed.dist_dataset.
    DistDataset.load>` does when given a mesh).
    """
    num_shards = mesh.devices.size
    rng = local_shard_range(mesh, axis_name)
    ip, ix, ei, c = shard_graph_blocks(topo, num_shards, shard_range=rng)
    return ShardedGraph(
        indptr=assemble_global(ip, mesh, axis_name),
        indices=assemble_global(ix, mesh, axis_name),
        edge_ids=assemble_global(ei, mesh, axis_name),
        nodes_per_shard=c, num_nodes=topo.num_nodes, num_shards=num_shards)


def shard_hetero_graph_global(topos, mesh: Mesh,
                              axis_name: str = "shard"):
    """Hetero analog of :func:`shard_graph_global`: every edge type's CSR
    sharded by its source type's ranges, each fed per host."""
    return {et: shard_graph_global(t, mesh, axis_name)
            for et, t in topos.items()}


def shard_feature_global(feature: np.ndarray, mesh: Mesh,
                         axis_name: str = "shard",
                         dtype=None) -> ShardedFeature:
    """``[N, d]`` rows (or this host's slice of them) -> per-host-fed
    :class:`ShardedFeature`.

    ``feature`` may be the full matrix (every host slices its own rows) —
    hosts holding only their partitions' rows should pass those through
    :func:`assemble_global` directly.
    """
    feature = np.asarray(feature)
    n, d = feature.shape
    num_shards = mesh.devices.size
    c = -(-n // num_shards)
    rng = local_shard_range(mesh, axis_name)
    rows = np.zeros((len(rng), c, d), feature.dtype if dtype is None
                    else np.dtype(dtype))
    for j, s in enumerate(rng):
        lo, hi = min(s * c, n), min((s + 1) * c, n)
        rows[j, : hi - lo] = feature[lo:hi]
    return ShardedFeature(rows=assemble_global(rows, mesh, axis_name),
                          nodes_per_shard=c, num_shards=num_shards)


def labels_global(labels: np.ndarray, mesh: Mesh, nodes_per_shard: int,
                  axis_name: str = "shard", fill: int = -1) -> jax.Array:
    """Global ``[N]`` labels -> ``[S, c]`` sharded block, fed per host."""
    labels = np.asarray(labels)
    n = labels.shape[0]
    num_shards = mesh.devices.size
    c = nodes_per_shard
    rng = local_shard_range(mesh, axis_name)
    blk = np.full((len(rng), c), fill, labels.dtype)
    for j, s in enumerate(rng):
        lo, hi = min(s * c, n), min((s + 1) * c, n)
        blk[j, : hi - lo] = labels[lo:hi]
    return assemble_global(blk, mesh, axis_name)


def feed_seeds(seeds: np.ndarray, mesh: Mesh,
               axis_name: str = "shard") -> jax.Array:
    """``[S, B]`` per-shard seed batch -> global array, fed per host.

    Every host may hold the full ``[S, B]`` matrix (the deterministic
    epoch split of :meth:`DistDataset.split_seeds` is reproducible from a
    shared seed) — each feeds only its own rows.
    """
    seeds = np.asarray(seeds)
    rng = local_shard_range(mesh, axis_name)
    return assemble_global(seeds[rng.start: rng.stop], mesh, axis_name)
