"""Distributed heterogeneous neighbor sampling over a device mesh.

Rebuild of the reference's distributed hetero path
(dist_neighbor_sampler.py:270-288: all edge-type hop tasks issued
concurrently, each routed per-partition and stitched).  Here every edge
type's CSR is sharded by its **source type's** contiguous node ranges, and
the hetero multi-hop body (:class:`HeteroNeighborSampler`) runs per shard
with the one-hop primitive swapped for the all-to-all exchange of
:func:`~glt_tpu.parallel.dist_sampler.exchange_one_hop` — per edge type,
over the same mesh axis.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..data.topology import CSRTopo
from ..ops.neighbor_sample import NeighborOutput
from ..sampler.base import HeteroSamplerOutput, NodeSamplerInput
from ..sampler.hetero_neighbor_sampler import (
    HeteroNeighborSampler,
    hetero_hop_widths,
)
from ..typing import EdgeType, NodeType, PADDING_ID
from .dist_sampler import (
    autotune_routing,
    bounded_remote_cap,
    exchange_one_hop,
    mesh_axis_sizes,
    resolve_mesh_axes,
)
from .sharding import ShardedGraph, shard_graph


def shard_hetero_graph(topos: Dict[EdgeType, CSRTopo], num_shards: int
                       ) -> Dict[EdgeType, ShardedGraph]:
    """Shard every edge type's CSR by its source type's node ranges."""
    return {et: shard_graph(t, num_shards) for et, t in topos.items()}


class DistHeteroNeighborSampler:
    """Multi-hop distributed hetero sampler.

    Args:
      sharded: dict ``EdgeType -> ShardedGraph`` (from
        :func:`shard_hetero_graph`).
      mesh / axis_name: the device mesh to sample over.
      num_neighbors / input_type / batch_size: as
        :class:`HeteroNeighborSampler`.
    """

    def __init__(self, sharded: Dict[EdgeType, ShardedGraph], mesh: Mesh,
                 num_neighbors, input_type: NodeType,
                 batch_size: int = 512, axis_name: Optional[str] = None,
                 frontier_cap: Optional[int] = None,
                 seed: int = 0,
                 last_hop_dedup: bool = True,
                 exchange_load_factor: Optional[float] = None,
                 route: str = "auto",
                 fused: Optional[bool] = None,
                 hier_load_factor: Optional[float] = None):
        self.sharded = sharded
        self.mesh = mesh
        # None resolves to the mesh's own axes (1-D name or 2-D tuple);
        # on a 2-D mesh the per-type hops ride the hierarchical
        # dedup-then-exchange topology when the route seam picks 'hier'.
        axis_name = resolve_mesh_axes(mesh, axis_name)
        self.axis_name = axis_name
        self.mesh_shape = mesh_axis_sizes(mesh, axis_name)
        self.hier_load_factor = hier_load_factor
        self.fused = fused
        # Capacity-bounded exchange, per edge type (homo parity — VERDICT
        # r4 #4; the reference's hetero engine issues worst-case per-hop
        # RPC fan-outs, dist_neighbor_sampler.py:270-288): each hop's
        # per-owner request buckets hold ceil(α * width / S) remote ids of
        # THAT edge type's frontier instead of the full width; shard-local
        # ids bypass the collective.  Per-type dropped counts surface in
        # metadata['exchange_dropped'].
        self.exchange_load_factor = exchange_load_factor
        self._trace_dropped: list = []
        # Reuse the single-device sampler's planning + multi-hop body; the
        # Graph objects aren't touched (one_hop is overridden).
        self._planner = HeteroNeighborSampler.__new__(HeteroNeighborSampler)
        p = self._planner
        p.graphs = {et: None for et in sharded}
        p.edge_types = sorted(sharded.keys())
        if isinstance(num_neighbors, dict):
            p.num_neighbors = {et: list(v) for et, v in num_neighbors.items()}
        else:
            p.num_neighbors = {et: list(num_neighbors)
                               for et in p.edge_types}
        p.num_hops = max(len(v) for v in p.num_neighbors.values())
        p.input_type = input_type
        p.batch_size = int(batch_size)
        p.last_hop_dedup = bool(last_hop_dedup)
        self.last_hop_dedup = bool(last_hop_dedup)
        # Global per-type node counts so the planner's dense inducer
        # engages (ids here are global across shards).
        p._num_nodes_by_type = {}
        for et, g in sharded.items():
            p._num_nodes_by_type.setdefault(
                et[0], g.nodes_per_shard * g.num_shards)
        self.input_type = input_type
        self.batch_size = int(batch_size)
        self._base_key = jax.random.PRNGKey(seed)
        self._call_count = 0

        self._widths, self._capacity = hetero_hop_widths(
            p.edge_types, p.num_neighbors, {input_type: self.batch_size},
            p.num_hops, frontier_cap=frontier_cap)

        # Routing A/B seam (homo parity): autotune at the widest per-type
        # frontier on TPU, heuristic elsewhere; GLT_ROUTE_FORCE still
        # wins at trace time.
        self.route = route
        if route == "auto":
            num_shards = next(iter(sharded.values())).num_shards
            widest = max(max(w.values()) for w in self._widths)
            self.route = autotune_routing(widest, num_shards,
                                          mesh_shape=self.mesh_shape)

        gspec = P(axis_name)
        arrays = {et: (g.indptr, g.indices, g.edge_ids)
                  for et, g in sharded.items()}
        specs = jax.tree.map(lambda _: gspec, arrays)
        self._shard_fn = jax.jit(jax.shard_map(
            self._local_body, mesh=mesh,
            in_specs=(specs, gspec, P()),
            out_specs=gspec,
            check_vma=False))

    def _next_key(self) -> jax.Array:
        key = jax.random.fold_in(self._base_key, self._call_count)
        self._call_count += 1
        return key

    def _one_hop(self, et, arrays, frontier, fanout, key):
        indptr, indices, edge_ids = arrays
        g = self.sharded[et]
        remote_cap = (None if self.exchange_load_factor is None
                      else bounded_remote_cap(frontier.shape[0],
                                              self.exchange_load_factor,
                                              g.num_shards))
        nbrs, eids, mask, dropped = exchange_one_hop(
            frontier, indptr, indices, edge_ids, g.nodes_per_shard,
            g.num_shards, fanout, key, self.axis_name,
            remote_cap=remote_cap, route=self.route, fused=self.fused,
            mesh_shape=self.mesh_shape,
            hier_load_factor=self.hier_load_factor)
        if self.exchange_load_factor is not None:
            self._trace_dropped.append(dropped)
        return NeighborOutput(nbrs=nbrs, eids=eids, mask=mask)

    def local_sample(self, arrays, seeds, key):
        """Multi-hop hetero sample from inside an enclosing shard_map.

        Public seam for fused train steps
        (:func:`~glt_tpu.parallel.dist_train.make_hetero_dist_train_step`):
        ``arrays`` is the per-shard ``{etype: (indptr, indices, edge_ids)}``
        view, ``seeds`` the local ``[batch]`` seed ids of ``input_type``,
        ``key`` already folded with the shard's axis index.
        """
        self._trace_dropped = []
        out = self._planner._sample_impl(
            self._widths, self._capacity, arrays,
            {self.input_type: seeds}, key, one_hop=self._one_hop)
        if self._trace_dropped:
            # Summed over hops and edge types during THIS trace; rides the
            # output so callers observe bounded-exchange drops exactly as
            # in the homo path (dist_sample_multi_hop's metadata).
            total = self._trace_dropped[0]
            for d in self._trace_dropped[1:]:
                total = total + d
            out.metadata = {"exchange_dropped": total,
                            **(out.metadata or {})}
            self._trace_dropped = []
        return out

    @property
    def edge_types(self):
        return list(self._planner.edge_types)

    @property
    def num_neighbors(self):
        return {et: list(v) for et, v in self._planner.num_neighbors.items()}

    @property
    def node_capacity(self):
        """Static per-node-type unique-node capacity of one local sample."""
        return dict(self._capacity)

    @property
    def hop_widths(self):
        """Per-hop per-node-type frontier widths (static trace shapes)."""
        return [dict(w) for w in self._widths]

    def _local_body(self, arrays_blk, seeds_blk, key):
        arrays = jax.tree.map(lambda x: x[0], arrays_blk)
        seeds = seeds_blk[0]
        key = jax.random.fold_in(key, lax.axis_index(self.axis_name))
        out = self.local_sample(arrays, seeds, key)
        return jax.tree.map(lambda x: x[None], out)

    def sample_from_nodes(self, seeds_per_shard: jnp.ndarray,
                          key: Optional[jax.Array] = None
                          ) -> HeteroSamplerOutput:
        """``seeds_per_shard``: ``[S, batch_size]`` global seed ids of the
        input type, -1 padded; returns per-shard hetero outputs (leading
        axis = shard)."""
        if key is None:
            key = self._next_key()
        arrays = {et: (g.indptr, g.indices, g.edge_ids)
                  for et, g in self.sharded.items()}
        return self._shard_fn(arrays, seeds_per_shard, key)
