"""Fully-fused distributed training step: sample + gather + SGD in one jit.

The reference's distributed training loop spans four process fleets —
sampling workers, shm channels, RPC feature servers, and DDP trainers
(SURVEY §3.2).  On TPU the entire iteration is **one XLA program over the
mesh**: per-shard all-to-all neighbor sampling
(:func:`~glt_tpu.parallel.dist_sampler.dist_sample_multi_hop`), all-to-all
feature/label gather (:func:`~glt_tpu.parallel.dist_feature.exchange_gather`),
model forward/backward, and a gradient ``pmean`` (the NCCL-allreduce analog,
examples/distributed/dist_train_sage_supervised.py:52-58).  Each mesh device
plays both roles of the reference's collocated mode (dist_loader.py:142-186):
graph-shard owner and data-parallel trainer.
"""
from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.train import TrainState, seed_cross_entropy
from ..typing import PADDING_ID
from .dist_feature import exchange_gather
from .dist_sampler import dist_sample_multi_hop
from .sharding import ShardedFeature, ShardedGraph


def make_dist_train_step(
    model,
    tx,
    g: ShardedGraph,
    f: ShardedFeature,
    labels: jnp.ndarray,          # [S, nodes_per_shard] int labels
    mesh: Mesh,
    num_neighbors: Sequence[int],
    batch_size: int,
    axis_name: str = "shard",
    frontier_cap: Optional[int] = None,
):
    """Build ``step(state, seeds [S, B], key) -> (state, loss, acc)``.

    ``seeds`` carries one seed batch per shard (the per-rank disjoint seed
    split of dist_train_sage_supervised.py:76); params/opt state are
    replicated; gradients are ``pmean``-ed across the mesh.
    """
    gspec = P(axis_name)

    def local_body(indptr, indices, edge_ids, rows, labels_blk, seeds,
                   params, key):
        indptr, indices, edge_ids = indptr[0], indices[0], edge_ids[0]
        rows, labels_blk, seeds = rows[0], labels_blk[0], seeds[0]
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

        out = dist_sample_multi_hop(
            indptr, indices, edge_ids, seeds, key, num_neighbors,
            g.nodes_per_shard, g.num_shards, axis_name, frontier_cap)
        x = exchange_gather(out.node, rows, f.nodes_per_shard,
                            f.num_shards, axis_name)
        y = exchange_gather(out.node, labels_blk[:, None].astype(jnp.int32),
                            g.nodes_per_shard, g.num_shards, axis_name)[:, 0]
        y = jnp.where(out.node >= 0, y, PADDING_ID)
        edge_index = jnp.stack([out.row, out.col])

        def loss_fn(p):
            logits = model.apply(p, x, edge_index, out.edge_mask,
                                 train=True, rngs={"dropout": key})
            return seed_cross_entropy(logits, y, batch_size, out.node_mask)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        grads = lax.pmean(grads, axis_name)
        loss = lax.pmean(loss, axis_name)
        acc = lax.pmean(acc, axis_name)
        return loss, acc, grads

    shard_fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(gspec, gspec, gspec, gspec, gspec, gspec, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)

    @jax.jit
    def step(state: TrainState, seeds: jnp.ndarray, key: jax.Array):
        loss, acc, grads = shard_fn(g.indptr, g.indices, g.edge_ids,
                                    f.rows, labels, seeds, state.params, key)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss, acc

    return step


def init_dist_state(model, tx, g: ShardedGraph, f: ShardedFeature,
                    rng: jax.Array, num_neighbors: Sequence[int],
                    batch_size: int) -> TrainState:
    """Initialize replicated params/opt-state with correctly-shaped dummies."""
    from ..sampler.neighbor_sampler import hop_widths, max_sampled_nodes

    cap = max_sampled_nodes(batch_size, list(num_neighbors))
    widths = hop_widths(batch_size, list(num_neighbors))
    ecap = sum(w * fo for w, fo in zip(widths, num_neighbors))

    x = jnp.zeros((cap, f.rows.shape[-1]), f.rows.dtype)
    ei = jnp.full((2, ecap), PADDING_ID, jnp.int32)
    mask = jnp.zeros((ecap,), bool)
    params = model.init({"params": rng}, x, ei, mask)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))
