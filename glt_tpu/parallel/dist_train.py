"""Fully-fused distributed training step: sample + gather + SGD in one jit.

The reference's distributed training loop spans four process fleets —
sampling workers, shm channels, RPC feature servers, and DDP trainers
(SURVEY §3.2).  On TPU the entire iteration is **one XLA program over the
mesh**: per-shard all-to-all neighbor sampling
(:func:`~glt_tpu.parallel.dist_sampler.dist_sample_multi_hop`), all-to-all
feature/label gather (:func:`~glt_tpu.parallel.dist_feature.exchange_gather`),
model forward/backward, and a gradient ``pmean`` (the NCCL-allreduce analog,
examples/distributed/dist_train_sage_supervised.py:52-58).  Each mesh device
plays both roles of the reference's collocated mode (dist_loader.py:142-186):
graph-shard owner and data-parallel trainer.
"""
from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.train import TrainState, seed_cross_entropy
from ..typing import PADDING_ID
from ..ops.unique import unique_first_occurrence
from .dist_feature import (
    TieredShardedFeature,
    HostColdStore,
    _dedup_scatter_back,
    exchange_gather,
    exchange_gather_hot,
    exchange_gather_xy,
    route_cold_requests,
)
from ..obs import metrics as _metrics
from .dist_sampler import (DistNeighborSampler, _topology_choice,
                           dist_sample_multi_hop, exchange_byte_model,
                           hier_request_cap, mesh_axis_sizes,
                           resolve_mesh_axes)
from .sharding import ShardedFeature, ShardedGraph


def dist_step_byte_model(nodes_per_shard, num_shards, num_neighbors,
                         batch_size, frontier_cap, feature_dim, axis_name,
                         mesh_shape, route="auto", hier_load_factor=None,
                         elem_bytes=4):
    """Static per-device collective bytes for ONE dist train step.

    Sums :func:`~glt_tpu.parallel.dist_sampler.exchange_byte_model` over
    the step's exchanges — one per sampling hop (id request + fanout
    neighbor/edge-id payload) plus the fused feature+label gather over
    the node capacity — and splits the total by fabric.  Returns
    ``{"ici": bytes, "dcn": bytes, "topology": 'flat'|'hier'}``.  On a
    1-D mesh everything is attributed to ICI (there is no host axis to
    split on); the numbers are what the
    ``glt.dist.collective_bytes{axis=}`` counters accumulate per step.
    """
    from ..sampler.neighbor_sampler import hop_widths, max_sampled_nodes

    topo = _topology_choice(route, axis_name, mesh_shape)
    if isinstance(axis_name, str) or mesh_shape is None:
        h, c = 1, int(num_shards)
    else:
        h, c = int(mesh_shape[0]), int(mesh_shape[1])
    widths = hop_widths(batch_size, list(num_neighbors), frontier_cap)
    node_cap = max_sampled_nodes(batch_size, list(num_neighbors),
                                 frontier_cap)
    ici = dcn = 0
    for w, fo in zip(widths, num_neighbors):
        hc = hier_request_cap(w, c, nodes_per_shard, hier_load_factor)
        i, d = exchange_byte_model(topo, h, c, w, 2 * fo, hier_cap=hc,
                                   elem_bytes=elem_bytes)
        ici += i
        dcn += d
    hc = hier_request_cap(node_cap, c, nodes_per_shard, hier_load_factor)
    i, d = exchange_byte_model(topo, h, c, node_cap, feature_dim + 1,
                               hier_cap=hc, elem_bytes=elem_bytes)
    return {"ici": ici + i, "dcn": dcn + d, "topology": topo}


def _byte_counters(byte_model):
    """The per-axis collective byte counters a step increments per call."""
    c_ici = _metrics.counter(
        "glt.dist.collective_bytes",
        "static per-device collective bytes moved by dist train steps, "
        "split by fabric (from the routing plan's shapes)",
        labels={"axis": "ici"})
    c_dcn = _metrics.counter(
        "glt.dist.collective_bytes",
        "static per-device collective bytes moved by dist train steps, "
        "split by fabric (from the routing plan's shapes)",
        labels={"axis": "dcn"})

    def record(steps=1):
        c_ici.inc(float(byte_model["ici"] * steps))
        c_dcn.inc(float(byte_model["dcn"] * steps))
    return record


def _gather_xy_local(node, rows, labels_blk, f, g, axis_name,
                     dedup_gather, route, fused, fuse_xy,
                     fused_frontier="off", mesh_shape=None,
                     hier_load_factor=None):
    """Per-shard feature+label gather for one sampled node list — the
    shared body of the serial and scanned dist train steps (one routing
    plan + one payload collective when the id spaces agree).
    ``fused_frontier`` selects the serving-side fused dedup+gather kernel
    on the FEATURE exchange (label columns are 1-wide — nothing to fuse);
    bit-identical either way.  ``mesh_shape``/``hier_load_factor``
    select the hierarchical topology on a 2-D mesh (tuple
    ``axis_name``); bit-identical to flat."""
    if fuse_xy:
        x, y = exchange_gather_xy(
            node, rows, labels_blk, f.nodes_per_shard, f.num_shards,
            axis_name, dedup=dedup_gather, route=route, fused=fused,
            fused_frontier=fused_frontier, mesh_shape=mesh_shape,
            hier_load_factor=hier_load_factor)
    elif dedup_gather:
        # ONE unique pass feeds both exchanges; rows/labels scatter
        # back to every original position (bit-identical batch).
        uniq, inv, _ = unique_first_occurrence(node)
        x = _dedup_scatter_back(
            exchange_gather(uniq, rows, f.nodes_per_shard,
                            f.num_shards, axis_name, route=route,
                            fused_frontier=fused_frontier,
                            mesh_shape=mesh_shape,
                            hier_load_factor=hier_load_factor),
            inv)
        y = _dedup_scatter_back(
            exchange_gather(uniq, labels_blk[:, None].astype(jnp.int32),
                            g.nodes_per_shard, g.num_shards, axis_name,
                            route=route, mesh_shape=mesh_shape,
                            hier_load_factor=hier_load_factor),
            inv)[:, 0]
    else:
        x = exchange_gather(node, rows, f.nodes_per_shard,
                            f.num_shards, axis_name, route=route,
                            fused_frontier=fused_frontier,
                            mesh_shape=mesh_shape,
                            hier_load_factor=hier_load_factor)
        y = exchange_gather(node,
                            labels_blk[:, None].astype(jnp.int32),
                            g.nodes_per_shard, g.num_shards,
                            axis_name, route=route,
                            mesh_shape=mesh_shape,
                            hier_load_factor=hier_load_factor)[:, 0]
    return x, jnp.where(node >= 0, y, PADDING_ID)


def make_dist_train_step(
    model,
    tx,
    g: ShardedGraph,
    f: ShardedFeature,
    labels: jnp.ndarray,          # [S, nodes_per_shard] int labels
    mesh: Mesh,
    num_neighbors: Sequence[int],
    batch_size: int,
    axis_name: Optional[str] = None,
    frontier_cap: Optional[int] = None,
    last_hop_dedup: bool = True,
    exchange_load_factor: Optional[float] = None,
    dedup_gather: bool = False,
    route: str = "auto",
    fused: Optional[bool] = None,
    fused_frontier: str = "off",
    hier_load_factor: Optional[float] = None,
):
    """Build ``step(state, seeds [S, B], key) -> (state, loss, acc)``.

    ``seeds`` carries one seed batch per shard (the per-rank disjoint seed
    split of dist_train_sage_supervised.py:76); params/opt state are
    replicated; gradients are ``pmean``-ed across the mesh.
    ``last_hop_dedup=False`` selects the leaf-block final hop (see
    NeighborSampler) — loss/acc are over seed rows, which stay in the
    compact interior prefix, so the objective is unchanged.
    ``exchange_load_factor`` bounds the sampler's all-to-all buckets (see
    :func:`~glt_tpu.parallel.dist_sampler.dist_sample_multi_hop`).
    ``dedup_gather`` routes unique node ids through the feature/label
    exchange (one unique pass shared by both) and scatters rows back —
    bit-identical batches, duplicated ids cross the ICI once; pair it
    with ``last_hop_dedup=False``, whose leaf blocks repeat hub nodes.
    ``route`` / ``fused`` select the routing implementation and fused
    collectives (see :mod:`~glt_tpu.parallel.dist_sampler`): features +
    labels ride ONE routing plan and ONE payload collective
    (:func:`~glt_tpu.parallel.dist_feature.exchange_gather_xy`).
    ``fused_frontier`` != 'off' serves each shard's landed feature
    requests through the one-dispatch dedup+gather kernel inside
    shard_map (sampling stays per-shard local; see
    :func:`~glt_tpu.parallel.dist_feature._request_rows`).

    ``axis_name=None`` resolves to the mesh's own axes — the 1-D
    ``global_mesh`` name or the 2-D ``global_mesh_2d`` tuple.  On a 2-D
    mesh the step runs both sampling hops and the gather over the
    hierarchical dedup-then-exchange topology when ``route`` resolves
    'hier' (bit-identical to 'flat'); ``hier_load_factor`` bounds the
    DCN leg (see :func:`~glt_tpu.parallel.dist_sampler.
    hier_request_cap`).  The returned step carries its static
    ``step.collective_bytes`` ICI/DCN byte model and feeds the
    ``glt.dist.collective_bytes{axis=}`` counters per call.
    """
    axis_name = resolve_mesh_axes(mesh, axis_name)
    mesh_shape = mesh_axis_sizes(mesh, axis_name)
    gspec = P(axis_name)
    # Feature/label fusion needs one id space for both (always true for
    # shard_graph/shard_feature over the same node set).
    fuse_xy = (f.nodes_per_shard == g.nodes_per_shard
               and f.num_shards == g.num_shards)
    byte_model = dist_step_byte_model(
        g.nodes_per_shard, g.num_shards, num_neighbors, batch_size,
        frontier_cap, f.rows.shape[-1], axis_name, mesh_shape,
        route=route, hier_load_factor=hier_load_factor)
    record_bytes = _byte_counters(byte_model)

    def local_body(indptr, indices, edge_ids, rows, labels_blk, seeds,
                   params, key):
        indptr, indices, edge_ids = indptr[0], indices[0], edge_ids[0]
        rows, labels_blk, seeds = rows[0], labels_blk[0], seeds[0]
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

        out = dist_sample_multi_hop(
            indptr, indices, edge_ids, seeds, key, num_neighbors,
            g.nodes_per_shard, g.num_shards, axis_name, frontier_cap,
            last_hop_dedup=last_hop_dedup,
            exchange_load_factor=exchange_load_factor,
            route=route, fused=fused, mesh_shape=mesh_shape,
            hier_load_factor=hier_load_factor)
        # ONE routing plan + ONE payload collective for features AND
        # labels when the id spaces agree (dedup additionally shares a
        # single unique pass) — see _gather_xy_local.
        x, y = _gather_xy_local(out.node, rows, labels_blk, f, g,
                                axis_name, dedup_gather, route, fused,
                                fuse_xy, fused_frontier,
                                mesh_shape=mesh_shape,
                                hier_load_factor=hier_load_factor)
        edge_index = jnp.stack([out.row, out.col])

        def loss_fn(p):
            logits = model.apply(p, x, edge_index, out.edge_mask,
                                 train=True, rngs={"dropout": key})
            return seed_cross_entropy(logits, y, batch_size, out.node_mask)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        grads = lax.pmean(grads, axis_name)
        loss = lax.pmean(loss, axis_name)
        acc = lax.pmean(acc, axis_name)
        return loss, acc, grads

    shard_fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(gspec, gspec, gspec, gspec, gspec, gspec, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)

    # The sharded graph/feature/label arrays ride as jit ARGUMENTS, not
    # closure captures: multi-host global arrays span non-addressable
    # devices and may not be closed over.
    @jax.jit
    def _step(indptr, indices, edge_ids, rows, labels_blk,
              state: TrainState, seeds: jnp.ndarray, key: jax.Array):
        loss, acc, grads = shard_fn(indptr, indices, edge_ids,
                                    rows, labels_blk, seeds, state.params,
                                    key)

        def apply(s):
            updates, opt_state = tx.update(grads, s.opt_state, s.params)
            params = optax.apply_updates(s.params, updates)
            return TrainState(params, opt_state, s.step + 1)

        # A fully-padded batch must not move a stateful optimizer or the
        # step counter (same gating as the scanned step): every exchange
        # carries only -1 slots over both fabrics, so the step is a
        # global no-op, not a momentum-only Adam update.
        nvalid = jnp.sum((seeds >= 0).astype(jnp.int32))
        new_state = jax.lax.cond(nvalid > 0, apply, lambda s: s, state)
        return new_state, loss, acc

    def step(state: TrainState, seeds: jnp.ndarray, key: jax.Array):
        record_bytes()
        return _step(g.indptr, g.indices, g.edge_ids, f.rows, labels,
                     state, seeds, key)

    step.collective_bytes = byte_model
    return step


def make_scanned_dist_train_step(
    model,
    tx,
    g: ShardedGraph,
    f: ShardedFeature,
    labels: jnp.ndarray,          # [S, nodes_per_shard] int labels
    mesh: Mesh,
    num_neighbors: Sequence[int],
    batch_size: int,
    axis_name: Optional[str] = None,
    frontier_cap: Optional[int] = None,
    last_hop_dedup: bool = True,
    exchange_load_factor: Optional[float] = None,
    dedup_gather: bool = False,
    route: str = "auto",
    fused: Optional[bool] = None,
    fused_frontier: str = "off",
    hier_load_factor: Optional[float] = None,
):
    """ONE jitted program trains ``G`` consecutive distributed batches.

    The fused-epoch shape of :func:`make_dist_train_step` (the dist
    analog of ``models.train.make_scanned_node_train_step``): per scan
    slot — all-to-all multi-hop sampling, fused feature+label exchange,
    fwd/bwd, gradient ``pmean``, optimizer update — under ``lax.scan``
    INSIDE one ``shard_map`` program, so intermediate ids and the
    updated replicated state never round-trip through host dispatch
    between batches.  BENCH_r05 measured the serial dist step at
    62.6 ms vs 51.9 ms single-device — most of the gap is per-batch
    dispatch + state re-feed that the scan amortises across ``G``.

    Returns ``step(state, seeds_blk [G, S, B], key) -> (state,
    losses [G], accs [G])``.  Per-slot keys follow the homo scan
    convention (``jax.random.split(key, G)``, then the per-shard
    ``fold_in(axis_index)`` of the serial step), and a fully padded
    slot (every shard's seeds all ``-1``) is an exact no-op — params,
    opt state, and the step counter hold, so a padded trailing block
    equals the serial loop over real batches only.

    ``fused_frontier`` != 'off' routes the per-shard feature serving of
    every scan slot through the fused dedup+gather kernel (sampling
    stays per-shard local; the kernel runs inside shard_map and compiles
    under the scanned dist program's compilewatch label); bit-identical
    batches, VMEM-overflowing request blocks fall back to the unfused
    serve.

    On a 2-D mesh (``axis_name=None`` resolves the tuple) the scan body
    traces the hierarchical exchange ONCE — the topology choice is
    static, so scanning over ``dist_seed_blocks`` recompiles nothing.
    """
    axis_name = resolve_mesh_axes(mesh, axis_name)
    mesh_shape = mesh_axis_sizes(mesh, axis_name)
    gspec = P(axis_name)
    blkspec = P(None, axis_name)
    fuse_xy = (f.nodes_per_shard == g.nodes_per_shard
               and f.num_shards == g.num_shards)
    byte_model = dist_step_byte_model(
        g.nodes_per_shard, g.num_shards, num_neighbors, batch_size,
        frontier_cap, f.rows.shape[-1], axis_name, mesh_shape,
        route=route, hier_load_factor=hier_load_factor)
    record_bytes = _byte_counters(byte_model)

    def local_body(indptr, indices, edge_ids, rows, labels_blk,
                   seeds_blk, state: TrainState, keys):
        indptr, indices, edge_ids = indptr[0], indices[0], edge_ids[0]
        rows, labels_blk = rows[0], labels_blk[0]
        seeds_blk = seeds_blk[:, 0]          # [G, B] local slice
        me = lax.axis_index(axis_name)

        def body(carry, inp):
            st, = carry
            seeds, k = inp
            key = jax.random.fold_in(k, me)
            out = dist_sample_multi_hop(
                indptr, indices, edge_ids, seeds, key, num_neighbors,
                g.nodes_per_shard, g.num_shards, axis_name, frontier_cap,
                last_hop_dedup=last_hop_dedup,
                exchange_load_factor=exchange_load_factor,
                route=route, fused=fused, mesh_shape=mesh_shape,
                hier_load_factor=hier_load_factor)
            x, y = _gather_xy_local(out.node, rows, labels_blk, f, g,
                                    axis_name, dedup_gather, route,
                                    fused, fuse_xy, fused_frontier,
                                    mesh_shape=mesh_shape,
                                    hier_load_factor=hier_load_factor)
            edge_index = jnp.stack([out.row, out.col])

            def loss_fn(p):
                logits = model.apply(p, x, edge_index, out.edge_mask,
                                     train=True, rngs={"dropout": key})
                return seed_cross_entropy(logits, y, batch_size,
                                          out.node_mask)

            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(st.params)
            grads = lax.pmean(grads, axis_name)
            loss = lax.pmean(loss, axis_name)
            acc = lax.pmean(acc, axis_name)

            def apply(s):
                updates, opt_state = tx.update(grads, s.opt_state,
                                               s.params)
                params = optax.apply_updates(s.params, updates)
                return TrainState(params, opt_state, s.step + 1)

            # Fully-padded slots must not move a stateful optimizer or
            # the step counter (same gating as the homo scanned step);
            # the predicate is a global count so every shard takes the
            # same branch.
            nvalid = lax.psum(jnp.sum((seeds >= 0).astype(jnp.int32)),
                              axis_name)
            st = jax.lax.cond(nvalid > 0, apply, lambda s: s, st)
            return (st,), (loss, acc)

        (state,), (losses, accs) = lax.scan(body, (state,),
                                            (seeds_blk, keys))
        return state, losses, accs

    shard_fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(gspec, gspec, gspec, gspec, gspec, blkspec, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)

    # Global arrays as jit arguments (multi-host: no closure capture).
    @jax.jit
    def _step(indptr, indices, edge_ids, rows, labels_blk,
              state: TrainState, seeds_blk: jnp.ndarray, key: jax.Array):
        keys = jax.random.split(key, seeds_blk.shape[0])
        return shard_fn(indptr, indices, edge_ids, rows, labels_blk,
                        seeds_blk, state, keys)

    def step(state: TrainState, seeds_blk: jnp.ndarray, key: jax.Array):
        seeds_blk = jnp.asarray(seeds_blk, jnp.int32)
        record_bytes(int(seeds_blk.shape[0]))
        return _step(g.indptr, g.indices, g.edge_ids, f.rows, labels,
                     state, seeds_blk, key)

    step.collective_bytes = byte_model
    return step


def dist_seed_blocks(train_idx, num_shards: int, batch_size: int,
                     group: int, rng):
    """Shuffled ``[G, S, B]`` seed blocks, -1 padded — the epoch feed
    for :func:`make_scanned_dist_train_step` (each scan slot carries one
    disjoint per-shard seed batch; trailing slots may be fully padded
    no-ops)."""
    ids = np.asarray(train_idx)[rng.permutation(len(train_idx))]
    per_block = batch_size * num_shards * group
    for lo in range(0, len(ids), per_block):
        blk = np.full((group, num_shards, batch_size), -1, np.int64)
        chunk = ids[lo: lo + per_block]
        blk.reshape(-1)[: chunk.shape[0]] = chunk
        yield blk


def run_scanned_dist_epoch(step, state, train_idx, num_shards: int,
                           batch_size: int, group: int, rng,
                           base_key, start_block: int = 0,
                           on_block=None):
    """One fused epoch through :func:`make_scanned_dist_train_step`.

    The dist twin of ``models.train.run_scanned_epoch``: shuffles
    ``train_idx`` into ``[G, S, B]`` blocks, drives one program dispatch
    per block, and reduces losses/accs with ONE device concat + ONE host
    fetch.  Returns ``(state, losses [n_real], accs [n_real])`` as host
    numpy; ``n_real`` counts real (non-padded) scan slots.  Block ``i``
    always runs under ``fold_in(base_key, i)`` — pure in its absolute
    position — so ``start_block``/``on_block`` give the same
    bit-identical resume seam as the homo driver.
    """
    blocks = list(dist_seed_blocks(train_idx, num_shards, batch_size,
                                   group, rng))
    n_real = -(-len(train_idx) // (batch_size * num_shards))
    n_real = max(0, n_real - int(start_block) * group)
    losses, accs = [], []
    for i, blk in enumerate(blocks):
        if i < start_block:
            continue
        state, ls, acs = step(state, blk, jax.random.fold_in(base_key, i))
        losses.append(ls)
        accs.append(acs)
        if on_block is not None:
            # The hook may checkpoint: the sync is the point (post-block
            # exact state), not an accidental per-batch round trip.
            # gltlint: disable-next=dispatch-in-epoch-loop
            jax.block_until_ready(state)
            on_block(state, i)
    losses = (np.asarray(jax.device_get(jnp.concatenate(losses)))[:n_real]
              if losses else np.zeros((0,), np.float32))
    accs = (np.asarray(jax.device_get(jnp.concatenate(accs)))[:n_real]
            if accs else np.zeros((0,), np.float32))
    return state, losses, accs


def make_tiered_train_step(
    model,
    tx,
    g: ShardedGraph,
    f: TieredShardedFeature,
    labels: jnp.ndarray,          # [S, nodes_per_shard] int labels
    mesh: Mesh,
    batch_size: int,
    axis_name: Optional[str] = None,
    dedup_gather: bool = False,
    route: str = "auto",
    fused: Optional[bool] = None,
    hier_load_factor: Optional[float] = None,
):
    """Build the train half of the tiered two-stage pipeline.

    Returns ``train(state, out, staged, key) -> (state, loss, acc)``
    where ``out`` is the sample stage's per-shard :class:`SamplerOutput`
    and ``staged = (rows, slots)`` is the COMPACT responder-side cold
    staging: shard ``s``'s ``rows[s] [cold_cap, d]`` hold host-gathered
    cold rows for its incoming request slots ``slots[s]``
    (:func:`route_cold_requests` -> :func:`compact_cold_requests` ->
    :meth:`HostColdStore.serve`), so each pod host stages only rows its
    own shards own and host->device bytes scale with actual cold traffic.
    Hot rows ride the in-jit all-to-all; cold rows are scattered into the
    response leg — the per-row HBM/host split the reference's
    UnifiedTensor makes inside its gather kernel (unified_tensor.cu:48-81).

    ``dedup_gather`` must match the :class:`TieredTrainPipeline`'s flag:
    the staged cold rows are keyed to the (possibly deduped) request
    layout.  The hot feature gather and the label gather share one
    routing plan and one fused payload collective
    (:func:`~glt_tpu.parallel.dist_feature.exchange_gather_xy`) when the
    graph and feature id spaces agree.
    """
    axis_name = resolve_mesh_axes(mesh, axis_name)
    mesh_shape = mesh_axis_sizes(mesh, axis_name)
    gspec = P(axis_name)
    fuse_xy = (f.nodes_per_shard == g.nodes_per_shard
               and f.num_shards == g.num_shards)

    def local_body(hot_rows, labels_blk, out, staged_rows, staged_slots,
                   params, key):
        hot_rows, labels_blk = hot_rows[0], labels_blk[0]
        staged_rows, staged_slots = staged_rows[0], staged_slots[0]
        out = jax.tree.map(lambda x: x[0], out)
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

        if fuse_xy:
            x, y = exchange_gather_xy(
                out.node, hot_rows, labels_blk, f.nodes_per_shard,
                f.num_shards, axis_name, hot_per_shard=f.hot_per_shard,
                staged_rows=staged_rows, staged_slots=staged_slots,
                dedup=dedup_gather, route=route, fused=fused,
                mesh_shape=mesh_shape, hier_load_factor=hier_load_factor)
        else:
            x = exchange_gather_hot(out.node, hot_rows, f.nodes_per_shard,
                                    f.hot_per_shard, f.num_shards,
                                    axis_name, staged_rows=staged_rows,
                                    staged_slots=staged_slots,
                                    dedup=dedup_gather, route=route,
                                    mesh_shape=mesh_shape,
                                    hier_load_factor=hier_load_factor)
            y = exchange_gather(out.node,
                                labels_blk[:, None].astype(jnp.int32),
                                g.nodes_per_shard, g.num_shards, axis_name,
                                dedup=dedup_gather, route=route,
                                mesh_shape=mesh_shape,
                                hier_load_factor=hier_load_factor)[:, 0]
        y = jnp.where(out.node >= 0, y, PADDING_ID)
        edge_index = jnp.stack([out.row, out.col])

        def loss_fn(p):
            logits = model.apply(p, x, edge_index, out.edge_mask,
                                 train=True, rngs={"dropout": key})
            return seed_cross_entropy(logits, y, batch_size, out.node_mask)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        grads = lax.pmean(grads, axis_name)
        loss = lax.pmean(loss, axis_name)
        acc = lax.pmean(acc, axis_name)
        return loss, acc, grads

    shard_fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(gspec, gspec, gspec, gspec, gspec, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)

    # Global arrays as jit arguments (multi-host: no closure capture).
    @jax.jit
    def _train(hot_rows, labels_blk, state: TrainState, out, staged_rows,
               staged_slots, key: jax.Array):
        loss, acc, grads = shard_fn(hot_rows, labels_blk, out, staged_rows,
                                    staged_slots, state.params, key)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss, acc

    def train(state: TrainState, out, staged, key: jax.Array):
        rows, slots = staged
        return _train(f.hot, labels, state, out, rows, slots, key)

    return train


class _ColdStagePipeline:
    """Shared core of the two-stage (sample → host cold gather → train)
    pipelines: staging/gather thread pools, the locked drop-counter
    reduction, the double-buffered epoch loop, and shutdown.  Subclasses
    implement ``_stage_cold_async(out) -> Future[staged]``.

    The cold gather for batch ``k`` runs on a staging thread while the main
    thread trains batch ``k-1`` — steady-state step time ≈
    ``max(device compute, host cold gather)`` rather than their sum, the
    UVA-overlap property of the reference's UnifiedTensor
    (unified_tensor.cu:202-311) recovered at the pipeline level.  A thread
    (not jax async dispatch) carries the overlap so it holds on every
    backend, including the synchronous CPU emulation the tests run on.
    """

    @staticmethod
    def _device_put_copies() -> bool:
        """Whether ``device_put`` of a numpy array COPIES on this backend.

        Host staging buffers may only be reused across batches when the
        device array made from them does not alias the host memory;
        zero-copy backends must fall back to fresh per-batch buffers.
        Probed once: put, mutate the source, compare.  The probe array
        must be LARGE: CPU zero-copy aliasing only engages for
        sufficiently-aligned buffers, and large numpy allocations are
        page-aligned exactly like the real staging buffers — a small
        probe can land on an unaligned pointer and falsely report copy
        semantics.
        """
        src = np.full((1 << 18,), 1.0, np.float32)   # 1 MB, page-aligned
        arr = jax.device_put(src)
        src[:] = 2.0
        return bool((np.asarray(arr) == 1.0).all())

    def _staged_buffer(self, bufs: list, flip: int, inflight: list,
                       shape, dtype) -> np.ndarray:
        """Next staging buffer: reused (after syncing the consumer that
        read it two batches ago) when device_put copies, else fresh."""
        if not self._reuse_staged:
            return np.empty(shape, dtype)
        prev = inflight[flip]
        if prev is not None:
            # The batch that used this buffer fed its rows to the device
            # two iterations ago; wait for that transfer before the
            # overwrite (depth-2 ring + this sync = no aliasing window).
            jax.block_until_ready(prev)
        return bufs[flip]

    def _init_pools(self, stage_threads: Optional[int],
                    name: str) -> None:
        import concurrent.futures
        import os
        import threading

        self._reuse_staged = self._device_put_copies()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"{name}-stage")
        # Gather workers: the host cold gather splits into (shard,
        # row-chunk) work items fanned across this pool (VERDICT r4 #5 —
        # the serial per-process stage dominated papers100M-shape steady
        # state).  numpy fancy indexing releases the GIL, so chunks scale
        # with host cores; a pod host sizes this to its core count.
        self.stage_threads = (max(1, os.cpu_count() or 1)
                              if stage_threads is None
                              else max(1, int(stage_threads)))
        self._gather_pool = (concurrent.futures.ThreadPoolExecutor(
            max_workers=self.stage_threads,
            thread_name_prefix=f"{name}-gather")
            if self.stage_threads > 1 else None)
        self._pending_dropped = []   # unreduced per-batch device counts
        self.dropped_total = 0       # host sum over all staged batches
        self._drop_lock = threading.Lock()  # staging thread vs caller

    def _record_dropped(self, dropped) -> None:
        # Accumulate lazily (device values; reduced on flush) so the
        # documented contract — "raise cold_cap if drops are ever
        # nonzero" — is checkable over a whole epoch without a per-batch
        # host sync.
        with self._drop_lock:
            self._pending_dropped.append(dropped)

    def _maybe_flush_on_stage_thread(self) -> None:
        # Periodic reduction rides the staging thread (it already blocks
        # on the route stage), never the main thread's critical path
        # (advisor r4 finding).
        if len(self._pending_dropped) >= 64:
            self.flush_dropped()

    def flush_dropped(self) -> int:
        """Reduce pending per-batch drop counters into ``dropped_total``."""
        with self._drop_lock:
            pending, self._pending_dropped = self._pending_dropped, []
        total = 0
        for d in pending:
            for leaf in jax.tree_util.tree_leaves(d):
                shards = getattr(leaf, "addressable_shards", None)
                if shards is not None:
                    total += int(sum(np.asarray(sh.data).sum()
                                     for sh in shards))
                else:
                    total += int(np.asarray(leaf).sum())
        with self._drop_lock:
            self.dropped_total += total
        return self.dropped_total

    def run_epoch(self, state: TrainState, seed_batches, key: jax.Array,
                  start_batch: int = 0, on_batch=None, supervisor=None):
        """Drive one epoch; ``seed_batches``: iterable of ``[S, B]`` seeds.

        Returns ``(state, losses, accs)`` (device scalars, unsynced).
        Check ``flush_dropped()`` after the epoch: nonzero means some
        cold requests overflowed the staging capacity and trained on
        zero rows.

        Preemption-safety seam (glt_tpu.ckpt): batch ``i`` always trains
        under keys folded from its absolute position, so resuming with
        ``start_batch=k`` (skipping the first ``k`` batches of a
        deterministic ``split_seeds`` schedule — thread the SAME
        epoch-rng state you checkpointed) replays the identical
        remaining stream.  ``on_batch(state, i)`` fires after each
        trained batch, synced — the checkpoint-cadence hook.
        ``supervisor`` (a :class:`~glt_tpu.distributed.supervisor.
        Supervisor`) is polled at the same boundary; a dead peer raises
        its structured :class:`~glt_tpu.distributed.supervisor.
        PeerDeadError` out of this loop for the caller's
        checkpoint-and-exit.
        """
        from . import multihost

        losses, accs = [], []
        pending = None  # (idx, out, cold future)
        n = 0

        def trained(i, state):
            if on_batch is None and supervisor is None:
                return
            jax.block_until_ready(state)
            if on_batch is not None:
                on_batch(state, i)
            if supervisor is not None:
                supervisor.raise_if_dead()

        for i, seeds in enumerate(seed_batches):
            if i < start_batch:
                continue
            kb = jax.random.fold_in(key, i)
            if not isinstance(seeds, jax.Array):
                # Per-host feed: every process holds the full [S, B] host
                # batch (deterministic split) and contributes its rows.
                # Host-side seeds, not a device fetch — this eager tiered
                # pipeline stages per batch BY DESIGN (the host cold
                # gather is the overlapped stage).
                # gltlint: disable-next=dispatch-in-epoch-loop
                seeds = multihost.feed_seeds(np.asarray(seeds), self.mesh,
                                             self.axis_name)
            out = self.sampler.sample_from_nodes(
                seeds, key=jax.random.fold_in(kb, 1))
            fut = self._stage_cold_async(out)
            if pending is not None:
                state, loss, acc = self.train_step(
                    state, pending[1], pending[2].result(),
                    jax.random.fold_in(kb, 2))
                losses.append(loss)
                accs.append(acc)
                trained(pending[0], state)
            pending = (i, out, fut)
            n = i + 1
        if pending is not None:
            state, loss, acc = self.train_step(
                state, pending[1], pending[2].result(),
                jax.random.fold_in(jax.random.fold_in(key, n), 2))
            losses.append(loss)
            accs.append(acc)
            trained(pending[0], state)
        # Epoch-boundary seam for tier-aware cold stores (glt_tpu.store):
        # a DiskColdStore snapshots + publishes its per-epoch glt.store.*
        # gauges here (bytes_from_dram/disk, hit rate, stage depth).
        pub = getattr(getattr(self, "cold_store", None),
                      "publish_epoch_stats", None)
        if pub is not None:
            pub()
        return state, losses, accs

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        if self._gather_pool is not None:
            self._gather_pool.shutdown(wait=False)
        closer = getattr(getattr(self, "cold_store", None), "close", None)
        if closer is not None:
            closer()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TieredTrainPipeline(_ColdStagePipeline):
    """Homogeneous two-stage pipeline (see :class:`_ColdStagePipeline`):
    jitted sample → host cold gather → jitted train, double-buffered."""

    def __init__(self, sampler: DistNeighborSampler,
                 train_step, f: TieredShardedFeature, mesh: Mesh,
                 axis_name: Optional[str] = None,
                 cold_store: Optional[HostColdStore] = None,
                 cold_cap: Optional[int] = None,
                 stage_threads: Optional[int] = None,
                 dedup_gather: bool = False,
                 route: str = "auto",
                 hier_load_factor: Optional[float] = None):
        from . import multihost
        from .dist_feature import compact_cold_requests

        self.sampler = sampler
        self.train_step = train_step
        self.f = f
        self.mesh = mesh
        axis_name = resolve_mesh_axes(mesh, axis_name)
        mesh_shape = mesh_axis_sizes(mesh, axis_name)
        self.axis_name = axis_name
        # Compact staging capacity: cold rows staged per responder shard
        # per batch.  Worst case is S * node_cap (every request cold and
        # aimed at one shard); the typical per-responder load is ~the
        # node capacity itself, so alpha=2 over it keeps drops rare.
        # Overflowed requests are served as zeros and counted in
        # ``last_dropped`` — raise cold_cap if it is ever nonzero.
        self.cold_cap = (2 * sampler.node_capacity if cold_cap is None
                         else int(cold_cap))
        # This process's contiguous shard block (all shards when
        # single-process); the cold store serves exactly these.
        self._local = multihost.local_shard_range(mesh, axis_name)
        if (cold_store is None and f.cold.shape[1] == 0
                and f.nodes_per_shard > f.hot_per_shard):
            # shard_feature_tiered_from_store leaves ``cold`` as a
            # zero-row placeholder: the cold tier lives on disk.  A
            # defaulted HostColdStore over it would serve silent zero
            # rows for every cold request — refuse instead.
            raise ValueError(
                "TieredShardedFeature has an empty host cold tier but "
                f"{f.nodes_per_shard - f.hot_per_shard} cold rows per "
                "shard — pass the DiskColdStore backing it as "
                "cold_store= (see docs/storage.md)")
        self.cold_store = cold_store or HostColdStore(
            f, shard_ids=self._local)
        self._init_pools(stage_threads, "glt-cold")
        self.last_dropped = None     # [S] device counts, latest batch
        # Observed per-shard cold-row peak — size cold_cap to this (+
        # margin) on a re-run to shrink the host->device feed.
        self.max_cold_rows = 0
        self._staged_bufs = [
            np.empty((len(self._local), self.cold_cap,
                      self.cold_store.dim), self.cold_store.dtype)
            for _ in range(2)]
        self._staged_flip = 0
        self._staged_inflight = [None, None]
        gspec = P(axis_name)

        def route_body(nodes):
            # dedup_gather must match the train step's flag: the staged
            # slots index the (possibly deduped) request layout.
            req = route_cold_requests(
                nodes[0], f.nodes_per_shard, f.hot_per_shard,
                f.num_shards, axis_name, dedup=dedup_gather, route=route,
                mesh_shape=mesh_shape, hier_load_factor=hier_load_factor)
            slots, ids, dropped = compact_cold_requests(req, self.cold_cap)
            return slots[None], ids[None], dropped[None]

        self._route = jax.jit(jax.shard_map(
            route_body, mesh=mesh, in_specs=(gspec,),
            out_specs=(gspec, gspec, gspec), check_vma=False))

    def _stage_cold_async(self, out):
        """Submit the cold staging for ``out.node``; returns a future.

        Route + compact (in-jit all_to_all) -> per-shard host gather of
        ONLY the compacted cold ids -> per-host feed of the
        ``[S, cold_cap, d]`` staged rows + their slot indices.  Each
        process serves only its local shards (all of them in the
        single-process emulation) and feeds only its slab of the global
        staged arrays — remote slabs are produced by their own hosts.
        """
        from . import multihost

        slots, ids, dropped = self._route(out.node)
        self.last_dropped = dropped
        self._record_dropped(dropped)

        def work():
            # Fetch only this host's addressable id rows (waits on the
            # route stage only).
            shards = sorted(ids.addressable_shards,
                            key=lambda sh: sh.index[0].start or 0)
            req = np.concatenate([np.asarray(sh.data) for sh in shards])
            # Staging buffer, never zeroed: rows at -1 slots are garbage
            # but the compact scatter drops them (exchange_gather_hot
            # mode="drop").  Reused across batches (page-resident) only
            # when device_put provably copies — see _staged_buffer; at
            # papers100M shape the per-batch 100+ MB zeroed alloc was a
            # measurable slice of the stage (VERDICT r4 #5).
            flip = self._staged_flip
            self._staged_flip ^= 1
            staged = self._staged_buffer(
                self._staged_bufs, flip, self._staged_inflight,
                (len(self._local), self.cold_cap, self.cold_store.dim),
                self.cold_store.dtype)
            self.max_cold_rows = max(self.max_cold_rows,
                                     int((req >= 0).sum(axis=1).max()))
            # Fan the gather across (shard, row-chunk) work items.
            futs = []
            for j, s in enumerate(self._local):
                futs += self.cold_store.serve_into(
                    staged[j], s, req[j], pool=self._gather_pool)
            for fu in futs:
                fu.result()
            self._maybe_flush_on_stage_thread()
            rows = multihost.assemble_global(staged, self.mesh,
                                             self.axis_name)
            self._staged_inflight[flip] = rows
            return rows, slots
        return self._pool.submit(work)


def init_dist_state(model, tx, g: ShardedGraph, f,
                    rng: jax.Array, num_neighbors: Sequence[int],
                    batch_size: int,
                    frontier_cap: Optional[int] = None) -> TrainState:
    """Initialize replicated params/opt-state with correctly-shaped dummies.

    ``f`` may be a :class:`ShardedFeature` or
    :class:`~glt_tpu.parallel.dist_feature.TieredShardedFeature`.
    """
    from ..sampler.neighbor_sampler import hop_widths, max_sampled_nodes

    cap = max_sampled_nodes(batch_size, list(num_neighbors), frontier_cap)
    widths = hop_widths(batch_size, list(num_neighbors), frontier_cap)
    ecap = sum(w * fo for w, fo in zip(widths, num_neighbors))

    rows = f.hot if isinstance(f, TieredShardedFeature) else f.rows
    x = jnp.zeros((cap, rows.shape[-1]), rows.dtype)
    ei = jnp.full((2, ecap), PADDING_ID, jnp.int32)
    mask = jnp.zeros((ecap,), bool)
    params = model.init({"params": rng}, x, ei, mask)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))


def make_hetero_dist_train_step(
    model,
    tx,
    sampler,                      # DistHeteroNeighborSampler
    feats,                        # Dict[NodeType, ShardedFeature]
    labels: jnp.ndarray,          # [S, c_target] target-type labels
    mesh: Mesh,
    batch_size: int,
    axis_name: Optional[str] = None,
    route: str = "auto",
    fused: Optional[bool] = None,
    hier_load_factor: Optional[float] = None,
):
    """Hetero analog of :func:`make_dist_train_step` (cf. the reference's
    igbh distributed run, examples/igbh/dist_train_rgat.py): hetero
    multi-hop exchange sampling, per-node-type all-to-all feature gather,
    R-GAT forward/backward, gradient pmean — one XLA program.

    ``model.edge_types`` must use the sampler's *reversed* output keys
    (``reverse_edge_type`` of the dataset's edge types), and
    ``model.target_type`` == ``sampler.input_type``.  The target type's
    feature gather and the label gather share one routing plan + one
    fused payload collective (``exchange_gather_xy``).
    """
    axis_name = resolve_mesh_axes(mesh, axis_name)
    mesh_shape = mesh_axis_sizes(mesh, axis_name)
    gspec = P(axis_name)
    tgt = sampler.input_type
    arrays = {et: (g.indptr, g.indices, g.edge_ids)
              for et, g in sampler.sharded.items()}
    rows = {t: f.rows for t, f in feats.items()}
    meta = {t: (f.nodes_per_shard, f.num_shards) for t, f in feats.items()}
    label_c = int(labels.shape[1])
    num_shards = next(iter(sampler.sharded.values())).num_shards
    fuse_xy = (meta[tgt][0] == label_c and meta[tgt][1] == num_shards)

    def local_body(arrays_blk, rows_blk, labels_blk, seeds_blk, params,
                   key):
        arrays_l = jax.tree.map(lambda a: a[0], arrays_blk)
        rows_l = {t: r[0] for t, r in rows_blk.items()}
        labels_l, seeds = labels_blk[0], seeds_blk[0]
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
        kdrop, ksample = jax.random.split(key)

        out = sampler.local_sample(arrays_l, seeds, ksample)
        x, y = {}, None
        for t in rows_l:
            if t == tgt and fuse_xy:
                x[t], y = exchange_gather_xy(
                    out.node[t], rows_l[t], labels_l, meta[t][0],
                    meta[t][1], axis_name, route=route, fused=fused,
                    mesh_shape=mesh_shape,
                    hier_load_factor=hier_load_factor)
            else:
                x[t] = exchange_gather(out.node[t], rows_l[t], meta[t][0],
                                       meta[t][1], axis_name, route=route,
                                       mesh_shape=mesh_shape,
                                       hier_load_factor=hier_load_factor)
        if y is None:
            y = exchange_gather(out.node[tgt],
                                labels_l[:, None].astype(jnp.int32),
                                label_c, num_shards, axis_name,
                                route=route, mesh_shape=mesh_shape,
                                hier_load_factor=hier_load_factor)[:, 0]
        y = jnp.where(out.node[tgt] >= 0, y, PADDING_ID)
        edge_index = {et: jnp.stack([out.row[et], out.col[et]])
                      for et in out.row}

        def loss_fn(prm):
            logits = model.apply(prm, x, edge_index, out.edge_mask,
                                 train=True, rngs={"dropout": kdrop})
            return seed_cross_entropy(logits, y, batch_size,
                                      out.node_mask[tgt])

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        grads = lax.pmean(grads, axis_name)
        loss = lax.pmean(loss, axis_name)
        acc = lax.pmean(acc, axis_name)
        return loss, acc, grads

    arr_specs = jax.tree.map(lambda _: gspec, arrays)
    row_specs = {t: gspec for t in rows}
    shard_fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(arr_specs, row_specs, gspec, gspec, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)

    # Global arrays as jit arguments (multi-host: no closure capture).
    @jax.jit
    def _step(arrays_arg, rows_arg, labels_blk, state: TrainState,
              seeds: jnp.ndarray, key: jax.Array):
        loss, acc, grads = shard_fn(arrays_arg, rows_arg, labels_blk,
                                    seeds, state.params, key)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss, acc

    def step(state: TrainState, seeds: jnp.ndarray, key: jax.Array):
        return _step(arrays, rows, labels, state, seeds, key)

    return step


def make_hetero_tiered_train_step(
    model,
    tx,
    sampler,                      # DistHeteroNeighborSampler
    feats,                        # Dict[NodeType, Sharded|TieredSharded]
    labels: jnp.ndarray,          # [S, c_target] target-type labels
    mesh: Mesh,
    batch_size: int,
    axis_name: Optional[str] = None,
    route: str = "auto",
    fused: Optional[bool] = None,
    hier_load_factor: Optional[float] = None,
):
    """Hetero analog of :func:`make_tiered_train_step` (VERDICT r4 #4):
    node types whose feature is a :class:`TieredShardedFeature` (e.g.
    IGBH paper features, ~350 GB — far past a v5e-16's HBM) gather their
    hot prefix in-jit and take cold rows from compact host staging;
    full-HBM types use the plain exchange.  Sampling happens OUTSIDE
    (two-stage pipeline: see :class:`HeteroTieredTrainPipeline`), exactly
    like the homo tiered step.

    Returns ``train(state, out, staged, key)`` with ``staged`` a dict
    ``{node_type: (rows [S, cold_cap, d], slots [S, cold_cap])}`` for the
    tiered types only.
    """
    axis_name = resolve_mesh_axes(mesh, axis_name)
    mesh_shape = mesh_axis_sizes(mesh, axis_name)
    gspec = P(axis_name)
    tgt = sampler.input_type
    tiered = sorted(t for t, f in feats.items()
                    if isinstance(f, TieredShardedFeature))
    hot_rows = {t: (f.hot if isinstance(f, TieredShardedFeature)
                    else f.rows) for t, f in feats.items()}
    meta = {t: (f.nodes_per_shard,
                (f.hot_per_shard if isinstance(f, TieredShardedFeature)
                 else f.nodes_per_shard),
                f.num_shards) for t, f in feats.items()}
    label_c = int(labels.shape[1])
    num_shards = next(iter(sampler.sharded.values())).num_shards
    fuse_xy = (meta[tgt][0] == label_c and meta[tgt][2] == num_shards)

    def local_body(hot_blk, labels_blk, out, srows_blk, sslots_blk, params,
                   key):
        hot_l = {t: r[0] for t, r in hot_blk.items()}
        labels_l = labels_blk[0]
        srows = {t: r[0] for t, r in srows_blk.items()}
        sslots = {t: r[0] for t, r in sslots_blk.items()}
        out = jax.tree.map(lambda x: x[0], out)
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

        x, y = {}, None
        for t in hot_l:
            c, h, s = meta[t]
            if t == tgt and fuse_xy:
                # Target-type features (hot tier + staged cold when
                # tiered) and labels ride one routing plan + one fused
                # payload collective.
                x[t], y = exchange_gather_xy(
                    out.node[t], hot_l[t], labels_l, c, s, axis_name,
                    hot_per_shard=h, staged_rows=srows.get(t),
                    staged_slots=sslots.get(t), route=route, fused=fused,
                    mesh_shape=mesh_shape,
                    hier_load_factor=hier_load_factor)
            elif t in srows:
                x[t] = exchange_gather_hot(out.node[t], hot_l[t], c, h, s,
                                           axis_name,
                                           staged_rows=srows[t],
                                           staged_slots=sslots[t],
                                           route=route,
                                           mesh_shape=mesh_shape,
                                           hier_load_factor=hier_load_factor)
            else:
                x[t] = exchange_gather(out.node[t], hot_l[t], c, s,
                                       axis_name, route=route,
                                       mesh_shape=mesh_shape,
                                       hier_load_factor=hier_load_factor)
        if y is None:
            y = exchange_gather(out.node[tgt],
                                labels_l[:, None].astype(jnp.int32),
                                label_c, num_shards, axis_name,
                                route=route, mesh_shape=mesh_shape,
                                hier_load_factor=hier_load_factor)[:, 0]
        y = jnp.where(out.node[tgt] >= 0, y, PADDING_ID)
        edge_index = {et: jnp.stack([out.row[et], out.col[et]])
                      for et in out.row}

        def loss_fn(prm):
            logits = model.apply(prm, x, edge_index, out.edge_mask,
                                 train=True, rngs={"dropout": key})
            return seed_cross_entropy(logits, y, batch_size,
                                      out.node_mask[tgt])

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        grads = lax.pmean(grads, axis_name)
        loss = lax.pmean(loss, axis_name)
        acc = lax.pmean(acc, axis_name)
        return loss, acc, grads

    hot_specs = {t: gspec for t in hot_rows}
    st_specs = {t: gspec for t in tiered}
    shard_fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(hot_specs, gspec, gspec, st_specs, st_specs, P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False)

    @jax.jit
    def _train(hot_arg, labels_blk, state: TrainState, out, srows, sslots,
               key: jax.Array):
        loss, acc, grads = shard_fn(hot_arg, labels_blk, out, srows,
                                    sslots, state.params, key)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss, acc

    def train(state: TrainState, out, staged, key: jax.Array):
        srows = {t: staged[t][0] for t in tiered}
        sslots = {t: staged[t][1] for t in tiered}
        return _train(hot_rows, labels, state, out, srows, sslots, key)

    return train


class HeteroTieredTrainPipeline(_ColdStagePipeline):
    """Hetero two-stage pipeline: jitted hetero sample → per-type host
    cold gather → jitted hetero train, double-buffered.

    The hetero twin of :class:`TieredTrainPipeline` (VERDICT r4 #4): each
    tiered node type routes + compacts its own cold requests (one jitted
    shard_map over the dict), the host gathers each type's compact id
    list (row-chunk parallel across ``stage_threads``), and the train
    step scatters every type's staged rows into its gather response.
    """

    def __init__(self, sampler, train_step, feats, mesh: Mesh,
                 axis_name: Optional[str] = None,
                 cold_caps=None,
                 stage_threads: Optional[int] = None,
                 route: str = "auto",
                 hier_load_factor: Optional[float] = None):
        from . import multihost
        from .dist_feature import compact_cold_requests

        self.sampler = sampler
        self.train_step = train_step
        self.mesh = mesh
        axis_name = resolve_mesh_axes(mesh, axis_name)
        mesh_shape = mesh_axis_sizes(mesh, axis_name)
        self.axis_name = axis_name
        self.tiered = {t: f for t, f in feats.items()
                       if isinstance(f, TieredShardedFeature)}
        cap_by_type = sampler.node_capacity
        self.cold_cap = {
            t: (2 * max(cap_by_type.get(t, 1), 1)
                if not cold_caps or t not in cold_caps else int(cold_caps[t]))
            for t in self.tiered}
        self._local = multihost.local_shard_range(mesh, axis_name)
        self.stores = {t: HostColdStore(f, shard_ids=self._local)
                       for t, f in self.tiered.items()}
        self._init_pools(stage_threads, "glt-hcold")
        # Per-type reused double buffers (see TieredTrainPipeline).
        self._staged_bufs = {
            t: [np.empty((len(self._local), self.cold_cap[t],
                          self.stores[t].dim), self.stores[t].dtype)
                for _ in range(2)]
            for t in self.tiered}
        self._staged_flip = 0
        self._staged_inflight = {t: [None, None] for t in self.tiered}
        self.max_cold_rows = {t: 0 for t in self.tiered}
        gspec = P(axis_name)
        tiered_types = sorted(self.tiered)

        def route_body(nodes_blk):
            slots, ids, dropped = {}, {}, {}
            for t in tiered_types:
                f = self.tiered[t]
                req = route_cold_requests(
                    nodes_blk[t][0], f.nodes_per_shard, f.hot_per_shard,
                    f.num_shards, axis_name, route=route,
                    mesh_shape=mesh_shape,
                    hier_load_factor=hier_load_factor)
                s, i, d = compact_cold_requests(req, self.cold_cap[t])
                slots[t], ids[t], dropped[t] = s[None], i[None], d[None]
            return slots, ids, dropped

        tspec = {t: gspec for t in tiered_types}
        self._route = jax.jit(jax.shard_map(
            route_body, mesh=mesh, in_specs=({t: gspec for t in tiered_types},),
            out_specs=(tspec, tspec, tspec), check_vma=False))

    def _stage_cold_async(self, out):
        from . import multihost

        nodes = {t: out.node[t] for t in self.tiered}
        slots, ids, dropped = self._route(nodes)
        self._record_dropped(dropped)

        def work():
            staged = {}
            futs = []
            arrs = {}
            flip = self._staged_flip
            self._staged_flip ^= 1
            for t in sorted(self.tiered):
                shards = sorted(ids[t].addressable_shards,
                                key=lambda sh: sh.index[0].start or 0)
                req = np.concatenate([np.asarray(sh.data)
                                      for sh in shards])
                st = self.stores[t]
                arr = self._staged_buffer(
                    self._staged_bufs[t], flip, self._staged_inflight[t],
                    (len(self._local), self.cold_cap[t], st.dim),
                    st.dtype)
                self.max_cold_rows[t] = max(
                    self.max_cold_rows[t],
                    int((req >= 0).sum(axis=1).max()))
                for j, s in enumerate(self._local):
                    futs += st.serve_into(arr[j], s, req[j],
                                          pool=self._gather_pool)
                arrs[t] = arr
            for fu in futs:
                fu.result()
            self._maybe_flush_on_stage_thread()
            for t, arr in arrs.items():
                rows = multihost.assemble_global(arr, self.mesh,
                                                 self.axis_name)
                self._staged_inflight[t][flip] = rows
                staged[t] = (rows, slots[t])
            return staged
        return self._pool.submit(work)


def init_hetero_dist_state(model, tx, sampler, feats,
                           rng: jax.Array) -> TrainState:
    """Replicated params/opt-state from the sampler's static shapes.

    ``feats`` values may be :class:`ShardedFeature` or
    :class:`TieredShardedFeature`."""
    from ..models.train import hetero_init_shapes

    def _rows(f):
        return f.hot if isinstance(f, TieredShardedFeature) else f.rows

    x, ei, mask = hetero_init_shapes(sampler, feats, _rows)
    params = model.init({"params": rng}, x, ei, mask)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))
