from .sharding import ShardedGraph, ShardedFeature, shard_graph, shard_feature
from .dist_sampler import (
    DistNeighborSampler,
    dist_sample_multi_hop,
    exchange_one_hop,
)
from .dist_feature import exchange_gather
from .dist_hetero_sampler import DistHeteroNeighborSampler, shard_hetero_graph
from .dist_train import init_dist_state, make_dist_train_step

__all__ = [
    "DistHeteroNeighborSampler",
    "DistNeighborSampler",
    "shard_hetero_graph",
    "ShardedFeature",
    "ShardedGraph",
    "dist_sample_multi_hop",
    "exchange_gather",
    "exchange_one_hop",
    "init_dist_state",
    "make_dist_train_step",
    "shard_feature",
    "shard_graph",
]
