from .sharding import ShardedGraph, ShardedFeature, shard_graph, shard_feature
from .dist_sampler import DistNeighborSampler, exchange_one_hop
from .dist_feature import exchange_gather

__all__ = [
    "DistNeighborSampler",
    "ShardedFeature",
    "ShardedGraph",
    "exchange_gather",
    "exchange_one_hop",
    "shard_feature",
    "shard_graph",
]
