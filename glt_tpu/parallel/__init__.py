from .. import compat  # noqa: F401  (installs the jax.shard_map shim)
from . import multihost
from .sharding import (
    ShardedGraph,
    ShardedFeature,
    shard_bounds,
    shard_graph,
    shard_graph_blocks,
    shard_feature,
)
from .dist_sampler import (
    DistNeighborSampler,
    bounded_remote_cap,
    build_sorted_edge_view,
    dist_edge_exists,
    dist_node_subgraph,
    dist_sample_multi_hop,
    exchange_one_hop,
)
from .dist_feature import (
    TieredShardedFeature,
    HostColdStore,
    cold_gather_host,
    compact_cold_requests,
    route_cold_requests,
    exchange_gather,
    exchange_gather_hot,
    shard_feature_tiered,
)
from .dist_hetero_sampler import DistHeteroNeighborSampler, shard_hetero_graph
from .dist_train import (
    HeteroTieredTrainPipeline,
    TieredTrainPipeline,
    init_dist_state,
    init_hetero_dist_state,
    make_dist_train_step,
    make_hetero_dist_train_step,
    make_hetero_tiered_train_step,
    make_tiered_train_step,
)

__all__ = [
    "HeteroTieredTrainPipeline",
    "make_hetero_tiered_train_step",
    "DistHeteroNeighborSampler",
    "DistNeighborSampler",
    "bounded_remote_cap",
    "build_sorted_edge_view",
    "compact_cold_requests",
    "dist_edge_exists",
    "multihost",
    "shard_bounds",
    "shard_graph_blocks",
    "shard_hetero_graph",
    "ShardedFeature",
    "ShardedGraph",
    "TieredShardedFeature",
    "TieredTrainPipeline",
    "HostColdStore",
    "cold_gather_host",
    "route_cold_requests",
    "dist_sample_multi_hop",
    "exchange_gather",
    "exchange_gather_hot",
    "exchange_one_hop",
    "init_dist_state",
    "init_hetero_dist_state",
    "make_dist_train_step",
    "make_hetero_dist_train_step",
    "make_tiered_train_step",
    "shard_feature",
    "shard_feature_tiered",
    "shard_graph",
]
