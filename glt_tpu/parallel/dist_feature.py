"""Distributed feature lookup: all-to-all row exchange inside shard_map.

TPU-native replacement for ``distributed/dist_feature.py:122-269``: the
reference masks ids through the feature partition book, gathers local rows
from the UnifiedTensor, issues per-remote-partition async RPCs
(``RpcFeatureLookupCallee``) and scatter-stitches responses into the output
buffer.  Here the whole lookup is one collective round-trip: bucket ids by
owner shard, ``all_to_all`` the id buckets, every shard gathers its rows
from HBM, ``all_to_all`` the row blocks back, unscatter.  Payload rides ICI
and overlaps with neighboring compute under XLA's scheduler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .dist_sampler import _bucket_by_owner


def exchange_gather(
    ids: jnp.ndarray,
    rows: jnp.ndarray,
    nodes_per_shard: int,
    num_shards: int,
    axis_name: str,
) -> jnp.ndarray:
    """Gather feature rows for global ``ids`` across shards.

    Call inside ``shard_map``. Args:
      ids: ``[B]`` global node ids on this shard (-1 padded -> zero rows).
      rows: ``[nodes_per_shard, d]`` this shard's feature block.

    Returns: ``[B, d]`` rows in input order.
    """
    b = ids.shape[0]
    d = rows.shape[-1]
    owner = jnp.where(ids >= 0, ids // nodes_per_shard, -1)
    routing = _bucket_by_owner(ids, owner, num_shards, cap=b)

    requests = lax.all_to_all(
        routing.buckets.reshape(num_shards, b), axis_name, 0, 0,
        tiled=False).reshape(num_shards * b)

    my_rank = lax.axis_index(axis_name)
    local = requests - my_rank * nodes_per_shard
    ok = (local >= 0) & (local < nodes_per_shard) & (requests >= 0)
    got = jnp.take(rows, jnp.where(ok, local, 0), axis=0, mode="clip")
    got = jnp.where(ok[:, None], got, 0)

    resp = lax.all_to_all(
        got.reshape(num_shards, b, d), axis_name, 0, 0,
        tiled=False).reshape(num_shards * b, d)
    out = resp[jnp.clip(routing.slot, 0, num_shards * b - 1)]
    return jnp.where(routing.valid[:, None], out, 0)
