"""Distributed feature lookup: all-to-all row exchange inside shard_map.

TPU-native replacement for ``distributed/dist_feature.py:122-269``: the
reference masks ids through the feature partition book, gathers local rows
from the UnifiedTensor, issues per-remote-partition async RPCs
(``RpcFeatureLookupCallee``) and scatter-stitches responses into the output
buffer.  Here the whole lookup is one collective round-trip: bucket ids by
owner shard (a :func:`~glt_tpu.parallel.dist_sampler.build_routing` plan,
reusable across exchanges), ``all_to_all`` the id buckets, every shard
gathers its rows from HBM, ``all_to_all`` the row blocks back, unscatter.
:func:`exchange_gather_xy` fuses the feature AND label lookup of a
frontier into ONE such round-trip (labels bitcast into a float32 payload
column — bit-exact).  Payload rides ICI and overlaps with neighboring
compute under XLA's scheduler.

**Host tiering** (:class:`TieredShardedFeature`): when the feature matrix
exceeds mesh HBM (papers100M ≈ 200GB), each shard keeps only a hotness-
ordered prefix of its rows in HBM; the remainder stays in host DRAM.  The
reference reads its host tier through UVA from inside the gather kernel
(unified_tensor.cu:202-311); a TPU kernel cannot read host memory, so the
cold path is a **host-side pipeline stage**: the sampler's node list (known
after the sample stage) drives a numpy gather whose result is
``device_put`` while the previous batch trains — the
:class:`~glt_tpu.parallel.dist_train.TieredTrainPipeline` double-buffers
the two jitted stages so step time approaches
``max(device compute, host gather)``, the same overlap UVA bought the GPU.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.fused_frontier import fused_frontier as _fused_frontier
from ..ops.unique import unique_first_occurrence
from .dist_sampler import (HierarchicalRouting, Routing, _topology_choice,
                           _use_fused, build_hier_routing, build_routing,
                           hier_requests, hier_response)


def _dedup_scatter_back(urows: jnp.ndarray, inv: jnp.ndarray) -> jnp.ndarray:
    """Expand unique-id rows back to every original position (-1 = pad)."""
    out = jnp.take(urows, jnp.clip(inv, 0, inv.shape[0] - 1), axis=0)
    return jnp.where((inv >= 0)[:, None], out, 0)


def _dedup_scatter_back_1d(uvals: jnp.ndarray, inv: jnp.ndarray
                           ) -> jnp.ndarray:
    """1-D analog of :func:`_dedup_scatter_back` (label columns)."""
    out = jnp.take(uvals, jnp.clip(inv, 0, inv.shape[0] - 1))
    return jnp.where(inv >= 0, out, 0)


def _request_rows(rows: jnp.ndarray, local: jnp.ndarray, ok: jnp.ndarray,
                  fused_frontier: str) -> jnp.ndarray:
    """Serving-side row fetch of every exchange: rows for the id
    requests landed on this shard (zeros where ``ok`` is False).

    ``fused_frontier`` != 'off' serves the request block through the
    one-dispatch dedup+gather kernel — the request list repeats hub rows
    across requesting shards, and the fused path reads each distinct row
    from HBM once, out of VMEM thereafter.  Bit-identical to the naive
    take (valid ``local`` needs no clip; invalid positions are -1-masked
    into the kernel's padding path, which zeroes them exactly like the
    ``where``).
    """
    if fused_frontier != "off":
        return _fused_frontier(rows, jnp.where(ok, local, -1),
                               force=fused_frontier).features
    got = jnp.take(rows, jnp.where(ok, local, 0), axis=0, mode="clip")
    return jnp.where(ok[:, None], got, 0)


def _exchange_ids(routing: Routing, num_shards: int, cap: int,
                  axis_name: str) -> jnp.ndarray:
    """The id request all-to-all of every exchange: row q of the result
    holds the ids shard q wants from us."""
    return lax.all_to_all(
        routing.buckets.reshape(num_shards, cap), axis_name, 0, 0,
        tiled=False).reshape(num_shards * cap)


def _resolve_plan(ids, nodes_per_shard, num_shards, axis_name, routing,
                  route, mesh_shape, hier_load_factor):
    """Shared plan prologue of every feature exchange: resolve the
    routing plan — flat :class:`Routing` or 2-D-mesh
    :class:`HierarchicalRouting`, building one when the caller didn't
    pass a shared plan — and run the id-request leg(s).

    Returns ``(routing, flat_plan, requests)``: ``requests`` is the id
    vector this shard must serve (``[S*b]`` flat, ``[H*hier_cap]``
    hier, where the hier DCN leg carries only the per-host-deduped
    ids), and ``flat_plan`` drives the shared unscatter epilogue (the
    hier response retraces its legs back into flat bucket order).
    """
    b = ids.shape[0]
    if routing is None:
        if _topology_choice(route, axis_name, mesh_shape) == "hier":
            routing = build_hier_routing(
                ids, nodes_per_shard, mesh_shape[0], mesh_shape[1],
                axis_name[0], axis_name[1],
                hier_load_factor=hier_load_factor, route=route)
        else:
            routing = build_routing(ids, nodes_per_shard, num_shards,
                                    route=route)
    if isinstance(routing, HierarchicalRouting):
        return routing, routing.base, hier_requests(routing)
    return routing, routing, _exchange_ids(routing, num_shards, b,
                                           axis_name)


def _return_payload(routing, payload, num_shards, b, axis_name):
    """Response leg of every feature exchange: per-request-slot payload
    back to the requesters, landing in flat bucket order
    ``[num_shards * b, w]`` (the hier path retraces DCN then ICI in
    reverse; dropped/padding slots come back as zero rows, exactly what
    the flat path's masked serve produces)."""
    w = payload.shape[-1]
    if isinstance(routing, HierarchicalRouting):
        return hier_response(routing, payload, 0)
    return lax.all_to_all(
        payload.reshape(num_shards, b, w), axis_name, 0, 0,
        tiled=False).reshape(num_shards * b, w)


def exchange_gather(
    ids: jnp.ndarray,
    rows: jnp.ndarray,
    nodes_per_shard: int,
    num_shards: int,
    axis_name: str,
    dedup: bool = False,
    routing=None,
    route: str = "auto",
    fused_frontier: str = "off",
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
) -> jnp.ndarray:
    """Gather feature rows for global ``ids`` across shards.

    Call inside ``shard_map``. Args:
      ids: ``[B]`` global node ids on this shard (-1 padded -> zero rows).
      rows: ``[nodes_per_shard, d]`` this shard's feature block.
      dedup: route UNIQUE ids through the exchange and scatter rows back
        to every original position — duplicated ids (un-deduped leaf
        hops, hub nodes) cross the ICI once instead of once per
        occurrence.  Output is bit-identical to ``dedup=False``.
      routing: pre-built plan for ``ids`` from
        :func:`~glt_tpu.parallel.dist_sampler.build_routing` (or
        :func:`~glt_tpu.parallel.dist_sampler.build_hier_routing` on a
        2-D mesh) — reuse ONE plan across the neighbor/feature/label
        exchanges of a frontier instead of re-bucketing per exchange.
        Ignored under ``dedup`` (the plan there is over the unique id
        list).
      fused_frontier: serving-side kernel seam (see
        :func:`_request_rows`); bit-identical either way.
      mesh_shape: static ``(num_hosts, chips_per_host)`` when
        ``axis_name`` is the 2-D mesh axis tuple — enables the
        hierarchical dedup-then-exchange topology (``route='hier'``).
      hier_load_factor: DCN buffer bound for the hier topology (see
        :func:`~glt_tpu.parallel.dist_sampler.hier_request_cap`).

    Returns: ``[B, d]`` rows in input order.
    """
    if dedup:
        uniq, inv, _ = unique_first_occurrence(ids)
        urows = exchange_gather(uniq, rows, nodes_per_shard, num_shards,
                                axis_name, route=route,
                                fused_frontier=fused_frontier,
                                mesh_shape=mesh_shape,
                                hier_load_factor=hier_load_factor)
        return _dedup_scatter_back(urows, inv)
    b = ids.shape[0]
    routing, flat_plan, requests = _resolve_plan(
        ids, nodes_per_shard, num_shards, axis_name, routing, route,
        mesh_shape, hier_load_factor)

    my_rank = lax.axis_index(axis_name)
    local = requests - my_rank * nodes_per_shard
    ok = (local >= 0) & (local < nodes_per_shard) & (requests >= 0)
    got = _request_rows(rows, local, ok, fused_frontier)

    resp = _return_payload(routing, got, num_shards, b, axis_name)
    out = resp[jnp.clip(flat_plan.slot, 0, num_shards * b - 1)]
    return jnp.where(flat_plan.valid[:, None], out, 0)


class TieredShardedFeature(NamedTuple):
    """Per-shard features split between HBM and host DRAM.

    ``hot``: ``[S, hot_per_shard, d]`` device array (shard axis placed on
    the mesh by ``put_sharded``); ``cold``: ``[S, c - hot_per_shard, d]``
    host numpy.  Row ``r`` of shard ``s`` holds global (relabeled) id
    ``s * c + r`` — use hotness-ordered
    :func:`~glt_tpu.partition.contiguous.contiguous_relabel` so the prefix
    really is the hot set (the ``cat_feature_cache``/``sort_by_in_degree``
    role, reference data/reorder.py:18, partition/base.py:606).
    """
    hot: jnp.ndarray
    cold: np.ndarray
    nodes_per_shard: int
    hot_per_shard: int
    num_shards: int

    @property
    def dim(self) -> int:
        return self.hot.shape[-1]


def shard_feature_tiered(feature: np.ndarray, num_shards: int,
                         hot_ratio: float, dtype=None
                         ) -> TieredShardedFeature:
    """Split ``[N, d]`` rows into per-shard HBM prefix + host remainder."""
    feature = np.asarray(feature)
    n, d = feature.shape
    c = -(-n // num_shards)
    # At least one hot row per shard: downstream exchange_gather_hot and
    # make_tiered_train_step derive shapes/dtype from the hot array, and a
    # [S, 0, d] hot tier would make jnp.take fail inside shard_map.
    h = min(c, max(1, int(round(c * float(hot_ratio)))))
    hot = np.zeros((num_shards, h, d), feature.dtype)
    cold = np.zeros((num_shards, c - h, d), feature.dtype)
    for s in range(num_shards):
        lo, hi = min(s * c, n), min((s + 1) * c, n)
        blk = feature[lo:hi]
        hot[s, : min(h, hi - lo)] = blk[:h]
        if hi - lo > h:
            cold[s, : hi - lo - h] = blk[h:]
    arr = jnp.asarray(hot) if dtype is None else jnp.asarray(hot, dtype)
    return TieredShardedFeature(hot=arr, cold=cold, nodes_per_shard=c,
                                hot_per_shard=h, num_shards=num_shards)


def shard_feature_tiered_from_store(store, num_shards: int,
                                    hot_ratio: float, dtype=None
                                    ) -> TieredShardedFeature:
    """Third-tier constructor (glt_tpu.store, docs/storage.md): hot
    prefixes load straight off a shard-major
    :class:`~glt_tpu.store.disk.DiskFeatureStore`; the cold remainder
    STAYS on disk.

    The store holds the full ``[num_shards * nodes_per_shard, d]``
    matrix in the :class:`TieredShardedFeature` id layout (shard ``s``
    row ``r`` at global row ``s * c + r``), so the same file backs both
    the hot loads here and a
    :class:`~glt_tpu.store.stager.DiskColdStore` — which you MUST pass
    as the pipeline's ``cold_store`` (the returned ``cold`` field is a
    zero-row placeholder; :class:`~glt_tpu.parallel.dist_train.
    TieredTrainPipeline` refuses to default it to a
    :class:`HostColdStore`).
    """
    if store.num_rows % num_shards:
        raise ValueError(
            f"store rows {store.num_rows} not divisible by {num_shards} "
            f"shards — pad the matrix to the shard grid before writing")
    c = store.num_rows // num_shards
    h = min(c, max(1, int(round(c * float(hot_ratio)))))
    hot = np.empty((num_shards, h, store.dim), store.dtype)
    for s in range(num_shards):
        hot[s] = store.read_rows(
            np.arange(s * c, s * c + h, dtype=np.int64))
    arr = jnp.asarray(hot) if dtype is None else jnp.asarray(hot, dtype)
    cold = np.zeros((num_shards, 0, store.dim), store.dtype)
    return TieredShardedFeature(hot=arr, cold=cold, nodes_per_shard=c,
                                hot_per_shard=h, num_shards=num_shards)


def exchange_gather_hot(
    ids: jnp.ndarray,
    hot_rows: jnp.ndarray,
    nodes_per_shard: int,
    hot_per_shard: int,
    num_shards: int,
    axis_name: str,
    staged_resp: Optional[jnp.ndarray] = None,
    staged_rows: Optional[jnp.ndarray] = None,
    staged_slots: Optional[jnp.ndarray] = None,
    dedup: bool = False,
    routing=None,
    route: str = "auto",
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
) -> jnp.ndarray:
    """Tiered gather; call inside ``shard_map``.

    Same collective round-trip as :func:`exchange_gather`, but the serving
    shard answers hot requests (``local < hot_per_shard``) from HBM and
    cold requests from host-staged rows (produced by
    :func:`route_cold_requests` + :meth:`HostColdStore.serve`).  Because
    every shard serves only rows it owns, each pod host stages only its
    own shards' cold rows — the multi-host seam the reference's
    UnifiedTensor UVA reads provided on a single node
    (unified_tensor.cu:202-311).

    Two staged forms:
      * **compact** (preferred): ``staged_rows`` ``[cold_cap, d]`` +
        ``staged_slots`` ``[cold_cap]`` request-slot indices (-1 pad),
        scattered into the response — host->device bytes scale with the
        actual cold traffic, not the worst-case request matrix
        (:func:`compact_cold_requests`);
      * **dense** (legacy): ``staged_resp`` ``[num_shards * b, d]``, one
        row per request slot.

    Without either, cold rows come back as zeros (fill them via the
    legacy :func:`merge_cold` overlay).

    ``dedup`` routes unique ids only (see :func:`exchange_gather`); the
    staged cold rows must then come from a :func:`route_cold_requests`
    call made with the SAME ``dedup`` flag — and, on a 2-D mesh, the
    same topology (``route``/``mesh_shape``) — or slot indices won't
    line up with the (possibly host-deduped) request layout.
    """
    if dedup:
        uniq, inv, _ = unique_first_occurrence(ids)
        urows = exchange_gather_hot(
            uniq, hot_rows, nodes_per_shard, hot_per_shard, num_shards,
            axis_name, staged_resp=staged_resp, staged_rows=staged_rows,
            staged_slots=staged_slots, route=route,
            mesh_shape=mesh_shape, hier_load_factor=hier_load_factor)
        return _dedup_scatter_back(urows, inv)
    b = ids.shape[0]
    routing, flat_plan, requests = _resolve_plan(
        ids, nodes_per_shard, num_shards, axis_name, routing, route,
        mesh_shape, hier_load_factor)

    my_rank = lax.axis_index(axis_name)
    local = requests - my_rank * nodes_per_shard
    ok = (local >= 0) & (local < hot_per_shard) & (requests >= 0)
    got = jnp.take(hot_rows, jnp.where(ok, local, 0), axis=0, mode="clip")
    if staged_rows is not None:
        # Compact scatter: cold slots are disjoint from hot slots; -1
        # pad slots are dropped as out-of-bounds (no copy, no trash row).
        got = jnp.where(ok[:, None], got, 0)
        idx = jnp.where(staged_slots >= 0, staged_slots, got.shape[0])
        got = got.at[idx].set(staged_rows.astype(got.dtype), mode="drop")
    elif staged_resp is None:
        got = jnp.where(ok[:, None], got, 0)
    else:
        # Hot slots from HBM, cold slots from the staged host rows
        # (disjoint by construction; padding slots are zero either way).
        got = jnp.where(ok[:, None], got, staged_resp.astype(got.dtype))

    resp = _return_payload(routing, got, num_shards, b, axis_name)
    out = resp[jnp.clip(flat_plan.slot, 0, num_shards * b - 1)]
    return jnp.where(flat_plan.valid[:, None], out, 0)


def exchange_gather_xy(
    ids: jnp.ndarray,
    rows: jnp.ndarray,
    labels_col: jnp.ndarray,
    nodes_per_shard: int,
    num_shards: int,
    axis_name: str,
    hot_per_shard: Optional[int] = None,
    staged_rows: Optional[jnp.ndarray] = None,
    staged_slots: Optional[jnp.ndarray] = None,
    dedup: bool = False,
    routing=None,
    route: str = "auto",
    fused: Optional[bool] = None,
    fused_frontier: str = "off",
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
):
    """Feature AND label gather for one frontier in a single exchange.

    Call inside ``shard_map``.  The pre-fusion train step ran this as two
    (or, tiered, three) independent exchanges over the SAME ids — each
    rebuilding the identical routing plan and launching its own id +
    payload collectives.  Here one :func:`build_routing` plan, one id
    all-to-all, and one fused payload all-to-all carry both: the serving
    shard's int32 label column is **bitcast** to a float32 payload column
    and concatenated onto the feature rows (pure data movement end to
    end, so the round trip is bit-exact for ANY label value), then split
    and bitcast back on the requester.  Halves the collective launches of
    the gather stage and removes two redundant routing prologues.

    Args:
      ids: ``[B]`` global node ids (-1 padded -> zero rows/labels).
      rows: ``[nodes_per_shard, d]`` (full) or hot-prefix feature block.
      labels_col: ``[nodes_per_shard]`` this shard's label column.
      hot_per_shard: tiered serving bound — requests past it take staged
        cold rows (see :func:`exchange_gather_hot`); None = full HBM.
      staged_rows / staged_slots: compact cold staging, as
        :func:`exchange_gather_hot`.
      dedup: unique ids ride the exchange once; scatter-back is
        bit-identical (see :func:`exchange_gather`).
      fused: collective-fusion seam; the split fallback still shares the
        routing plan and id collective, paying one extra payload launch.
        Value-fusion also requires a float32 feature block (the bitcast
        target); other dtypes silently take the shared-routing split.
      fused_frontier: serving-side kernel seam for the feature-row fetch
        (see :func:`_request_rows`); bit-identical either way.
      mesh_shape / hier_load_factor: 2-D mesh hierarchical-topology
        knobs (see :func:`exchange_gather`).  The fused x+y payload
        rides the hier legs as one block, so the feature+label lookup
        stays a single round trip on both topologies.

    Returns:
      ``(x [B, d], y [B] int32)`` in input order (zeros at invalid
      slots, exactly like the separate exchanges).
    """
    if dedup:
        uniq, inv, _ = unique_first_occurrence(ids)
        ux, uy = exchange_gather_xy(
            uniq, rows, labels_col, nodes_per_shard, num_shards,
            axis_name, hot_per_shard=hot_per_shard,
            staged_rows=staged_rows, staged_slots=staged_slots,
            route=route, fused=fused, fused_frontier=fused_frontier,
            mesh_shape=mesh_shape, hier_load_factor=hier_load_factor)
        return _dedup_scatter_back(ux, inv), _dedup_scatter_back_1d(uy, inv)

    b = ids.shape[0]
    d = rows.shape[-1]
    routing, flat_plan, requests = _resolve_plan(
        ids, nodes_per_shard, num_shards, axis_name, routing, route,
        mesh_shape, hier_load_factor)

    my_rank = lax.axis_index(axis_name)
    local = requests - my_rank * nodes_per_shard
    h = nodes_per_shard if hot_per_shard is None else int(hot_per_shard)
    okx = (local >= 0) & (local < h) & (requests >= 0)
    oky = (local >= 0) & (local < nodes_per_shard) & (requests >= 0)
    gotx = _request_rows(rows, local, okx, fused_frontier)
    if staged_rows is not None:
        idx = jnp.where(staged_slots >= 0, staged_slots, gotx.shape[0])
        gotx = gotx.at[idx].set(staged_rows.astype(gotx.dtype),
                                mode="drop")
    goty = jnp.take(labels_col.astype(jnp.int32),
                    jnp.where(oky, local, 0), mode="clip")
    goty = jnp.where(oky, goty, 0)

    if _use_fused(fused) and rows.dtype == jnp.float32:
        ybits = lax.bitcast_convert_type(goty, jnp.float32)[:, None]
        resp = _return_payload(
            routing, jnp.concatenate([gotx, ybits], axis=-1),
            num_shards, b, axis_name)
        respx = resp[:, :d]
        respy = lax.bitcast_convert_type(resp[:, d], jnp.int32)
    else:
        respx = _return_payload(routing, gotx, num_shards, b, axis_name)
        respy = _return_payload(routing, goty[:, None], num_shards, b,
                                axis_name)[:, 0]

    slot = jnp.clip(flat_plan.slot, 0, num_shards * b - 1)
    x = jnp.where(flat_plan.valid[:, None], respx[slot], 0)
    y = jnp.where(flat_plan.valid, respy[slot], 0)
    return x, y


def compact_cold_requests(cold_req: jnp.ndarray, cold_cap: int):
    """Compress a responder-side cold-request vector to ``cold_cap`` slots.

    ``cold_req``: ``[R]`` local cold row ids from
    :func:`route_cold_requests` (-1 = not cold).  Returns ``(slots, ids,
    dropped)``: request-slot indices and local cold ids (``[cold_cap]``,
    -1 padded) plus the count of cold requests past the cap (served as
    zero rows — monitor and raise ``cold_cap`` if ever nonzero).  The
    host then gathers ``ids`` only: staged host->device bytes drop from
    the dense ``R = num_shards * node_cap`` rows to ``cold_cap`` (the
    capacity-bounding trick of the sampler exchange applied to the
    feature tier).
    """
    is_cold = cold_req >= 0
    order = jnp.argsort(~is_cold, stable=True)   # cold slots first
    slots = order[:cold_cap].astype(jnp.int32)
    ids = cold_req[slots]
    slots = jnp.where(ids >= 0, slots, -1)
    dropped = jnp.maximum(
        jnp.sum(is_cold.astype(jnp.int32)) - cold_cap, 0)
    return slots, ids, dropped


def route_cold_requests(
    ids: jnp.ndarray,
    nodes_per_shard: int,
    hot_per_shard: int,
    num_shards: int,
    axis_name: str,
    dedup: bool = False,
    routing=None,
    route: str = "auto",
    mesh_shape: Optional[tuple] = None,
    hier_load_factor: Optional[float] = None,
) -> jnp.ndarray:
    """Responder-side cold request slots; call inside ``shard_map``.

    Runs the SAME deterministic bucketing + id exchange as
    :func:`exchange_gather_hot` and returns, for this shard, the local
    cold row index (``0..c-h``) of every incoming request slot, or -1
    for hot/foreign/padding slots: ``[num_shards * b]`` on the flat
    topology, ``[num_hosts * hier_cap]`` on the hierarchical one (the
    request layout follows the topology).  The host then gathers
    exactly these rows from its local cold store — no host ever touches
    another host's rows.  Pass the same ``dedup`` flag — and, on a 2-D
    mesh, the same ``route``/``mesh_shape``/``hier_load_factor`` — as
    the paired :func:`exchange_gather_hot` call so both resolve the
    identical request layout.
    """
    if dedup:
        ids = unique_first_occurrence(ids).uniques
        routing = None   # the shared plan is over the un-deduped list
    routing, _, requests = _resolve_plan(
        ids, nodes_per_shard, num_shards, axis_name, routing, route,
        mesh_shape, hier_load_factor)
    my_rank = lax.axis_index(axis_name)
    local = requests - my_rank * nodes_per_shard
    is_cold = (requests >= 0) & (local >= hot_per_shard) & (
        local < nodes_per_shard)
    return jnp.where(is_cold, local - hot_per_shard, -1)


class HostColdStore:
    """Cold rows for the shards one host owns (all shards by default).

    On a multi-host pod each process builds
    ``HostColdStore(f, shard_ids=<its local shards>)`` and serves only
    those; the single-process emulation holds every shard.  The staged
    response for shard ``s`` depends only on shard ``s``'s store, so
    per-host ``device_put`` placement is naturally correct.
    """

    def __init__(self, f: TieredShardedFeature, shard_ids=None):
        self.shard_ids = (tuple(range(f.num_shards)) if shard_ids is None
                          else tuple(shard_ids))
        self._blocks = {s: np.asarray(f.cold[s]) for s in self.shard_ids}
        self.dim = f.cold.shape[-1]
        self.dtype = f.cold.dtype

    def serve(self, shard: int, cold_req: np.ndarray) -> np.ndarray:
        """Rows for one shard's request slots.

        Args:
          cold_req: ``[R]`` local cold row ids from
            :func:`route_cold_requests` (-1 = not a cold row of ours).
        Returns ``[R, d]`` with zeros at -1 slots.
        """
        cold_req = np.asarray(cold_req)
        out = np.zeros((cold_req.shape[0], self.dim), self.dtype)
        self.serve_into(out, shard, cold_req)
        return out

    def serve_into(self, out: np.ndarray, shard: int, cold_req: np.ndarray,
                   pool=None, row_chunk: int = 16384) -> list:
        """Gather one shard's cold rows into ``out`` (``[R, d]``), row-chunk
        parallel.

        With ``pool`` (a ThreadPoolExecutor) the gather splits into
        ``row_chunk``-row work items and returns their futures (caller
        awaits); numpy fancy indexing releases the GIL during the copy,
        so chunks scale across host cores — the thread-level rebuild of
        the warp-parallel UVA gather (unified_tensor.cu:48-81).  Without
        a pool the gather runs inline and returns ``[]``.
        """
        if shard not in self._blocks:
            raise KeyError(
                f"shard {shard} is not local to this host "
                f"(local: {self.shard_ids})")
        blk = self._blocks[shard]
        cold_req = np.asarray(cold_req)
        sel = np.where(cold_req >= 0)[0]
        if blk.shape[0] == 0 or sel.size == 0:
            return []

        def work(lo, hi):
            idx = sel[lo:hi]
            out[idx] = blk[cold_req[idx]]

        if pool is None:
            work(0, sel.size)
            return []
        return [pool.submit(work, lo, min(lo + row_chunk, sel.size))
                for lo in range(0, sel.size, row_chunk)]


def cold_mask(ids: jnp.ndarray, nodes_per_shard: int,
              hot_per_shard: int) -> jnp.ndarray:
    """True where ``ids`` resolve to the host tier (jit-safe)."""
    return (ids >= 0) & (ids % nodes_per_shard >= hot_per_shard)


def merge_cold(hot_x: jnp.ndarray, staged_cold: jnp.ndarray,
               ids: jnp.ndarray, nodes_per_shard: int,
               hot_per_shard: int) -> jnp.ndarray:
    """Overlay staged cold rows onto the hot-tier gather result."""
    m = cold_mask(ids, nodes_per_shard, hot_per_shard)
    return jnp.where(m[:, None], staged_cold.astype(hot_x.dtype), hot_x)


def cold_gather_host(f: TieredShardedFeature,
                     nodes: np.ndarray) -> np.ndarray:
    """Host-side gather of the cold rows for per-shard node lists.

    Args:
      nodes: ``[S, cap]`` global (relabeled) ids, -1 padded — the sample
        stage's ``out.node``.

    Returns ``[S, cap, d]`` host array with zeros at hot/padding slots.
    On a multi-host pod each host only holds its own shards' cold rows;
    this single-process build holds all of them (the emulation mirrors the
    reference's single-host multi-GPU tests, SURVEY §4).
    """
    nodes = np.asarray(nodes)
    s_axis, cap = nodes.shape
    c, h = f.nodes_per_shard, f.hot_per_shard
    d = f.cold.shape[-1]
    out = np.zeros((s_axis, cap, d), f.cold.dtype)
    if f.cold.shape[1] == 0:
        return out
    flat = nodes.reshape(-1)
    is_cold = (flat >= 0) & (flat % c >= h)
    # Gather only the cold slots (typically a minority of the batch):
    # the host stage bounds pipelined step time, so no wasted rows.
    cold_flat = flat[is_cold]
    out.reshape(-1, d)[is_cold] = f.cold[cold_flat // c, cold_flat % c - h]
    return out
