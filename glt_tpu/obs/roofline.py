"""Gather roofline: a measured device-memcpy bandwidth ceiling.

The ROADMAP's gather-wall item needs ``gather_gb_s`` expressed as a
fraction of what the chip can actually stream, not of the datasheet HBM
number (``est_hbm_fraction`` divides by 819 GB/s — a spec constant this
host may never reach through the tunnel-dispatched runtime).
PyTorch-Direct and GIDS (PAPERS.md) both anchor their irregular-gather
claims the same way: achieved vs a *measured* sequential-copy peak.

Methodology (docs/observability.md "Roofline"):

  * the probe is ``x -> x + 1.0`` over a contiguous f32 buffer under
    jit: one HBM read + one HBM write per pass = ``2 * nbytes`` traffic,
    the same in/out streaming a memcpy pays, with no gather indirection;
  * passes chain (``x = step(x)``) so one host fetch at the end syncs
    the whole timed region — ``block_until_ready`` does not wait under
    the axon tunnel (bench.py:33), a host value fetch provably does;
  * ``memcpy_gb_s = 2 * nbytes * iters / elapsed``; a gather variant's
    ``roofline_fraction(gather_gb_s, memcpy_gb_s)`` is then the number
    ROADMAP item 1 names as its success metric (within ~2x of 1.0).
"""
from __future__ import annotations

import os
import time
from typing import Dict

#: Datasheet HBM bandwidth (GB/s) by device-kind substring, most
#: specific first (matched against a lowercased, space-stripped
#: ``device_kind``).  The table exists so roofline fractions stop
#: silently assuming v5e on every backend; ``GLT_HBM_GBPS`` overrides
#: it for hardware the table doesn't know.
DEVICE_HBM_GB_S = (
    ("v6e", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)
#: Last-resort spec constant (the historical hard-coded v5e number).
DEFAULT_HBM_GB_S = 819.0


def measure_memcpy_roofline(nbytes: int = 1 << 27, iters: int = 10,
                            warmup: int = 2) -> Dict[str, float]:
    """Measure the streaming-copy bandwidth of the default device.

    Returns ``{"memcpy_gb_s", "bytes", "iters", "elapsed_s"}``.  The
    default 128 MiB buffer is large enough to defeat on-chip caching on
    any current TPU; shrink ``nbytes`` for CPU smoke runs.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    n = max(int(nbytes) // 4, 1024)
    x = jnp.zeros((n,), jnp.float32)
    step = jax.jit(lambda a: a + 1.0)
    for _ in range(max(warmup, 1)):
        x = step(x)
    float(np.asarray(jax.device_get(x[0])))   # compile + true sync
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    float(np.asarray(jax.device_get(x[0])))   # host fetch = true sync
    elapsed = time.perf_counter() - t0
    moved_gb = 2.0 * n * 4 * iters / 1e9
    return {
        "memcpy_gb_s": moved_gb / max(elapsed, 1e-9),
        "bytes": float(n * 4),
        "iters": float(iters),
        "elapsed_s": elapsed,
    }


def roofline_fraction(achieved_gb_s: float, roofline_gb_s: float) -> float:
    """Achieved bandwidth as a fraction of the measured roofline."""
    return float(achieved_gb_s) / max(float(roofline_gb_s), 1e-9)


def peak_hbm_gb_s(measure_fallback: bool = False) -> Dict[str, object]:
    """Resolve the peak HBM bandwidth WITH its provenance.

    Returns ``{"gb_s": float, "source": str}`` where source is one of
    ``env`` (``GLT_HBM_GBPS`` override), ``device_kind:<kind>`` (the
    datasheet table), ``measured_memcpy`` (opt-in small memcpy probe
    when the backend is unknown), or ``default_v5e``.  bench.py labels
    its ``est_hbm_fraction`` with the source so a fraction computed
    against the wrong ceiling is visible, not silent.
    """
    env = os.environ.get("GLT_HBM_GBPS")
    if env:
        try:
            return {"gb_s": float(env), "source": "env"}
        except ValueError:
            pass
    kind = None
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — resolution must never raise
        kind = None
    if kind:
        canon = str(kind).lower().replace(" ", "")
        for sub, gb_s in DEVICE_HBM_GB_S:
            if sub in canon:
                return {"gb_s": gb_s, "source": f"device_kind:{kind}"}
    if measure_fallback:
        try:
            probe = measure_memcpy_roofline(nbytes=1 << 24, iters=4)
            return {"gb_s": probe["memcpy_gb_s"],
                    "source": "measured_memcpy"}
        except Exception:  # noqa: BLE001
            pass
    return {"gb_s": DEFAULT_HBM_GB_S, "source": "default_v5e"}
