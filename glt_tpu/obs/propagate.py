"""Cross-process trace propagation over the remote-sampling protocol.

Three small wire pieces, all **optional and backward compatible** with
pre-trace peers (docs/observability.md "Distributed tracing"):

* **Request context** — a traced client adds :data:`WIRE_KEY`
  (``"#trace"``) to the JSON control request: ``{"tid": trace id,
  "sid": parent span id, "ts": client send time in the client's trace
  clock}``.  An old server parses the JSON and reads only the keys it
  knows — the extra key is ignored and the run degrades to untraced
  operation, never a :class:`ProtocolError`.

* **Response echo** — a traced server answers with :data:`WIRE_KEY` in
  the JSON response (or, for binary sample frames, in an **append-only
  trailer**, below): ``{"pid", "role", "t1": server receive time,
  "t2": server send time}`` — both in the *server's* trace clock.
  Together with the client's send/receive times this is one NTP-style
  sample ``(t0, t1, t2, t3)`` from which ``obs merge`` estimates the
  per-process clock offset (no extra RPCs: every request/response
  round-trip doubles as a sync probe).

* **Sample-frame trailer** — binary ``_KIND_MSG`` frames cannot carry a
  JSON key, so the echo rides an append-only trailer AFTER the
  serialized payload: ``payload || trailer-json || u32 len || b"GLTT"``.
  The server only appends it when the request carried :data:`WIRE_KEY`
  (i.e. the peer already speaks this protocol revision), so an old
  client never sees trailer bytes; a new client strips it by checking
  the magic.  This is the negotiated, append-only framing the
  mixed-version test locks in.

Clock-sync events recorded into traces (consumed by ``obs merge``):

* ``obs.clock_sync`` — full NTP sample; args ``{peer_pid, peer_role,
  t0_us, t1_us, t2_us, t3_us}`` with t0/t3 in the *recording* process's
  clock and t1/t2 in the peer's.
* ``obs.clock_oneway`` — a one-directional sample for peers without a
  request/response path (shm-channel sampling workers); args
  ``{peer_pid, peer_role, t_send_peer_us, t_recv_us}``.  Offset from
  the minimum observed ``t_recv - t_send`` (bias: the minimum one-way
  latency, microseconds on a same-host shm ring).
"""
from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple, Union

from .trace import Span, Tracer, current

#: Reserved JSON key carrying the trace context in both directions.
WIRE_KEY = "#trace"

#: Trailer magic closing a traced ``_KIND_MSG`` frame.
TRAILER_MAGIC = b"GLTT"
_TRAILER_FOOTER = struct.Struct("<I4s")  # trailer-json length + magic


def inject(req: Dict[str, Any], span: Span) -> Dict[str, Any]:
    """Attach ``span``'s wire context to a JSON request (in place).

    No-op (and no key) when tracing is off — the request stays
    byte-identical to the pre-trace protocol.
    """
    ctx = span.context()
    if ctx is not None:
        req[WIRE_KEY] = ctx
    return req


def extract(req: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Pop the trace context from an inbound request (None if absent)."""
    ctx = req.pop(WIRE_KEY, None)
    return ctx if isinstance(ctx, dict) else None


def server_echo(tracer: Optional[Tracer], t_recv_us: float,
                role: str = "server") -> Optional[Dict[str, Any]]:
    """The server's half of one NTP sample: receive + send timestamps in
    the server's trace clock (``t2`` stamped here, just before send)."""
    if tracer is None:
        return None
    return {"pid": tracer.pid, "role": role,
            "t1": round(t_recv_us, 3), "t2": round(tracer.now_us(), 3)}


def record_clock_sync(echo: Optional[Dict[str, Any]],
                      t0_us: Optional[float],
                      t3_us: Optional[float]) -> None:
    """Record one full NTP sample against the peer that sent ``echo``.

    ``t0``/``t3`` are this process's send/receive times (trace clock),
    ``echo`` the peer's ``{"pid", "role", "t1", "t2"}``.  Silently does
    nothing unless tracing is on and all four timestamps exist.
    """
    tracer = current()
    if (tracer is None or not isinstance(echo, dict)
            or t0_us is None or t3_us is None
            or "t1" not in echo or "t2" not in echo):
        return
    tracer.instant(
        "obs.clock_sync",
        peer_pid=echo.get("pid"),
        peer_role=echo.get("role"),
        t0_us=round(t0_us, 3),
        t1_us=float(echo["t1"]),
        t2_us=float(echo["t2"]),
        t3_us=round(t3_us, 3),
    )


def record_clock_oneway(peer_pid: Optional[int], peer_role: Optional[str],
                        t_send_peer_us: float) -> None:
    """Record a one-directional sync sample at receive time (shm-channel
    peers — sampling workers — have no response path to complete NTP)."""
    tracer = current()
    if tracer is None or peer_pid is None:
        return
    tracer.instant(
        "obs.clock_oneway",
        peer_pid=int(peer_pid),
        peer_role=peer_role,
        t_send_peer_us=round(float(t_send_peer_us), 3),
        t_recv_us=round(tracer.now_us(), 3),
    )


def pack_trailer(payload: bytes, echo: Optional[Dict[str, Any]]) -> bytes:
    """Append the trace echo to a binary sample payload (append-only:
    the original payload bytes are untouched)."""
    if echo is None:
        return payload
    blob = json.dumps(echo).encode()
    return payload + blob + _TRAILER_FOOTER.pack(len(blob), TRAILER_MAGIC)


def split_trailer(data: Union[bytes, memoryview]
                  ) -> Tuple[memoryview, Optional[Dict[str, Any]]]:
    """Split ``(payload, echo-or-None)`` off a possibly-trailed frame.

    Safe on untrailed frames: without the closing magic (or with an
    implausible length) the whole buffer is the payload.
    """
    mv = memoryview(data)
    n = len(mv)
    if n < _TRAILER_FOOTER.size:
        return mv, None
    blob_len, magic = _TRAILER_FOOTER.unpack_from(
        mv, n - _TRAILER_FOOTER.size)
    if magic != TRAILER_MAGIC or blob_len > n - _TRAILER_FOOTER.size:
        return mv, None
    start = n - _TRAILER_FOOTER.size - blob_len
    try:
        echo = json.loads(bytes(mv[start:n - _TRAILER_FOOTER.size]))
    except (ValueError, UnicodeDecodeError):
        return mv, None
    if not isinstance(echo, dict):
        return mv, None
    return mv[:start], echo
