"""Flight recorder: an always-on black box for postmortem telemetry.

The tracer and metrics (PRs 6-7) answer questions someone ASKED —
``GLT_OBS_TRACE`` armed, metrics enabled, the incident re-run.  Chaos
failures do not wait for arming: a peer dies, a producer is SIGKILLed,
an engine faults, and the process that noticed carries the only record
of the last few seconds.  This module keeps that record unconditionally:

* **Ring buffer.**  A fixed-size :class:`collections.deque` of
  structured events (reconnects, replays, admission rejections,
  evictions, supervisor beats/deaths, SLO alerts, epoch summaries).
  Recording is one lock + dict build + append — nanoseconds-to-
  microseconds, and every call site is an already-rare control-plane
  event, never the per-batch hot path.
* **Crash dump.**  The ring is dumped atomically (GLT011 tmp +
  ``os.replace``) on SIGTERM, on an uncaught exception, on
  ``SupervisedExit``/emergency checkpoint (the training loop calls
  :func:`dump_now`), and on demand via the ``flight_dump`` wire op on
  :class:`~glt_tpu.distributed.dist_server.DistServer`.  Handlers
  self-install on the FIRST recorded event — no arming step exists.
* **Fleet view.**  :func:`merge_flight_dumps` folds per-process dumps
  into one time-ordered stream (``python -m glt_tpu.obs merge`` routes
  flight dumps here automatically).

Stdlib only (the :mod:`.metrics` constraint): importable from the
analysis CI image and from pure-host tooling, no jax/numpy.

Event schema (docs/observability.md "Flight recorder"):

    {"seq": 42, "ts": <unix seconds>, "kind": "server.replay", ...}

``seq`` is a per-process monotonic counter (gaps at the front of a dump
mean the ring wrapped — ``dropped`` counts them); ``ts`` is wall-clock
``time.time()`` so dumps from different hosts merge on a common axis
(coarse NTP alignment is enough for postmortem ordering; durations are
never computed from it — gltlint GLT015).
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import socket
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

SCHEMA_KEY = "glt_flight"
SCHEMA_VERSION = 1

DEFAULT_CAPACITY = 512

_ENV_DIR = "GLT_FLIGHT_DIR"
_ENV_CAPACITY = "GLT_FLIGHT_EVENTS"


class FlightRecorder:
    """Fixed-capacity ring of structured events + atomic dumper.

    One per process (module singleton :func:`recorder`); thread-safe.
    """

    def __init__(self, capacity: Optional[int] = None,
                 role: Optional[str] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(_ENV_CAPACITY,
                                              DEFAULT_CAPACITY))
            except ValueError:
                capacity = DEFAULT_CAPACITY
        self.capacity = max(8, int(capacity))
        self.role = str(role) if role else "proc"
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dumped: List[str] = []

    # The event envelope — no field may shadow these, or the dump's
    # ordering proof breaks (a replayed message's seq=0 once clobbered
    # the ring seq); colliding fields are kept under an x_ prefix.
    _ENVELOPE = ("seq", "ts", "kind")

    # -- recording ---------------------------------------------------------
    def record(self, kind: str, /, **fields: Any) -> None:
        """Append one event.  Always on; never raises.  ``kind`` is
        positional-only so a stray ``kind=`` field (e.g. via ``**report``
        passthrough) lands in ``fields`` instead of a TypeError."""
        try:
            with self._lock:
                seq = self._seq
                self._seq += 1
                ev = {"seq": seq, "ts": time.time(), "kind": str(kind)}
                for k, v in fields.items():
                    ev["x_" + k if k in self._ENVELOPE else k] = v
                self._ring.append(ev)
        except Exception:  # noqa: BLE001 — the black box must not crash
            pass
        _install_crash_handlers()

    def events(self) -> List[dict]:
        with self._lock:
            return [dict(ev) for ev in self._ring]

    @property
    def recorded(self) -> int:
        """Total events ever recorded (>= len(ring) once wrapped)."""
        return self._seq

    @property
    def dropped(self) -> int:
        """Events overwritten by ring wrap-around."""
        with self._lock:
            return max(0, self._seq - len(self._ring))

    def clear(self) -> None:
        """Drop all events and reset the sequence (tests)."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dumped = []

    # -- dumping -----------------------------------------------------------
    def snapshot(self, reason: str = "snapshot") -> dict:
        """JSON-able dump object: metadata + the ring's events."""
        with self._lock:
            events = [dict(ev) for ev in self._ring]
            seq = self._seq
        return {
            SCHEMA_KEY: SCHEMA_VERSION,
            "pid": os.getpid(),
            "role": self.role,
            "host": socket.gethostname(),
            "reason": str(reason),
            "dumped_at": time.time(),
            "capacity": self.capacity,
            "recorded": seq,
            "dropped": max(0, seq - len(events)),
            "events": events,
        }

    def default_path(self) -> str:
        d = os.environ.get(_ENV_DIR) or tempfile.gettempdir()
        return os.path.join(
            d, f"glt_flight-{self.role}-{os.getpid()}.json")

    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand") -> str:
        """Write the ring atomically (GLT011 tmp + ``os.replace``).

        The dump is readable at every instant: a reader sees either the
        previous complete dump or this one, never a torn file.
        """
        path = path or self.default_path()
        obj = self.snapshot(reason=reason)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(obj, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        with self._lock:
            self._dumped.append(path)
        return path


#: The process-global recorder every hook site records into.
_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, /, **fields: Any) -> None:
    """Record one event into the process recorder (always on)."""
    _RECORDER.record(kind, **fields)


def configure(capacity: Optional[int] = None,
              role: Optional[str] = None) -> FlightRecorder:
    """Adjust the process recorder (capacity change drops nothing that
    still fits; role tags later dumps)."""
    global _RECORDER
    if capacity is not None and int(capacity) != _RECORDER.capacity:
        old = _RECORDER.events()
        fresh = FlightRecorder(capacity=capacity,
                               role=role or _RECORDER.role)
        for ev in old[-fresh.capacity:]:
            fresh._ring.append(ev)
        fresh._seq = _RECORDER._seq
        _RECORDER = fresh
    elif role is not None:
        _RECORDER.role = str(role)
    return _RECORDER


def dump_now(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Best-effort dump for fatal paths (``SupervisedExit``, emergency
    checkpoint): never raises — the exception in flight outranks the
    black box.  Returns the written path, or None on failure."""
    try:
        return _RECORDER.dump(path=path, reason=reason)
    except Exception:  # noqa: BLE001 — fatal path; must not mask the cause
        return None


# -- crash-time dumping ------------------------------------------------------
# Mirrors glt_tpu.obs.trace's crash-flush discipline: handlers chain to
# whatever was installed before (the tracer's SIGTERM flush included) and
# install exactly once, from the first recorded event — so a process that
# ever produced an event needs zero arming to leave a black box behind.
_handlers_lock = threading.Lock()
_handlers_installed = False


def _dump_best_effort(reason: str) -> None:
    try:
        if _RECORDER.recorded:
            _RECORDER.dump(reason=reason)
    except Exception:  # noqa: BLE001 — dying; nothing useful to do
        pass


def _install_crash_handlers() -> None:
    global _handlers_installed
    if _handlers_installed:
        return
    with _handlers_lock:
        if _handlers_installed:
            return
        _handlers_installed = True

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            record("process.uncaught", exc=getattr(
                exc_type, "__name__", str(exc_type)), msg=str(exc)[:200])
            _dump_best_effort(f"uncaught:{exc_type.__name__}")
            prev_hook(exc_type, exc, tb)

        sys.excepthook = hook
        atexit.register(_atexit_dump)
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                record("process.sigterm")
                _dump_best_effort("sigterm")
                # Chain: restore whatever was installed before (the
                # tracer's flush handler included) and re-raise, so the
                # process still dies with the TERM disposition.
                signal.signal(signum, prev if callable(prev)
                              else signal.SIG_DFL)
                os.kill(os.getpid(), signum)

            signal.signal(signal.SIGTERM, on_term)
        except ValueError:
            # Not the main thread — the atexit/excepthook half still runs.
            pass


def _atexit_dump() -> None:
    # Normal exits only leave a file when the operator opted in with
    # GLT_FLIGHT_DIR; crash paths (SIGTERM/uncaught/fatal) always dump.
    if os.environ.get(_ENV_DIR):
        _dump_best_effort("atexit")


# -- validation / merge ------------------------------------------------------
def validate_flight_dump(obj: Any) -> List[str]:
    """Structural problems of a flight dump ([] = valid).

    The contract the chaos tests and ``obs merge`` assert on: schema
    marker, metadata fields, events as dicts with monotonically
    increasing ``seq`` and the required ``ts``/``kind`` fields.
    """
    problems: List[str] = []
    if not isinstance(obj, dict) or SCHEMA_KEY not in obj:
        return [f"not a flight dump (missing {SCHEMA_KEY!r} marker)"]
    # A merged stream (merge_flight_dumps) carries per-source metadata
    # under "sources" and interleaves processes, so seq is monotonic
    # PER PROCESS rather than globally.
    is_merged = "merged_from" in obj
    required = (("sources", "events") if is_merged
                else ("pid", "role", "reason", "capacity", "recorded",
                      "dropped", "events"))
    for field in required:
        if field not in obj:
            problems.append(f"missing field {field!r}")
    events = obj.get("events")
    if not isinstance(events, list):
        return problems + ["events is not a list"]
    prev_seq: Dict[Any, int] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        for field in ("seq", "ts", "kind"):
            if field not in ev:
                problems.append(f"event {i} missing {field!r}")
        seq = ev.get("seq")
        if isinstance(seq, int):
            stream = ((ev.get("pid"), ev.get("role")) if is_merged
                      else None)
            prev = prev_seq.get(stream)
            if prev is not None and seq <= prev:
                problems.append(
                    f"event {i} seq {seq} not after {prev}")
            prev_seq[stream] = seq
    n_dropped = obj.get("dropped")
    n_rec, n_ev = obj.get("recorded"), len(events)
    if (not is_merged and isinstance(n_dropped, int)
            and isinstance(n_rec, int)
            and n_dropped != max(0, n_rec - n_ev)):
        problems.append(
            f"dropped={n_dropped} inconsistent with recorded={n_rec}, "
            f"{n_ev} events")
    return problems


def is_flight_dump(obj: Any) -> bool:
    return isinstance(obj, dict) and SCHEMA_KEY in obj


def merge_flight_dumps(paths: Sequence[str],
                       out: Optional[str] = None) -> dict:
    """Fold per-process flight dumps into one time-ordered stream.

    Each event is re-tagged with its process's ``pid``/``role``; the
    merged stream orders by wall-clock ``ts`` (coarse cross-host
    alignment — postmortem ordering, not profiling).  Written
    atomically when ``out`` is given (GLT011).
    """
    if not paths:
        raise ValueError("no flight dumps to merge")
    sources: List[dict] = []
    merged: List[dict] = []
    for path in paths:
        with open(path) as fh:
            obj = json.load(fh)
        problems = validate_flight_dump(obj)
        if problems:
            raise ValueError(f"{path}: {problems[0]}")
        sources.append({
            "path": path, "pid": obj["pid"], "role": obj["role"],
            "reason": obj["reason"], "dropped": obj["dropped"],
        })
        for ev in obj["events"]:
            ev = dict(ev)
            ev["pid"] = obj["pid"]
            ev["role"] = obj["role"]
            merged.append(ev)
    merged.sort(key=lambda ev: (ev.get("ts", 0.0), ev.get("seq", 0)))
    result: Dict[str, Any] = {
        SCHEMA_KEY: SCHEMA_VERSION,
        "merged_from": [s["path"] for s in sources],
        "sources": sources,
        "events": merged,
    }
    # Fold the triggered-profiler capture index into the merged
    # timeline (obs/profiler.py): the postmortem reader sees which
    # trace directory belongs to which incident without scanning the
    # whole event stream.
    captures = [ev for ev in merged if ev.get("kind") == "profiler.capture"]
    if captures:
        result["captures"] = captures
    if out is not None:
        tmp = f"{out}.tmp-{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(result, fh)
        os.replace(tmp, out)
    return result
