"""Human summary of an exported Chrome trace (`python -m glt_tpu.obs`).

Aggregates complete-events by span name: call count, total/mean/max
wall, and *self* time (total minus time attributed to nested spans on
the same thread) — self time is what ranks where a step actually goes.
"""
from __future__ import annotations

import json
from typing import Dict, List


def summarize_trace(obj: dict) -> List[dict]:
    """Per-span-name aggregate rows, sorted by total time descending.

    Row keys: ``name, count, total_ms, self_ms, mean_ms, max_ms,
    device_wait_ms`` (device wait summed over fenced spans only).
    """
    events = [e for e in obj.get("traceEvents", []) if e.get("ph") == "X"]
    by_tid: Dict[tuple, List[dict]] = {}
    for ev in events:
        by_tid.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    stats: Dict[str, dict] = {}
    eps = 0.5  # us; tolerates rounding at span edges
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[dict] = []        # {"end", "name"} of open ancestors
        for ev in evs:
            while stack and stack[-1]["end"] <= ev["ts"] + eps:
                stack.pop()
            if stack:
                # A child's whole duration leaves its direct parent's
                # self time (grandchildren subtract from the child).
                stats[stack[-1]["name"]]["self_us"] -= ev["dur"]
            stack.append({"end": ev["ts"] + ev["dur"], "name": ev["name"]})
            r = stats.setdefault(ev["name"], {
                "name": ev["name"], "count": 0, "total_us": 0.0,
                "self_us": 0.0, "max_us": 0.0, "device_wait_us": 0.0})
            r["count"] += 1
            r["total_us"] += ev["dur"]
            r["self_us"] += ev["dur"]
            r["max_us"] = max(r["max_us"], ev["dur"])
            r["device_wait_us"] += ev.get("args", {}).get(
                "device_wait_us", 0.0)
    rows = []
    for r in sorted(stats.values(), key=lambda r: -r["total_us"]):
        rows.append({
            "name": r["name"],
            "count": r["count"],
            "total_ms": round(r["total_us"] / 1e3, 3),
            "self_ms": round(r["self_us"] / 1e3, 3),
            "mean_ms": round(r["total_us"] / max(r["count"], 1) / 1e3, 3),
            "max_ms": round(r["max_us"] / 1e3, 3),
            "device_wait_ms": round(r["device_wait_us"] / 1e3, 3),
        })
    return rows


def format_summary(rows: List[dict]) -> str:
    cols = ("name", "count", "total_ms", "self_ms", "mean_ms", "max_ms",
            "device_wait_ms")
    widths = {c: (max(len(c), *(len(str(r[c])) for r in rows))
                  if rows else len(c)) for c in cols}
    head = "  ".join(c.ljust(widths[c]) for c in cols)
    sep = "  ".join("-" * widths[c] for c in cols)
    lines = [head, sep]
    for r in rows:
        lines.append("  ".join(
            str(r[c]).ljust(widths[c]) if c == "name"
            else str(r[c]).rjust(widths[c]) for c in cols))
    return "\n".join(lines)


def load_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
