"""Human summary of an exported Chrome trace (`python -m glt_tpu.obs`).

Aggregates complete-events by span name: call count, total/mean/max
wall, and *self* time (total minus time attributed to nested spans on
the same thread) — self time is what ranks where a step actually goes.
"""
from __future__ import annotations

import json
from typing import Dict, List


def summarize_trace(obj: dict) -> List[dict]:
    """Per-span-name aggregate rows, sorted by total time descending.

    Row keys: ``name, count, total_ms, self_ms, mean_ms, max_ms,
    device_wait_ms`` (device wait summed over fenced spans only).
    """
    events = [e for e in obj.get("traceEvents", []) if e.get("ph") == "X"]
    by_tid: Dict[tuple, List[dict]] = {}
    for ev in events:
        by_tid.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    stats: Dict[str, dict] = {}
    eps = 0.5  # us; tolerates rounding at span edges
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[dict] = []        # {"end", "name"} of open ancestors
        for ev in evs:
            while stack and stack[-1]["end"] <= ev["ts"] + eps:
                stack.pop()
            if stack:
                # A child's whole duration leaves its direct parent's
                # self time (grandchildren subtract from the child).
                stats[stack[-1]["name"]]["self_us"] -= ev["dur"]
            stack.append({"end": ev["ts"] + ev["dur"], "name": ev["name"]})
            r = stats.setdefault(ev["name"], {
                "name": ev["name"], "count": 0, "total_us": 0.0,
                "self_us": 0.0, "max_us": 0.0, "device_wait_us": 0.0})
            r["count"] += 1
            r["total_us"] += ev["dur"]
            r["self_us"] += ev["dur"]
            r["max_us"] = max(r["max_us"], ev["dur"])
            r["device_wait_us"] += ev.get("args", {}).get(
                "device_wait_us", 0.0)
    rows = []
    for r in sorted(stats.values(), key=lambda r: -r["total_us"]):
        rows.append({
            "name": r["name"],
            "count": r["count"],
            "total_ms": round(r["total_us"] / 1e3, 3),
            "self_ms": round(r["self_us"] / 1e3, 3),
            "mean_ms": round(r["total_us"] / max(r["count"], 1) / 1e3, 3),
            "max_ms": round(r["max_us"] / 1e3, 3),
            "device_wait_ms": round(r["device_wait_us"] / 1e3, 3),
        })
    return rows


def format_summary(rows: List[dict]) -> str:
    cols = ("name", "count", "total_ms", "self_ms", "mean_ms", "max_ms",
            "device_wait_ms")
    widths = {c: (max(len(c), *(len(str(r[c])) for r in rows))
                  if rows else len(c)) for c in cols}
    head = "  ".join(c.ljust(widths[c]) for c in cols)
    sep = "  ".join("-" * widths[c] for c in cols)
    lines = [head, sep]
    for r in rows:
        lines.append("  ".join(
            str(r[c]).ljust(widths[c]) if c == "name"
            else str(r[c]).rjust(widths[c]) for c in cols))
    return "\n".join(lines)


def summarize_flight(obj: dict) -> dict:
    """Sectioned summary of a flight-recorder dump (single or merged).

    ``{"reason", "events_total", "kinds", "device", "compile",
    "captures", "slo"}`` — the device-memory and compile sections are
    the postmortem's first questions ("was it leaking?", "was it
    recompiling?") answered without scrolling the raw event stream.
    """
    events = [e for e in obj.get("events", []) if isinstance(e, dict)]
    kinds: Dict[str, int] = {}
    for ev in events:
        k = str(ev.get("kind", "?"))
        kinds[k] = kinds.get(k, 0) + 1
    leaks = [e for e in events if e.get("kind") == "device.leak_suspect"]
    storms = [e for e in events if e.get("kind") == "compile.storm"]
    captures = [e for e in events if e.get("kind") == "profiler.capture"]
    alerts = [e for e in events if e.get("kind") == "slo.alert"]
    return {
        "reason": obj.get("reason"),
        "events_total": len(events),
        "kinds": dict(sorted(kinds.items(), key=lambda kv: -kv[1])),
        "device": {
            "leak_suspects": len(leaks),
            "last_leak": leaks[-1] if leaks else None,
        },
        "compile": {
            "storms": len(storms),
            "storm_programs": sorted({str(e.get("program", "?"))
                                      for e in storms}),
            "last_storm": storms[-1] if storms else None,
        },
        "captures": [{"dir": e.get("dir"), "reason": e.get("reason"),
                      "ms": e.get("ms")} for e in captures],
        "slo": {
            "alerts": len(alerts),
            "firing": sorted({str(e.get("slo", "?")) for e in alerts
                              if e.get("state") == "firing"}),
        },
    }


def format_flight_summary(summary: dict) -> str:
    lines = [f"flight dump: {summary['events_total']} events "
             f"(reason={summary['reason']!r})", "", "Event kinds:"]
    for kind, n in summary["kinds"].items():
        lines.append(f"  {kind:<28} {n}")
    dev = summary["device"]
    lines += ["", "Device memory:"]
    if dev["leak_suspects"]:
        last = dev["last_leak"] or {}
        lines.append(f"  LEAK SUSPECT x{dev['leak_suspects']} — live "
                     f"{last.get('live_bytes')} B after "
                     f"{last.get('growth_epochs')} growing epochs")
    else:
        lines.append("  no leak suspects")
    comp = summary["compile"]
    lines += ["", "Compile:"]
    if comp["storms"]:
        lines.append(f"  RECOMPILE STORM x{comp['storms']} — programs: "
                     + ", ".join(comp["storm_programs"]))
    else:
        lines.append("  no recompile storms")
    lines += ["", f"Profiler captures: {len(summary['captures'])}"]
    for cap in summary["captures"]:
        lines.append(f"  {cap['reason']:<24} {cap['dir']}")
    slo = summary["slo"]
    lines += ["", f"SLO alerts: {slo['alerts']}"
              + (f" (fired: {', '.join(slo['firing'])})"
                 if slo["firing"] else "")]
    return "\n".join(lines)


def load_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
