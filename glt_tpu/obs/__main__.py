"""CLI: summarize / validate exported traces, dump the metrics snapshot.

    python -m glt_tpu.obs summarize trace.json [--sort self|total|count]
    python -m glt_tpu.obs validate trace.json
"""
from __future__ import annotations

import argparse
import sys

from .summarize import format_summary, load_trace, summarize_trace
from .trace import validate_chrome_trace


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m glt_tpu.obs",
        description="glt_tpu observability: trace summary + validation")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_sum = sub.add_parser("summarize",
                           help="aggregate a Chrome-trace JSON by span")
    p_sum.add_argument("trace")
    p_sum.add_argument("--sort", default="total",
                       choices=("total", "self", "count", "max"),
                       help="sort column (default: total time)")
    p_val = sub.add_parser("validate",
                           help="check Chrome-trace structure + nesting")
    p_val.add_argument("trace")
    args = parser.parse_args(argv)

    obj = load_trace(args.trace)
    if args.cmd == "validate":
        problems = validate_chrome_trace(obj)
        for p in problems:
            print(f"INVALID: {p}")
        n = len(obj.get("traceEvents", []))
        if not problems:
            print(f"OK: {n} events, spans nest, durations non-negative")
        return 1 if problems else 0

    rows = summarize_trace(obj)
    key = {"total": "total_ms", "self": "self_ms", "count": "count",
           "max": "max_ms"}[args.sort]
    rows.sort(key=lambda r: -r[key])
    print(format_summary(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
