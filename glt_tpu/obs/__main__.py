"""CLI: summarize / validate / merge exported traces + flight dumps.

    python -m glt_tpu.obs summarize trace.json [--sort self|total|count]
                                               [--json]
    python -m glt_tpu.obs validate trace.json|flight.json
    python -m glt_tpu.obs merge -o merged.json client.json server.json ...

``validate`` and ``merge`` auto-detect flight-recorder dumps
(``glt_flight`` schema marker, obs/flight.py) and route them through
the flight validator/merger — one postmortem CLI for both artifact
kinds.
"""
from __future__ import annotations

import argparse
import json
import sys

from .flight import is_flight_dump, merge_flight_dumps, validate_flight_dump
from .merge import merge_traces
from .summarize import (format_flight_summary, format_summary, load_trace,
                        summarize_flight, summarize_trace)
from .trace import validate_chrome_trace


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m glt_tpu.obs",
        description="glt_tpu observability: trace summary, validation, "
                    "and cross-process merge")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_sum = sub.add_parser(
        "summarize",
        help="aggregate a Chrome-trace JSON by span (flight dumps "
             "summarize into device-memory/compile/capture sections)")
    p_sum.add_argument("trace")
    p_sum.add_argument("--sort", default="total",
                       choices=("total", "self", "count", "max"),
                       help="sort column (default: total time)")
    p_sum.add_argument("--json", action="store_true",
                       help="emit the aggregate rows as a JSON list "
                            "(machine-readable; no screen-scraping)")
    p_val = sub.add_parser("validate",
                           help="check Chrome-trace structure + nesting")
    p_val.add_argument("trace")
    p_merge = sub.add_parser(
        "merge",
        help="stitch per-process trace files into one clock-aligned "
             "Chrome trace (NTP-style offsets from obs.clock_sync "
             "samples; see docs/observability.md)")
    p_merge.add_argument("traces", nargs="+",
                         help="per-process trace files (client, server, "
                              "workers)")
    p_merge.add_argument("-o", "--out", required=True,
                         help="merged output path")
    p_merge.add_argument("--ref-pid", type=int, default=None,
                         help="process whose clock is the reference "
                              "(default: the one with most sync samples)")
    args = parser.parse_args(argv)

    if args.cmd == "merge":
        heads = [load_trace(p) for p in args.traces]
        if any(is_flight_dump(h) for h in heads):
            if not all(is_flight_dump(h) for h in heads):
                print("ERROR: cannot merge flight dumps with Chrome "
                      "traces (merge each kind separately)")
                return 2
            merged = merge_flight_dumps(args.traces, args.out)
            problems = validate_flight_dump(merged)
            for p in problems:
                print(f"INVALID: {p}")
            print(f"{'INVALID' if problems else 'OK'}: merged "
                  f"{len(args.traces)} flight dumps, "
                  f"{len(merged['events'])} events -> {args.out}")
            return 1 if problems else 0
        merged = merge_traces(args.traces, out=args.out,
                              ref_pid=args.ref_pid)
        info = merged["glt"]
        for pid, off in sorted(info["clock_offsets_us"].items()):
            print(f"pid {pid}: offset {off:+.1f} us")
        if info["unaligned_pids"]:
            print(f"WARNING: no sync path for pids "
                  f"{info['unaligned_pids']} (kept unshifted)")
        problems = validate_chrome_trace(merged)
        for p in problems:
            print(f"INVALID: {p}")
        print(f"{'INVALID' if problems else 'OK'}: merged "
              f"{len(args.traces)} files, "
              f"{len(merged['traceEvents'])} events -> {args.out}")
        return 1 if problems else 0

    obj = load_trace(args.trace)
    if args.cmd == "validate":
        if is_flight_dump(obj):
            problems = validate_flight_dump(obj)
            for p in problems:
                print(f"INVALID: {p}")
            if not problems:
                print(f"OK: flight dump, {len(obj['events'])} events, "
                      f"seq monotonic, reason={obj.get('reason')!r}")
            return 1 if problems else 0
        problems = validate_chrome_trace(obj)
        for p in problems:
            print(f"INVALID: {p}")
        n = len(obj.get("traceEvents", []))
        if not problems:
            print(f"OK: {n} events, spans nest, durations non-negative")
        return 1 if problems else 0

    if is_flight_dump(obj):
        # Flight dumps summarize into device-memory / compile / capture
        # sections (docs/observability.md) — same auto-routing as
        # validate/merge.
        summary = summarize_flight(obj)
        print(json.dumps(summary) if args.json
              else format_flight_summary(summary))
        return 0
    rows = summarize_trace(obj)
    key = {"total": "total_ms", "self": "self_ms", "count": "count",
           "max": "max_ms"}[args.sort]
    rows.sort(key=lambda r: -r[key])
    if args.json:
        print(json.dumps(rows))
    else:
        print(format_summary(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
