"""glt_tpu.obs — unified tracing, metrics, and roofline profiling.

The library-wide observability subsystem (docs/observability.md):

  * **Tracing** (:mod:`.trace`): nested host-side spans with explicit
    device fencing, exported as Chrome-trace/Perfetto JSON; summarize
    with ``python -m glt_tpu.obs summarize trace.json``.
  * **Metrics** (:mod:`.metrics`): counters/gauges/histograms under one
    ``glt.*`` namespace with near-zero-cost no-op defaults; Prometheus
    text exposition serves the ``get_metrics`` op on ``DistServer``.
  * **Roofline** (:mod:`.roofline`): a measured device-memcpy bandwidth
    ceiling so ``gather_gb_s`` becomes an achieved-vs-peak fraction.

Both tracing and metrics are OFF by default and cost roughly a global
read + branch per call site when off.  Everything here is **host-side**:
never call span()/inc() inside a jit-traced function (gltlint GLT010).

>>> from glt_tpu import obs
>>> obs.metrics.enable()
>>> tracer = obs.start_trace()
>>> with obs.span("epoch") as sp:
...     loss = step(...)
...     sp.fence(loss)                    # close waits for the device
>>> obs.stop_trace("/tmp/trace.json")
>>> obs.metrics.snapshot()["glt.loader.batches"]
"""
from . import attrib  # noqa: F401  (stdlib-only; jax imports are lazy)
from . import compilewatch  # noqa: F401  (stdlib-only; lazy jax)
from . import device  # noqa: F401  (stdlib-only; jax imports are lazy)
from . import flight  # noqa: F401  (stdlib-only; safe without jax)
from . import metrics  # noqa: F401  (stdlib-only; safe without jax)
from . import profiler  # noqa: F401  (stdlib-only; jax imports lazy)
from . import slo  # noqa: F401  (stdlib-only; safe without jax)
from .flight import (  # noqa: F401
    FlightRecorder,
    merge_flight_dumps,
    validate_flight_dump,
)
from .merge import merge_traces, span_tree_check  # noqa: F401
from .metrics import prune_unmeasured  # noqa: F401
from .slo import SloMonitor, SloSpec, default_specs  # noqa: F401
from .roofline import measure_memcpy_roofline, roofline_fraction  # noqa: F401
from .summarize import (  # noqa: F401
    format_flight_summary,
    format_summary,
    summarize_flight,
    summarize_trace,
)
from .trace import (  # noqa: F401
    Span,
    Tracer,
    auto_trace,
    auto_trace_export,
    current,
    install,
    span,
    start_trace,
    stop_trace,
    validate_chrome_trace,
)

__all__ = [
    "FlightRecorder",
    "SloMonitor",
    "SloSpec",
    "Span",
    "Tracer",
    "attrib",
    "auto_trace",
    "compilewatch",
    "device",
    "profiler",
    "auto_trace_export",
    "current",
    "default_specs",
    "flight",
    "format_flight_summary",
    "format_summary",
    "install",
    "measure_memcpy_roofline",
    "merge_flight_dumps",
    "merge_traces",
    "metrics",
    "prune_unmeasured",
    "slo",
    "validate_flight_dump",
    "roofline_fraction",
    "span",
    "span_tree_check",
    "start_trace",
    "stop_trace",
    "summarize_flight",
    "summarize_trace",
    "validate_chrome_trace",
]
