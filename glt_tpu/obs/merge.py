"""Stitch per-process trace files into one clock-aligned Chrome trace.

Every process in a remote-sampling fleet (client, server, mp sampling
workers) exports its own trace file whose timestamps are **tracer
relative** — microseconds since that process's tracer started, an
arbitrary origin per process.  ``merge_traces`` estimates each
process's clock offset against a reference process and shifts every
event into the reference clock, so one file renders the whole fleet as
causally ordered, per-process-named tracks in Perfetto.

Offset estimation (docs/observability.md "Clock alignment"):

* **NTP-style pairs.**  Traced request/response round-trips record
  ``obs.clock_sync`` instants carrying ``(t0, t1, t2, t3)`` — client
  send, server receive, server send, client receive, the first two
  clocks local, the middle two the peer's.  For each sample the peer
  offset is ``theta = ((t1 - t0) + (t2 - t3)) / 2`` with round-trip
  ``delta = (t3 - t0) - (t2 - t1)``; the sample with the smallest
  ``delta`` wins (classic NTP filter), and its error is bounded by the
  link asymmetry, at most ``delta / 2``.

* **One-way samples.**  Peers reachable only through a one-directional
  channel (shm-ring sampling workers) stamp each message with their
  send time; the receiver records ``obs.clock_oneway``.  With
  ``theta`` the peer clock's lead, every sample satisfies
  ``t_send - t_recv = theta - latency <= theta``; the tightest bound
  ``max(t_send - t_recv)`` is the estimate, biased low by the minimum
  one-way latency (microseconds on a same-host ring).

Offsets compose transitively (worker -> server -> client), so processes
with no direct samples against the reference still align.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _load(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError(f"{path}: not a Chrome-trace object")
    return obj


def _file_identity(obj: dict, path: str) -> Tuple[Optional[int], str]:
    """(pid, process_name) of a trace file, from the ``glt`` sidecar or,
    for hand-built files, the metadata events / first timed event."""
    meta = obj.get("glt") or {}
    pid = meta.get("pid")
    name = meta.get("process_name")
    for ev in obj["traceEvents"]:
        if pid is None and "pid" in ev:
            pid = ev["pid"]
        if (name is None and ev.get("ph") == "M"
                and ev.get("name") == "process_name"):
            name = ev.get("args", {}).get("name")
    return pid, (name or path)


def _sync_edges(files: List[dict]) -> List[Tuple[int, int, float, float]]:
    """``(local_pid, peer_pid, theta, quality)`` from every sync sample:
    ``theta`` = peer clock minus local clock (``ts_local = ts_peer -
    theta``), ``quality`` = the sample's error bound in us (lower is
    better; used to pick among multiple samples for the same pair)."""
    edges: List[Tuple[int, int, float, float]] = []
    for f in files:
        local_pid = f["pid"]
        best_ntp: Dict[int, Tuple[float, float]] = {}
        best_oneway: Dict[int, Tuple[float, float]] = {}
        for ev in f["obj"]["traceEvents"]:
            args = ev.get("args", {})
            if ev.get("name") == "obs.clock_sync":
                try:
                    t0, t1 = float(args["t0_us"]), float(args["t1_us"])
                    t2, t3 = float(args["t2_us"]), float(args["t3_us"])
                    peer = int(args["peer_pid"])
                except (KeyError, TypeError, ValueError):
                    continue
                theta = ((t1 - t0) + (t2 - t3)) / 2.0
                delta = (t3 - t0) - (t2 - t1)
                err = max(delta, 0.0) / 2.0
                cur = best_ntp.get(peer)
                if cur is None or err < cur[1]:
                    best_ntp[peer] = (theta, err)
            elif ev.get("name") == "obs.clock_oneway":
                try:
                    peer = int(args["peer_pid"])
                    lag = (float(args["t_send_peer_us"])
                           - float(args["t_recv_us"]))
                except (KeyError, TypeError, ValueError):
                    continue
                cur = best_oneway.get(peer)
                # theta >= t_send - t_recv for every sample; the max is
                # the tightest lower bound.  Error bound unknown (the
                # min one-way latency); rank it behind any NTP pair.
                if cur is None or lag > cur[0]:
                    best_oneway[peer] = (lag, 1e9)
        for peer, (theta, err) in best_ntp.items():
            edges.append((local_pid, peer, theta, err))
        for peer, (theta, err) in best_oneway.items():
            if peer not in best_ntp:
                edges.append((local_pid, peer, theta, err))
    return edges


def estimate_offsets(files: List[dict], ref_pid: int) -> Dict[int, float]:
    """Per-pid offsets ``Theta`` with ``ts_ref = ts_pid - Theta[pid]``,
    composed transitively from the sync edges (BFS from the reference,
    best-quality edge first)."""
    edges = _sync_edges(files)
    # Undirected adjacency: an edge recorded in L about P maps either way.
    adj: Dict[int, List[Tuple[int, float, float]]] = {}
    for local, peer, theta, err in edges:
        adj.setdefault(local, []).append((peer, theta, err))
        adj.setdefault(peer, []).append((local, -theta, err))
    offsets: Dict[int, float] = {ref_pid: 0.0}
    frontier = [ref_pid]
    while frontier:
        nxt: List[int] = []
        for node in frontier:
            for peer, theta, _err in sorted(adj.get(node, ()),
                                            key=lambda e: e[2]):
                if peer in offsets:
                    continue
                # ts_node = ts_peer - theta and ts_ref = ts_node -
                # Theta[node]  =>  Theta[peer] = theta + Theta[node].
                offsets[peer] = theta + offsets[node]
                nxt.append(peer)
        frontier = nxt
    return offsets


def merge_traces(paths: Sequence[str], out: Optional[str] = None,
                 ref_pid: Optional[int] = None) -> dict:
    """Merge per-process trace files into one aligned Chrome trace.

    The reference process (``ref_pid``, default: the file with the most
    ``obs.clock_sync`` recordings — the client — else the first file)
    keeps its timestamps; every other process's events are shifted by
    its estimated offset.  Files with no sync path to the reference are
    kept unshifted and listed under ``glt.unaligned_pids``.
    """
    if not paths:
        raise ValueError("no trace files to merge")
    files: List[dict] = []
    seen_pids: Dict[int, int] = {}
    for i, path in enumerate(paths):
        obj = _load(path)
        pid, name = _file_identity(obj, path)
        pid = int(pid if pid is not None else -(i + 1))
        if pid in seen_pids:
            # Two files from one pid (in-process client+server tests):
            # keep them distinct tracks; sync edges resolve to the
            # first file's clock.
            seen_pids[pid] += 1
            pid = pid + 10_000_000 * seen_pids[pid]
        else:
            seen_pids[pid] = 0
        files.append({"path": path, "obj": obj, "pid": pid, "name": name})

    if ref_pid is None:
        def n_syncs(f):
            return sum(1 for ev in f["obj"]["traceEvents"]
                       if ev.get("name") == "obs.clock_sync")
        files_by_syncs = sorted(files, key=n_syncs, reverse=True)
        ref_pid = files_by_syncs[0]["pid"]

    offsets = estimate_offsets(files, ref_pid)
    merged: List[dict] = []
    unaligned: List[int] = []
    for f in files:
        theta = offsets.get(f["pid"])
        if theta is None:
            unaligned.append(f["pid"])
            theta = 0.0
        named = False
        for ev in f["obj"]["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = f["pid"]
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    named = True
            elif "ts" in ev:
                ev["ts"] = round(ev["ts"] - theta, 3)
            merged.append(ev)
        if not named:
            merged.append({"name": "process_name", "ph": "M",
                           "pid": f["pid"], "tid": 0,
                           "args": {"name": f["name"]}})
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    result = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "glt": {
            "merged_from": [f["path"] for f in files],
            "ref_pid": ref_pid,
            "clock_offsets_us": {str(f["pid"]):
                                 round(offsets.get(f["pid"], 0.0), 3)
                                 for f in files},
            "unaligned_pids": unaligned,
        },
    }
    if out is not None:
        # Atomic publish (GLT011): the merged trace is read by Perfetto /
        # the CLI while a re-merge may be running over the same path.
        tmp = f"{out}.tmp-{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(result, fh)
        os.replace(tmp, out)
    return result


def span_tree_check(merged: dict, tol_us: float = 0.0) -> List[str]:
    """Cross-process causality problems in a merged trace ([] = good).

    For every span with a REMOTE parent (``parent_span_id`` pointing at
    a span in a different process), check the child's interval nests
    within the parent's, allowing ``tol_us`` slack per edge for the
    residual clock-alignment error.  This is the merge-quality check the
    skew tests assert on.
    """
    spans: Dict[int, dict] = {}
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        sid = ev.get("args", {}).get("span_id")
        if sid is not None:
            spans[sid] = ev
    problems: List[str] = []
    checked = 0
    for ev in spans.values():
        pid = ev.get("args", {}).get("parent_span_id")
        parent = spans.get(pid)
        if parent is None or parent["pid"] == ev["pid"]:
            continue
        checked += 1
        lo, hi = parent["ts"], parent["ts"] + parent["dur"]
        if (ev["ts"] < lo - tol_us
                or ev["ts"] + ev["dur"] > hi + tol_us):
            problems.append(
                f"span {ev['name']!r} (pid {ev['pid']}) "
                f"[{ev['ts']:.1f}, {ev['ts'] + ev['dur']:.1f}] does not "
                f"nest in remote parent {parent['name']!r} "
                f"(pid {parent['pid']}) [{lo:.1f}, {hi:.1f}] "
                f"within {tol_us:.1f} us")
    if checked == 0:
        problems.append("no cross-process parent/child span pairs found")
    return problems
