"""Per-stage cost attribution: expected-bytes models -> roofline table.

ROADMAP item 1 asks "break the NEXT wall", but only the gather stage
has a measured roofline fraction — the other stages' walls are guessed
from two numbers.  This module gives every pipeline stage (sample /
dedup / gather / train) an **expected-bytes model**: the bytes the
stage must move if it did no redundant work.  Dividing by measured
stage time yields an achieved bandwidth, and dividing THAT by the
measured memcpy ceiling (:mod:`.roofline`) yields a comparable
``{stage}_roofline_frac`` — the fraction of the machine the stage
actually uses.  bench.py emits the table as ``stage_roofline`` and
regress.py tracks every fraction UP, so "what is the current wall" is
a measured, release-over-release answer.

Byte models are intentionally FLOORS (useful bytes, not implementation
traffic): a fraction above 1.0 is impossible, a fraction far below 1.0
means the stage is latency- or compute-bound — exactly the signal that
picks the next optimization target.  Where XLA exposes its own
accounting (``compiled.cost_analysis()``), :func:`compiled_cost_bytes`
substitutes the compiler's number for the analytic one.

Module-level code is stdlib-only (jax imports are lazy, the
:mod:`.roofline` pattern) so the analysis image can import the models.
"""
from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Tuple

STAGES: Tuple[str, ...] = ("sample", "dedup", "gather", "train")


def sample_expected_bytes(batch_size: int, fanouts: Sequence[int],
                          index_bytes: int = 4) -> int:
    """Bytes a fanout neighbor-sampling pass must touch.

    Per hop, each frontier node reads its CSR degree (two ``indptr``
    entries) and ``fanout`` neighbor ids from ``indices``, and writes
    the sampled node + edge ids.  Frontier sizes are the no-dedup
    expansion ``batch * prod(fanouts[:i])`` — the worst case the padded
    capacities are sized for.
    """
    batch_size = int(batch_size)
    total = batch_size * index_bytes            # seed ids read
    frontier = batch_size
    for f in fanouts:
        total += frontier * 2 * index_bytes     # indptr bounds
        total += frontier * int(f) * index_bytes  # neighbor ids read
        total += frontier * int(f) * 2 * index_bytes  # node + edge out
        frontier *= int(f)
    return total


def dedup_expected_bytes(num_ids: int, index_bytes: int = 4,
                         passes: int = 4) -> int:
    """Bytes for the unique-first-occurrence pass over ``num_ids`` ids.

    A sort-based unique reads and writes the id vector ~``passes``
    times (sort + segment marks + scatter of the inverse map).
    """
    return int(num_ids) * index_bytes * int(passes)


def gather_expected_bytes(rows: int, dim: int, itemsize: int = 4) -> int:
    """Payload bytes of a feature gather: the useful rows the model
    consumes (the numerator every ``gather_gb_s`` variant shares)."""
    return int(rows) * int(dim) * int(itemsize)


def train_expected_bytes(param_bytes: int, batch_feature_bytes: int
                         ) -> int:
    """Analytic floor for one optimizer step: parameters are read by
    the forward pass, their gradients written and read, and the adam
    moments read+written (~5x params), plus the batch features read
    twice (forward + backward recompute/use)."""
    return 5 * int(param_bytes) + 2 * int(batch_feature_bytes)


def param_nbytes(params) -> int:
    """Total bytes of a jax/flax parameter pytree (lazy jax import)."""
    import jax

    return int(sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(params)
                   if hasattr(x, "size")))


def compiled_cost_bytes(fn, *args) -> Optional[float]:
    """XLA's own ``bytes accessed`` for ``fn(*args)`` where available.

    ``fn`` must be a jitted callable.  Returns None when the backend /
    jax version exposes no cost analysis — callers fall back to the
    analytic model.  Never raises: attribution is advisory.
    """
    try:
        cost = fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):      # older jax: per-device
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            return None
        v = cost.get("bytes accessed")
        return float(v) if v is not None and v > 0 else None
    except Exception:  # noqa: BLE001 — advisory; analytic model covers
        return None


def stage_roofline_table(stage_ms: Mapping[str, float],
                         stage_bytes: Mapping[str, float],
                         memcpy_gb_s: float) -> Dict[str, dict]:
    """Fold per-stage times + expected bytes into the roofline table.

    Returns ``{stage: {"ms", "gb", "gb_s", "roofline_frac"}}`` for
    stages present in BOTH mappings (an unmeasured stage is omitted,
    never emitted as a sentinel — the ``prune_unmeasured`` contract).
    """
    table: Dict[str, dict] = {}
    for stage in stage_ms:
        ms = stage_ms[stage]
        nbytes = stage_bytes.get(stage)
        if nbytes is None or ms is None or ms <= 0 or nbytes <= 0:
            continue
        gb = float(nbytes) / 1e9
        gb_s = gb / (float(ms) / 1e3)
        frac = gb_s / memcpy_gb_s if memcpy_gb_s > 0 else 0.0
        table[stage] = {
            "ms": round(float(ms), 3),
            "gb": round(gb, 6),
            "gb_s": round(gb_s, 3),
            "roofline_frac": round(frac, 4),
        }
    return table


def flat_roofline_fracs(table: Mapping[str, dict],
                        skip: Sequence[str] = ()) -> Dict[str, float]:
    """``{stage}_roofline_frac`` keys for the bench JSON / regress.py
    (``skip`` keeps pre-existing headline keys authoritative)."""
    return {f"{stage}_roofline_frac": row["roofline_frac"]
            for stage, row in table.items() if stage not in skip}
