"""Tracing core: nested host-side spans -> Chrome-trace / Perfetto JSON.

A :class:`Tracer` collects complete-events (``ph: "X"``) from ``with
span(...)`` blocks; the export loads directly into ``chrome://tracing``
or https://ui.perfetto.dev, and ``python -m glt_tpu.obs summarize``
renders a per-span aggregate table.

Two rules make spans safe around jit:

  * **Host-side only.**  Never open a span (or touch a metric) inside a
    jit-traced function — the call runs once at trace time and vanishes
    from the compiled program.  gltlint GLT010 ``span-in-traced-code``
    enforces this statically.
  * **Explicit device fencing.**  jax dispatch is async, so a span
    around a jitted call measures *dispatch*, not execution.  Register
    the call's outputs with ``span.fence(out)`` and the span's close
    waits for them: ``jax.block_until_ready`` first, then a **host value
    fetch** — under the axon tunnel ``block_until_ready`` returns before
    the device finishes (the bench.py:33 caveat; verified there with a
    matmul chain), and only a host fetch provably waits.  The span then
    records both the dispatch slice and the device wait in ``args``.

When no tracer is installed, ``span()`` returns a shared no-op object —
one module-global read per call, cheap enough to leave in hot loops.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Any, Dict, List, Optional

# Whole-array host fetches are the provable sync, but fetching a padded
# frontier or a feature block through the tunnel would distort the span;
# above this element count only one element is pulled (its value still
# chains the whole computation).
_FETCH_MAX_ELEMS = 4096


def _device_fence(token_groups: List[Any]) -> None:
    """Wait until every registered device value is actually computed."""
    import jax
    import numpy as np

    leaves: List[Any] = []
    for tokens in token_groups:
        leaves.extend(jax.tree_util.tree_leaves(tokens))
    arrs = [a for a in leaves if isinstance(a, jax.Array)]
    if not arrs:
        return
    jax.block_until_ready(arrs)
    for a in arrs:
        if getattr(a, "size", 0) <= _FETCH_MAX_ELEMS:
            np.asarray(jax.device_get(a))
        else:
            np.asarray(jax.device_get(a.ravel()[0]))


def _gen_trace_id() -> str:
    """A fresh 64-bit trace id (hex) — unique across processes."""
    return os.urandom(8).hex()


class Span:
    """One timed region; use as a context manager (see :func:`span`)."""

    __slots__ = ("_tracer", "name", "_attrs", "_t0_ns", "_tokens", "_depth",
                 "span_id", "trace_id", "_parent_id")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self._attrs = attrs
        self._tokens: Optional[List[Any]] = None
        self.span_id: Optional[int] = None
        self.trace_id: Optional[str] = None
        self._parent_id: Optional[int] = None

    def __enter__(self) -> "Span":
        stack = self._tracer._stack()
        self._depth = len(stack)
        self.span_id = self._tracer._next_span_id()
        if stack:
            parent = stack[-1]
            self._parent_id = parent.span_id
            if self.trace_id is None:
                self.trace_id = parent.trace_id
        stack.append(self)
        self._t0_ns = time.perf_counter_ns()
        return self

    def link(self, trace_id: Optional[str],
             parent_span_id: Optional[int]) -> "Span":
        """Adopt a REMOTE parent (cross-process trace propagation).

        The span joins trace ``trace_id`` as a child of the peer's
        ``parent_span_id`` — ``python -m glt_tpu.obs merge`` uses these
        links to stitch per-process trace files into one causally
        connected tree.  Returns ``self`` for chaining.
        """
        if trace_id:
            self.trace_id = str(trace_id)
        if parent_span_id is not None:
            self._parent_id = int(parent_span_id)
        return self

    def context(self) -> Dict[str, Any]:
        """Wire context for propagating this span to another process:
        ``{"tid": trace id, "sid": this span's id, "ts": send time in
        this process's trace clock (us)}``.  Call inside the ``with``
        block; generates a fresh trace id for a root span."""
        if self.trace_id is None:
            self.trace_id = _gen_trace_id()
        return {"tid": self.trace_id, "sid": self.span_id,
                "ts": self._tracer.now_us()}

    def fence(self, tokens):
        """Register device values to sync before the span closes.

        Returns ``tokens`` unchanged so it drops into assignments:
        ``loss = sp.fence(loss)``.
        """
        if self._tokens is None:
            self._tokens = []
        self._tokens.append(tokens)
        return tokens

    def set(self, **attrs) -> None:
        """Attach key/value attributes to the span's trace args."""
        self._attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb) -> bool:
        dispatch_ns = time.perf_counter_ns() - self._t0_ns
        if self._tokens is not None and exc_type is None:
            _device_fence(self._tokens)
        end_ns = time.perf_counter_ns()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:          # exited out of order; stay consistent
            stack.remove(self)
        args = dict(self._attrs)
        args["depth"] = self._depth
        args["span_id"] = self.span_id
        if self._parent_id is not None:
            args["parent_span_id"] = self._parent_id
        if self.trace_id is not None:
            args["trace_id"] = self.trace_id
        if self._tokens is not None:
            args["dispatch_us"] = round(dispatch_ns / 1e3, 3)
            args["device_wait_us"] = round(
                (end_ns - self._t0_ns - dispatch_ns) / 1e3, 3)
        self._tracer._emit({
            "name": self.name,
            "ph": "X",
            "cat": "glt",
            "ts": round((self._t0_ns - self._tracer._t0_ns) / 1e3, 3),
            "dur": round((end_ns - self._t0_ns) / 1e3, 3),
            "pid": self._tracer.pid,
            "tid": threading.get_ident(),
            "args": args,
        })
        return False


class _NullSpan:
    """Shared no-op span served while no tracer is installed."""

    __slots__ = ()

    span_id = None
    trace_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, tokens):
        return tokens

    def set(self, **attrs):
        pass

    def link(self, trace_id, parent_span_id):
        return self

    def context(self):
        return None


_NULL_SPAN = _NullSpan()


class Tracer:
    """Collects span events; thread-safe (one span stack per thread).

    ``process_name`` labels this process's track in merged traces (the
    Chrome-trace ``process_name`` metadata event); ``now_us`` is the
    tracer's clock — microseconds since the tracer started, the same
    scale every event's ``ts`` uses, and the clock the cross-process
    sync samples (``obs.clock_sync``) are taken in.
    """

    def __init__(self, process_name: Optional[str] = None):
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0_ns = time.perf_counter_ns()
        self.pid = os.getpid()
        self.process_name = process_name
        # Span ids must not collide across the fleet's processes (merge
        # stitches remote parent links by id): random high bits + a
        # process-local counter.
        self._span_id_base = (
            struct.unpack("<Q", os.urandom(8))[0] & ~0xFFFFF)
        self._span_seq = 0

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _next_span_id(self) -> int:
        with self._lock:
            self._span_seq += 1
            return self._span_id_base + self._span_seq

    def now_us(self) -> float:
        """Current time in this tracer's clock (us since tracer start)."""
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def instant(self, name: str, **args) -> None:
        """Emit a zero-duration instant event (``ph: "i"``) — used for
        point occurrences like clock-sync samples, replays, reconnects."""
        self._emit({
            "name": name,
            "ph": "i",
            "s": "t",
            "cat": "glt",
            "ts": round(self.now_us(), 3),
            "pid": self.pid,
            "tid": threading.get_ident(),
            "args": args,
        })

    def _emit(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def metadata_events(self) -> List[dict]:
        """Chrome ``ph: "M"`` metadata naming this process's track.

        Without these, Perfetto renders a merged multi-process trace as
        anonymous numeric pids; with them each process is one named
        track (``client``, ``server``, ``worker0`` ...)."""
        if not self.process_name:
            return []
        return [{
            "name": "process_name",
            "ph": "M",
            "pid": self.pid,
            "tid": 0,
            "args": {"name": self.process_name},
        }]

    def chrome_trace(self) -> dict:
        """The trace as a Chrome-trace-format object (JSON-serializable)."""
        events = sorted(self.events, key=lambda e: e.get("ts", 0.0))
        out = {"traceEvents": self.metadata_events() + events,
               "displayTimeUnit": "ms"}
        # Sidecar identity for `obs merge`: which process wrote this
        # file, and that all ts are tracer-relative (arbitrary origin
        # per process — exactly what the clock alignment estimates).
        out["glt"] = {"pid": self.pid,
                      "process_name": self.process_name,
                      "clock": "tracer_relative_us"}
        return out

    def export(self, path: str) -> str:
        """Write the Chrome-trace JSON to ``path``; returns ``path``.

        Atomic (tmp + ``os.replace``, the checkpoint-store publish
        discipline): a process killed mid-export — exactly the moment
        the crash-time flush runs — leaves the previous complete export
        or none, never a torn JSON that ``obs merge`` chokes on.
        """
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


# -- global tracer ---------------------------------------------------------

_current: Optional[Tracer] = None


def install(tracer: Optional[Tracer]) -> None:
    """Install ``tracer`` as the process-global span sink (None = off)."""
    global _current
    _current = tracer


def current() -> Optional[Tracer]:
    return _current


def start_trace(process_name: Optional[str] = None) -> Tracer:
    """Install (and return) a fresh global tracer.

    ``process_name`` labels this process's track in merged traces
    (e.g. ``"client"``, ``"server"``, ``"worker0"``).
    """
    tracer = Tracer(process_name=process_name)
    install(tracer)
    return tracer


#: Env var: when set to a directory, fleet roles (DistServer, remote
#: loaders, mp sampling workers) auto-start a process-global tracer and
#: export to ``$GLT_OBS_TRACE_DIR/trace-<role>-<pid>.json`` at shutdown.
TRACE_DIR_ENV = "GLT_OBS_TRACE_DIR"


def auto_trace(role: str) -> Optional[str]:
    """Opt-in per-process tracing for fleet roles.

    If :data:`TRACE_DIR_ENV` names a directory, ensure a global tracer
    is running (naming it ``role`` if it has no name yet) and return the
    path this process should export to at teardown; otherwise return
    ``None`` and touch nothing.  Callers hold the path and call
    :func:`auto_trace_export` when the role shuts down.

    Registration also arms the crash-time flush: the first registered
    path installs ``atexit`` + SIGTERM handlers so a killed/preempted
    process still exports its partial trace (see :func:`flush_exports`).
    """
    trace_dir = os.environ.get(TRACE_DIR_ENV)
    if not trace_dir:
        return None
    tracer = _current
    if tracer is None:
        tracer = start_trace(process_name=role)
    elif tracer.process_name is None:
        tracer.process_name = role
    path = os.path.join(trace_dir, f"trace-{role}-{os.getpid()}.json")
    with _flush_lock:
        _flush_paths.add(path)
    _install_crash_handlers()
    return path


def auto_trace_export(path: Optional[str]) -> Optional[str]:
    """Export the global tracer to ``path`` (from :func:`auto_trace`);
    no-op when ``path`` is None or tracing stopped in the meantime."""
    tracer = _current
    if path is None or tracer is None:
        return None
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    return tracer.export(path)


def stop_trace(path: Optional[str] = None) -> Optional[Tracer]:
    """Uninstall the global tracer; export to ``path`` if given."""
    tracer = _current
    install(None)
    if tracer is not None and path is not None:
        tracer.export(path)
    return tracer


# -- crash-time flush -------------------------------------------------------
#
# A preempted/killed fleet process used to lose its spans: the export
# only ran on the role's orderly shutdown path.  Registering a path via
# auto_trace() now arms a one-time atexit + SIGTERM flush, so normal
# interpreter exit AND the polite half of preemption (SIGTERM before the
# SIGKILL grace deadline) both export the partial trace.  SIGKILL itself
# is unflushable by definition — nothing user-space runs — which is why
# the supervisor's PEER-side spans (`supervisor.peer_dead` instants, the
# surviving roles' traces) are the record of a hard-killed process; see
# docs/distributed.md "Fleet supervision".

_flush_lock = threading.Lock()
_flush_paths: set = set()
_handlers_installed = False


def flush_exports(reason: Optional[str] = None) -> List[str]:
    """Export the global tracer to every auto-trace-registered path NOW.

    Idempotent and crash-ordered: exports are atomic (tmp + replace), so
    repeated flushes (supervisor exit path, then atexit) each publish a
    complete snapshot.  ``reason`` is stamped as a ``trace.flush``
    instant so a flushed-early trace is self-describing.  Returns the
    written paths ([] when tracing is off or nothing registered).
    """
    tracer = _current
    with _flush_lock:
        paths = sorted(_flush_paths)
    if tracer is None or not paths:
        return []
    if reason is not None:
        tracer.instant("trace.flush", reason=str(reason))
    written = []
    for path in paths:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            written.append(tracer.export(path))
        except OSError:
            continue    # a dead disk must not mask the original exit
    return written


def _install_crash_handlers() -> None:
    """Arm atexit + SIGTERM flush, once per process.

    The SIGTERM handler flushes, restores the previous disposition, and
    re-raises the signal against this process — so exit status, parent
    supervisors, and any chained handler all observe the genuine signal
    death, with the trace already on disk.  Installed lazily from
    :func:`auto_trace` (import must stay side-effect free); non-main
    threads skip the signal half (Python restricts ``signal.signal`` to
    the main thread — the atexit half still covers orderly exits).
    """
    global _handlers_installed
    with _flush_lock:
        if _handlers_installed:
            return
        _handlers_installed = True
    import atexit
    import signal as _signal

    atexit.register(flush_exports)

    def _on_sigterm(signum, frame):
        flush_exports(reason="sigterm")
        _signal.signal(signum, prev if callable(prev) else
                       _signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    try:
        prev = _signal.signal(_signal.SIGTERM, _on_sigterm)
    except ValueError:      # not the main thread: atexit-only coverage
        pass


def span(name: str, **attrs):
    """A span on the global tracer — the shared no-op when tracing is off.

    >>> with span("loader.sample_dispatch") as sp:
    ...     out = sampler.sample_from_nodes(inp)
    ...     sp.fence(out.num_sampled_edges)   # close waits for the device
    """
    tracer = _current
    if tracer is None:
        return _NULL_SPAN
    return Span(tracer, name, attrs)


# -- validation ------------------------------------------------------------

def validate_chrome_trace(obj: Any) -> List[str]:
    """Structural validity problems of a Chrome-trace object ([] = valid).

    Checks the complete-event contract the exporter emits: required keys,
    non-negative durations/device timings, and — per (pid, tid) — that
    spans strictly nest (no partial overlap), which is what makes the
    Perfetto flame view truthful.
    """
    problems: List[str] = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["top level must be an object with a traceEvents list"]
    events = obj["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    by_tid: Dict[tuple, List[dict]] = {}
    # Required keys per phase: complete events carry timing; instants
    # carry a timestamp; metadata events only name a track.
    required = {"X": ("name", "ph", "ts", "dur", "pid", "tid"),
                "i": ("name", "ph", "ts", "pid", "tid"),
                "M": ("name", "ph", "pid")}
    for i, ev in enumerate(events):
        keys = required.get(ev.get("ph"), ("name", "ph", "ts", "dur",
                                           "pid", "tid"))
        missing = [k for k in keys if k not in ev]
        if missing:
            problems.append(f"event {i} missing keys {missing}")
            continue
        if ev["ph"] != "X":
            continue
        if ev["dur"] < 0:
            problems.append(f"event {i} ({ev['name']}) has negative dur")
        wait = ev.get("args", {}).get("device_wait_us")
        if wait is not None and wait < 0:
            problems.append(
                f"event {i} ({ev['name']}) has negative device_wait_us")
        by_tid.setdefault((ev["pid"], ev["tid"]), []).append(ev)
    eps = 0.5  # us; tolerates equal-microsecond rounding at span edges
    for (pid, tid), evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[tuple] = []   # (end_ts, name)
        for ev in evs:
            end = ev["ts"] + ev["dur"]
            while stack and stack[-1][0] <= ev["ts"] + eps:
                stack.pop()
            if stack and end > stack[-1][0] + eps:
                problems.append(
                    f"tid {tid}: span {ev['name']!r} overlaps "
                    f"{stack[-1][1]!r} without nesting")
                continue
            stack.append((end, ev["name"]))
    return problems
