"""Continuous perf-regression tracking over the committed bench history.

The repo carries one ``BENCH_r*.json`` snapshot per growth round — the
regression signal nothing read until now (``overlap_speedup`` sat at
0.97–0.99 for three rounds without anyone being told).  This module
turns that history plus an optional fresh ``bench.py`` run into a
markdown trend table and a direction-aware regress/improve verdict;
``scripts/bench_compare.py`` is the CLI and CI (advisory job
``bench-compare``) runs it on every push.

Three ideas, all deliberately simple and stdlib-only:

* **Direction awareness.**  ``*_ms`` down is good, ``*_gb_s`` /
  ``*_frac`` up is good; metrics with no inherent direction (capacity
  choices, occupancy counts, tunnel weather) are tracked but never
  verdicted.  :func:`direction` resolves explicit names first, then
  suffix/infix conventions.

* **Noise tolerance.**  A metric regresses only when the latest value
  is worse than the history's median by more than
  ``max(rel_tol * |median|, noise_k * sigma)`` where ``sigma`` is a
  robust spread (MAD) of the prior rounds — one noisy round does not
  page anyone, a real step change does.

* **Stuck detection.**  Some metrics have a *target*, not just a
  direction (:data:`ASPIRATIONS`): ``best_step_ms`` must reach the
  train-bound ~40 ms for the gather-wall work to be done.  A metric
  that is flat across the recent rounds while failing its target is
  flagged ``stuck`` — the "nothing regressed, but nothing is getting
  better either" state a pure-delta check never reports.  (This is the
  mechanism that finally killed the overlapped path: three flat rounds
  of ``overlap_speedup`` 0.97–0.99 against a >= 1.05 target.)
"""
from __future__ import annotations

import json
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

UP = 1        # bigger is better
DOWN = -1     # smaller is better
NEUTRAL = 0   # tracked, never verdicted

#: Exact-name directions (override every convention below).
#: ``overlap_speedup`` is RETIRED (not merely unlisted): the overlapped
#: epoch driver was deleted after three rounds stuck at 0.97-0.99 — the
#: fused scanned route is the only epoch driver now, and ``best_step_ms``
#: below tracks the headline instead.  The metric will show as ``gone``
#: in trend tables spanning the deletion; that is the honest reading.
EXPLICIT_DIRECTIONS: Dict[str, int] = {
    "value": UP,
    "vs_baseline": UP,
    "vs_ref_cpu": UP,
    "best_step_ms": DOWN,
    "scanned_step_ms": DOWN,
    "dist_scanned_step_ms_tpu": DOWN,
    "cache_hit_rate": UP,
    "cache_hit_rate_cold": UP,
    "est_hbm_fraction": UP,
    "gather_roofline_frac": UP,
    # Per-stage attribution (ISSUE 13, glt_tpu/obs/attrib.py): every
    # stage's achieved fraction of the memcpy ceiling tracks UP — a
    # drop means that stage got further from the machine.
    "sample_roofline_frac": UP,
    "dedup_roofline_frac": UP,
    "train_roofline_frac": UP,
    # Sampling-wall A/B (ISSUE 15, ops/sample_pallas.py +
    # ops/fused_frontier.py): both sides of the neighbor-read kernel
    # race track DOWN (the _xla/_pallas endings dodge the _ms suffix
    # rule, so they are pinned here), each path's delivered fraction of
    # memcpy tracks UP, and the one-dispatch dedup+gather must beat (or
    # at least not lose ground to) its two-pass unfused twin.
    "sample_ms_xla": DOWN,
    "sample_ms_pallas": DOWN,
    "sample_roofline_frac_xla": UP,
    "sample_roofline_frac_pallas": UP,
    "fused_frontier_ms": DOWN,
    "fused_frontier_ms_unfused": DOWN,
    "scanned_fused_step_ms": DOWN,
    "obs_disabled_overhead_frac": DOWN,
    "sampling_overhead_frac": DOWN,
    "sampling_overhead_frac_epoch": DOWN,
    "ckpt_overhead_frac": DOWN,
    "ckpt_bytes": NEUTRAL,
    "overflow_rate": DOWN,
    "dist_routing_overhead": DOWN,
    "obs_noop_ns_per_call": DOWN,
    # Hierarchical ICI/DCN routing A/B (ISSUE 17, parallel/dist_sampler
    # HierarchicalRouting): both step timings track DOWN; the point of
    # the dedup-then-exchange plan is the cross-host byte count, so
    # dcn_bytes_hier tracks DOWN while the flat reference is a workload
    # reading (NEUTRAL), and the measured zipf-frontier dedup factor
    # (flat request slots / host-unique DCN slots) tracks UP.
    "dist_flat_step_ms": DOWN,
    "dist_hier_step_ms": DOWN,
    "dcn_bytes_flat": NEUTRAL,
    "dcn_bytes_hier": DOWN,
    "hier_dedup_factor": UP,
    # Serving SLO metrics (benchmarks/bench_serving.py, docs/serving.md):
    # latency quantiles down-good, the coalescing win up-good.
    "serving_p50_ms": DOWN,
    "serving_p99_ms": DOWN,
    "serving_p99_light_ms": DOWN,
    "serving_single_ms": DOWN,
    "serving_coalesce_speedup": UP,
    "serving_rps_coalesced": UP,
    "serving_rps_per_request": NEUTRAL,
    "serving_overload_reject_frac": NEUTRAL,
    "serving_offered_rps": NEUTRAL,
    # Disk feature tier (benchmarks/bench_cold_tier.py, docs/storage.md):
    # DRAM residency should absorb traffic (hit rate up-good); epoch
    # wall time down-good; raw tier byte counts are workload readings.
    "dram_hit_rate": UP,
    "store_epoch_ms": DOWN,
    "disk_bytes_per_epoch": NEUTRAL,
    "bytes_from_dram": NEUTRAL,
    "bytes_from_disk": NEUTRAL,
    "bytes_from_hbm": NEUTRAL,
    "store_budget_bytes": NEUTRAL,
    # Device telemetry (ISSUE 14, glt_tpu/obs/device.py +
    # compilewatch.py): measured peak HBM use is a workload property
    # (NEUTRAL) but bounded by CEILINGS below; steady-state epochs must
    # recompile ZERO programs, so the per-epoch compile count tracks
    # DOWN with a <= 0 aspiration.
    "hbm_peak_bytes": NEUTRAL,
    "hbm_bw_gb_s": NEUTRAL,
    "hbm_fraction_measured": UP,
    "compile_count_epoch": DOWN,
    # Environment / configuration readings — not better or worse.
    "tunnel_rtt_ms": NEUTRAL,
    "dedup_ratio": NEUTRAL,
    "cap_fraction": NEUTRAL,
    "occupancy_p50": NEUTRAL,
    "occupancy_p99": NEUTRAL,
    "node_cap_full": NEUTRAL,
    "node_cap_calibrated": NEUTRAL,
    "cache_capacity_rows": NEUTRAL,
    "epoch_batches": NEUTRAL,
    "scanned_group": NEUTRAL,
    # Compressed tiers + whole-graph refresh (ISSUE 18,
    # benchmarks/bench_cold_tier.py, docs/refresh.md): refresh
    # throughput up-good (the `_per_s` suffix would catch it, pinned
    # for the table's sake); tier byte counts are workload readings;
    # staging errors must be zero, so any count tracks DOWN.  The
    # per-codec effective gather bandwidths (`gather_gb_s_effective_*`,
    # logical f32 bytes per second) resolve UP via the `_gb_s_` infix.
    "refresh_nodes_per_s": UP,
    "refresh_bytes_from_hbm": NEUTRAL,
    "refresh_bytes_from_dram": NEUTRAL,
    "refresh_bytes_from_disk": NEUTRAL,
    "refresh_stage_errors": DOWN,
    "gather_effective_speedup_bf16": UP,
    "gather_effective_speedup_int8": UP,
    # Fleet routing + failover (ISSUE 19, benchmarks/bench_fleet.py,
    # docs/serving.md "Fleet"): affinity hit rate up-good and random is
    # its A/B control (a workload reading); the kill-recovery tail and
    # re-convergence time down-good; the structured-reject fraction is
    # a policy reading, but ANY unstructured error is a bug, so that
    # count tracks DOWN (and the bench asserts it is zero).
    "fleet_affinity_hit_rate": UP,
    "fleet_random_hit_rate": NEUTRAL,
    "fleet_affinity_gain": UP,
    "fleet_p99_ms": DOWN,
    "fleet_recovery_s": DOWN,
    "fleet_structured_reject_frac": NEUTRAL,
    "fleet_unstructured_errors": DOWN,
    "fleet_hit_rate_reconverged": UP,
    "fleet_replica_kills": NEUTRAL,
}

#: ``(suffix, direction)`` checked in order after the explicit table.
_SUFFIX_DIRECTIONS: Tuple[Tuple[str, int], ...] = (
    ("_gb_s", UP),
    ("_m_edges_s", UP),
    ("_edges_s", UP),
    ("_tflops", UP),
    ("_per_s", UP),
    ("_speedup", UP),
    ("_frac", UP),
    ("_ms", DOWN),
    ("_ms_per_batch", DOWN),
)

#: ``(infix, direction)`` for width/variant-suffixed families
#: (``gather_gb_s_naive``, ``gather_xla_ms_d128``, ``epoch_s_config1``).
_INFIX_DIRECTIONS: Tuple[Tuple[str, int], ...] = (
    ("_gb_s_", UP),
    ("tflops", UP),
    ("_ms_", DOWN),
    ("epoch_s_", DOWN),
    ("epoch_best", DOWN),
)

#: Metric targets: flat-while-unmet => ``stuck``.  The roofline
#: fraction is ROADMAP item 1's success metric (~within 2x of memcpy);
#: ``best_step_ms`` is its headline (train-bound means <= ~40 ms at the
#: r05 train_ms of 34.8).  The former ``overlap_speedup >= 1.05``
#: aspiration is retired with its path (see EXPLICIT_DIRECTIONS note).
ASPIRATIONS: Dict[str, Tuple[str, float]] = {
    "best_step_ms": ("<=", 40.0),
    "gather_roofline_frac": (">=", 0.5),
    # Preemption-safety must stay ~free at cadence N=50 (ISSUE 8's
    # acceptance bar; benchmarks/bench_resume.py emits the reading).
    "ckpt_overhead_frac": ("<=", 0.05),
    # Serving acceptance bars (ISSUE 9): coalesced dispatch must beat
    # per-request dispatch by >1.5x at saturating load, and the loaded
    # p99 should stay interactive (tracked so a flat miss flags stuck).
    "serving_coalesce_speedup": (">=", 1.5),
    "serving_p99_ms": ("<=", 50.0),
    # Disk tier (ISSUE 12): the warmed stager must absorb at least half
    # of cold traffic in DRAM on the skewed bench workload.
    "dram_hit_rate": (">=", 0.5),
    # Runtime recompile telemetry (ISSUE 14): a steady-state fused
    # epoch compiles nothing — any flat nonzero count is stuck.
    "compile_count_epoch": ("<=", 0.0),
    # Sampling wall (ISSUE 15): the degree-binned kernel should deliver
    # at least 30% of memcpy on the sample stage's expected-bytes floor
    # — flat below that is stuck, exactly like the gather bar above.
    "sample_roofline_frac_pallas": (">=", 0.3),
    # Hierarchical routing (ISSUE 17): the zipf-skewed bench frontier
    # should collapse at least 1.5x of its flat request slots into
    # host-unique DCN slots — flat below that means the per-host dedup
    # is not earning its extra ICI hop.
    "hier_dedup_factor": (">=", 1.5),
    # Compressed tiers (ISSUE 18): int8 rows move 4x fewer wire bytes,
    # so the effective (logical-f32) gather bandwidth should reach at
    # least 2x the raw arm on the same workload — flat below that means
    # the dequant epilogue is eating the transfer win.
    "gather_effective_speedup_int8": (">=", 2.0),
}

#: NEUTRAL-with-ceiling: metrics with no better/worse direction that
#: must still stay under a hard bound.  A NEUTRAL metric normally
#: short-circuits to ``info``; exceeding its ceiling verdicts
#: ``regress`` instead (measured peak HBM use is a workload reading —
#: until it stops fitting the chip).
CEILINGS: Dict[str, float] = {
    "hbm_peak_bytes": 16 * 2**30,     # v5e HBM capacity
}


def direction(metric: str) -> int:
    if metric in EXPLICIT_DIRECTIONS:
        return EXPLICIT_DIRECTIONS[metric]
    for suffix, d in _SUFFIX_DIRECTIONS:
        if metric.endswith(suffix):
            return d
    for infix, d in _INFIX_DIRECTIONS:
        if infix in metric:
            return d
    return NEUTRAL


def load_bench_metrics(path: str) -> Optional[Dict[str, Any]]:
    """The metrics dict of one bench snapshot, or None if unparseable.

    Accepts three shapes: the driver wrapper (``{"parsed": {...}}`` or
    ``{"tail": "...<one JSON line>..."}``), and a raw ``bench.py``
    output line / JSON object (``{"metric": ..., "value": ...}``) as
    written by ``GLT_BENCH_OUT``.
    """
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict):
        if isinstance(obj.get("parsed"), dict):
            return obj["parsed"]
        if "metric" in obj or "value" in obj:
            return obj
        text = obj.get("tail", "")
    # Fall back to the last parseable JSON line (bench stdout capture).
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def _aspiration_met(metric: str, value: float) -> Optional[bool]:
    asp = ASPIRATIONS.get(metric)
    if asp is None:
        return None
    op, target = asp
    return value >= target if op == ">=" else value <= target


def compare(
    runs: Sequence[Tuple[str, Dict[str, Any]]],
    rel_tol: float = 0.05,
    noise_k: float = 3.0,
    flat_tol: float = 0.05,
    flat_window: int = 3,
) -> Dict[str, Any]:
    """Trend + verdict over ``[(label, metrics), ...]`` (oldest first,
    the last run is the one under judgment — typically a fresh bench).

    Returns ``{"labels", "rows", "regressions", "improvements",
    "stuck", "verdict"}``; each row carries the per-run values, the
    baseline (median of prior rounds), the direction-adjusted relative
    delta, and a status in ``regress / improve / stuck / ok / flat /
    new / gone / info``.
    """
    if len(runs) < 2:
        raise ValueError("need at least two runs to compare")
    labels = [label for label, _ in runs]
    ordered: List[str] = []
    seen = set()
    for _, metrics in reversed(runs):      # latest run's order wins
        for k in metrics:
            if k not in seen:
                seen.add(k)
                ordered.append(k)

    rows: List[Dict[str, Any]] = []
    regressions: List[str] = []
    improvements: List[str] = []
    stuck: List[str] = []
    for metric in ordered:
        values: List[Optional[float]] = []
        for _, metrics in runs:
            v = metrics.get(metric)
            values.append(float(v)
                          if isinstance(v, (int, float))
                          and not isinstance(v, bool) else None)
        if all(v is None for v in values):
            continue                        # string metric (paths, units)
        d = direction(metric)
        latest = values[-1]
        prior = [v for v in values[:-1] if v is not None]
        row: Dict[str, Any] = {"metric": metric, "values": values,
                               "direction": d, "baseline": None,
                               "rel_delta": None}
        if latest is None:
            row["status"] = "gone"
            rows.append(row)
            continue
        if not prior:
            row["status"] = "new"
            rows.append(row)
            continue
        baseline = statistics.median(prior)
        row["baseline"] = baseline
        delta = latest - baseline
        rel = delta / abs(baseline) if baseline else (0.0 if not delta
                                                      else float("inf"))
        row["rel_delta"] = rel
        if d == NEUTRAL:
            ceiling = CEILINGS.get(metric)
            if ceiling is not None and latest > ceiling:
                row["status"] = "regress"
                row["ceiling"] = ceiling
                regressions.append(metric)
            else:
                row["status"] = "info"
            rows.append(row)
            continue
        # Robust spread of the history: MAD scaled to sigma.
        if len(prior) >= 2:
            mad = statistics.median(abs(v - baseline) for v in prior)
            sigma = 1.4826 * mad
        else:
            sigma = 0.0
        threshold = max(rel_tol * abs(baseline), noise_k * sigma)
        status = "ok"
        if abs(delta) > threshold:
            status = "improve" if delta * d > 0 else "regress"
        # Stuck: flat over the recent window while missing the target.
        met = _aspiration_met(metric, latest)
        if met is False and status in ("ok", "regress"):
            recent = [v for v in values[-flat_window:] if v is not None]
            if len(recent) >= flat_window:
                center = statistics.median(recent)
                spread = max(recent) - min(recent)
                if abs(center) > 0 and spread <= flat_tol * abs(center):
                    status = "stuck"
        row["status"] = status
        if status == "regress":
            regressions.append(metric)
        elif status == "improve":
            improvements.append(metric)
        elif status == "stuck":
            stuck.append(metric)
        rows.append(row)

    verdict = ("regress" if regressions
               else "improve" if improvements else "ok")
    return {"labels": labels, "rows": rows, "regressions": regressions,
            "improvements": improvements, "stuck": stuck,
            "verdict": verdict}


_STATUS_MARK = {"regress": "🔴 regress", "improve": "🟢 improve",
                "stuck": "🟡 stuck", "ok": "ok", "flat": "ok",
                "new": "new", "gone": "gone", "info": "·"}


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "—"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.4g}"


def markdown_report(report: Dict[str, Any]) -> str:
    """The trend table + verdict as CI-artifact markdown."""
    labels = report["labels"]
    lines = ["# Bench trend report", ""]
    lines.append(f"**Verdict: {report['verdict']}** — "
                 f"{len(report['regressions'])} regressed, "
                 f"{len(report['improvements'])} improved, "
                 f"{len(report['stuck'])} stuck "
                 f"(latest run: `{labels[-1]}`).")
    lines.append("")
    for kind, names in (("Regressions", report["regressions"]),
                        ("Improvements", report["improvements"]),
                        ("Stuck (flat while missing target)",
                         report["stuck"])):
        if names:
            lines.append(f"**{kind}:** " + ", ".join(
                f"`{n}`" for n in names))
            lines.append("")
    header = ["metric"] + labels + ["Δ vs median", "status"]
    lines.append("| " + " | ".join(header) + " |")
    lines.append("|" + "|".join(["---"] * len(header)) + "|")
    for row in report["rows"]:
        rel = row["rel_delta"]
        rel_s = "—" if rel is None else f"{rel:+.1%}"
        cells = ([f"`{row['metric']}`"]
                 + [_fmt(v) for v in row["values"]]
                 + [rel_s, _STATUS_MARK.get(row["status"],
                                            row["status"])])
        lines.append("| " + " | ".join(cells) + " |")
    lines.append("")
    lines.append("Directions: `*_ms` down-good, `*_gb_s`/`*_frac`/"
                 "throughput up-good; `·` rows are tracked but "
                 "directionless.  Thresholds are noise-tolerant "
                 "(median ± max(rel_tol, 3·MAD)); see "
                 "`glt_tpu/obs/regress.py`.")
    return "\n".join(lines)
