"""Metrics registry: counters / gauges / histograms, one namespace.

Unifies the engine's scattered stats — ``feature_cache.cache_stats``,
``RemoteNeighborLoader.epoch_stats``, routing/collective timings,
reconnect/lease/replay-window counters — under dotted ``glt.*`` names
(catalog: docs/observability.md).  Design constraints, in order:

  1. **Near-zero cost when disabled.**  Metrics are OFF by default; a
     disabled ``Counter.inc()`` is one module-global read and a branch
     (~100 ns) — measured and reported by ``bench.py`` as
     ``obs_noop_ns_per_call`` / ``obs_disabled_overhead_frac``, and
     bounded by the overhead smoke test in ``tests/test_obs.py``.
  2. **Host-side only.**  Never call these inside a jit-traced function:
     the Python call runs once at trace time and vanishes from the
     compiled program (gltlint GLT010 ``span-in-traced-code`` flags it).
     Device-side quantities ride as device scalars (the feature cache's
     hit/miss counters) and are *published* here from host code after a
     sync point.
  3. **Stdlib only.**  No jax/numpy imports — usable from the analysis
     CI image and from pure-host tooling.

Instruments are process-global and identified by ``(kind, name,
labels)``; re-requesting one returns the same object, so modules create
them at import time and hot loops pay only the method call.  A
Prometheus-style text exposition (:func:`render_prometheus`) backs the
``get_metrics`` op on :class:`~glt_tpu.distributed.dist_server.DistServer`.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Mapping, Optional, Tuple

_enabled = False


def enable() -> None:
    """Turn metric recording on, process-wide."""
    global _enabled
    _enabled = True


def disable() -> None:
    """Turn metric recording off (instruments keep their values)."""
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


# Geometric-ish latency buckets in milliseconds: spans the ~0.1 ms
# dispatch floor through multi-second epochs.
DEFAULT_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                      100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Mapping[str, str]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    kind = ""

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Mapping[str, str]] = None):
        self.name = name
        self.help = help
        self.labels: Dict[str, str] = dict(labels or {})
        self._lock = threading.Lock()

    def _suffix(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))
        return "{" + inner + "}"

    @property
    def full_name(self) -> str:
        return self.name + self._suffix()


class Counter(_Instrument):
    """Monotonic count (``inc``).  Snapshot value: the running total."""
    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Instrument):
    """Point-in-time value (``set`` / ``inc``)."""
    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class _NullTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


class _Timer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe((time.perf_counter() - self._t0) * 1e3)
        return False


class Histogram(_Instrument):
    """Cumulative-bucket histogram (Prometheus semantics).

    ``observe(v)`` records one value; ``time()`` is a context manager
    observing the block's wall time in **milliseconds** (a shared no-op
    object when disabled, so instrumented loops pay nothing).
    """
    kind = "histogram"

    def __init__(self, name, help="", labels=None,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS_MS):
        super().__init__(name, help, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def time(self):
        if not _enabled:
            return _NULL_TIMER
        return _Timer(self)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1) by linear interpolation
        within the cumulative buckets (Prometheus
        ``histogram_quantile`` semantics).

        The estimate lands inside the bucket containing the target rank,
        interpolated between the bucket's bounds (lower bound 0 for the
        first bucket); ranks in the +Inf tail return the highest finite
        bucket edge.  NaN on an empty histogram.
        """
        with self._lock:
            counts = list(self._counts)
        return quantile_from_counts(self.buckets, counts, q)


def quantile_from_counts(buckets: Tuple[float, ...],
                         counts: List[int], q: float) -> float:
    """Quantile over raw per-bucket counts (``len(buckets) + 1`` entries,
    +Inf tail last) — the interpolation :meth:`Histogram.quantile` and
    the SLO monitor's windowed bucket deltas share.  NaN when empty."""
    q = min(max(float(q), 0.0), 1.0)
    count = sum(counts)
    if count == 0:
        return float("nan")
    target = q * count
    cum = 0.0
    for i, c in enumerate(counts[:-1]):
        prev = cum
        cum += c
        if cum >= target and c > 0:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            return lo + (hi - lo) * (target - prev) / c
    return buckets[-1]


class Registry:
    """Process-global instrument table, keyed by ``(kind, name, labels)``."""

    def __init__(self):
        self._table: Dict[Tuple[str, str, _LabelKey], _Instrument] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, labels, **kw):
        key = (cls.kind, name, _label_key(labels))
        with self._lock:
            inst = self._table.get(key)
            if inst is None:
                inst = cls(name, help=help, labels=labels, **kw)
                self._table[key] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Mapping[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Mapping[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Mapping[str, str]] = None,
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS_MS
                  ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._table.values())

    def reset(self) -> None:
        """Zero every instrument IN PLACE (tests).

        Module-level instruments are created once at import and held by
        the hot paths forever; dropping the table would silently detach
        those live handles from every later snapshot, so reset clears
        values, not registrations.
        """
        for inst in self.instruments():
            with inst._lock:
                if isinstance(inst, Histogram):
                    inst._counts = [0] * (len(inst.buckets) + 1)
                    inst._sum = 0.0
                    inst._count = 0
                else:
                    inst._value = 0.0

    def snapshot(self) -> Dict[str, float]:
        """Flat ``{name[{labels}]: value}`` view; histograms contribute
        ``<name>.count`` and ``<name>.sum`` plus derived
        ``.p50/.p95/.p99`` latency quantiles once they hold samples —
        the SLO read ``bench_serving``-class consumers want without
        re-deriving from buckets."""
        out: Dict[str, float] = {}
        for inst in self.instruments():
            if isinstance(inst, Histogram):
                out[inst.full_name + ".count"] = float(inst.count)
                out[inst.full_name + ".sum"] = float(inst.sum)
                if inst.count:
                    out[inst.full_name + ".p50"] = inst.quantile(0.50)
                    out[inst.full_name + ".p95"] = inst.quantile(0.95)
                    out[inst.full_name + ".p99"] = inst.quantile(0.99)
            else:
                out[inst.full_name] = float(inst.value)
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        by_name: Dict[str, List[_Instrument]] = {}
        for inst in self.instruments():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            group = by_name[name]
            pname = _prom_name(name)
            kind = group[0].kind
            if kind == "counter":
                pname += "_total"
            help_text = next((g.help for g in group if g.help), "")
            if help_text:
                lines.append(f"# HELP {pname} {help_text}")
            lines.append(f"# TYPE {pname} {kind}")
            for inst in group:
                if isinstance(inst, Histogram):
                    base = _prom_name(inst.name)
                    acc = 0
                    for b, c in zip(inst.buckets, inst._counts):
                        acc += c
                        lines.append(
                            f'{base}_bucket{{{_prom_labels(inst, le=b)}}}'
                            f" {acc}")
                    lines.append(
                        f'{base}_bucket{{{_prom_labels(inst, le="+Inf")}}}'
                        f" {inst.count}")
                    lines.append(f"{base}_sum{_prom_label_suffix(inst)}"
                                 f" {inst.sum}")
                    lines.append(f"{base}_count{_prom_label_suffix(inst)}"
                                 f" {inst.count}")
                else:
                    lines.append(
                        f"{pname}{_prom_label_suffix(inst)} {inst.value}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _escape_label_value(v: str) -> str:
    # Text exposition format 0.0.4: inside a quoted label value,
    # backslash, double-quote, and line-feed must be escaped (backslash
    # FIRST, or the other escapes get double-escaped).
    return (v.replace("\\", r"\\")
             .replace('"', r"\"")
             .replace("\n", r"\n"))


def _prom_labels(inst: _Instrument, **extra) -> str:
    items = dict(inst.labels)
    items.update({k: str(v) for k, v in extra.items()})
    return ",".join(f'{k}="{_escape_label_value(str(v))}"'
                    for k, v in sorted(items.items()))


def _prom_label_suffix(inst: _Instrument) -> str:
    if not inst.labels:
        return ""
    return "{" + _prom_labels(inst) + "}"


#: The process-global registry every module-level instrument lands in.
REGISTRY = Registry()


def counter(name: str, help: str = "",
            labels: Optional[Mapping[str, str]] = None) -> Counter:
    return REGISTRY.counter(name, help=help, labels=labels)


def gauge(name: str, help: str = "",
          labels: Optional[Mapping[str, str]] = None) -> Gauge:
    return REGISTRY.gauge(name, help=help, labels=labels)


def histogram(name: str, help: str = "",
              labels: Optional[Mapping[str, str]] = None,
              buckets: Tuple[float, ...] = DEFAULT_BUCKETS_MS) -> Histogram:
    return REGISTRY.histogram(name, help=help, labels=labels,
                              buckets=buckets)


def snapshot() -> Dict[str, float]:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def reset() -> None:
    REGISTRY.reset()


def prune_unmeasured(d: Mapping[str, object]) -> Dict[str, object]:
    """Drop unmeasured (``None``) entries from a metrics mapping.

    The bench's JSON contract: a metric that was not measured this run is
    OMITTED, never emitted as an in-band sentinel (``-1.0`` leaking into
    ``overflow_rate`` was exactly that bug — downstream consumers can't
    tell "not measured" from a measured negative).
    """
    return {k: v for k, v in d.items() if v is not None}
