"""SLO monitor: declarative objectives + multi-window burn-rate alerts.

ROADMAP item 4 wants shed-load and scaling decisions "derived from the
``glt.serving.*`` histograms" — this module is the component that
actually evaluates those histograms against targets.  Specs are
declarative (:class:`SloSpec`), evaluation is windowed (the monitor
samples instrument state on a thread and differences cumulative
counters/buckets over sliding windows), and the output is three-way:

* a structured ``slo.alert`` event into the flight recorder (the
  postmortem sees WHICH objective burned before the crash),
* ``glt.slo.*`` instruments for the Prometheus exposition
  (``glt.slo.firing{slo=...}`` gauge + ``glt.slo.alerts`` counter),
* an ``on_alert`` callback seam — the serving front consumes it to
  shed load (:meth:`~glt_tpu.serving.front.ServingFront.slo_alert`).

**Burn rate** is consumption of the error budget, normalized so 1.0
means "exactly at objective": a ratio spec with objective 0.05 burning
at 2.0 is rejecting 10% of requests; a ``<=`` latency spec burning at
2.0 has a windowed p99 at twice its bound.  An alert FIRES only when
every configured window exceeds its threshold — the classic
multi-window rule: the long window proves sustained damage, the short
window proves it is still happening (so alerts auto-resolve quickly
once the burn stops).

Windowed quantiles come from differencing a histogram's cumulative
bucket counts between two samples — the delta IS the window's
histogram, fed through the same interpolation as
:meth:`~glt_tpu.obs.metrics.Histogram.quantile`.

Stdlib only (usable wherever :mod:`.metrics` is).  All window math uses
``time.monotonic()`` (GLT015: wall clock never measures durations).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import (Any, Callable, Dict, List, Mapping, Optional,
                    Sequence, Tuple)

from . import flight as _flight
from . import metrics as _metrics

#: (window_seconds, burn_threshold) pairs: fast-burn page + slow-burn
#: confirmation, scaled down from the SRE-book hours to engine-loop
#: seconds (a serving incident is over in minutes, not days).
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = ((30.0, 1.0),
                                                    (5.0, 1.0))

_M_ALERTS = _metrics.counter(
    "glt.slo.alerts", "SLO burn alerts fired (all specs)")
_M_TICKS = _metrics.counter(
    "glt.slo.ticks", "SLO monitor evaluation passes")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative objective over existing ``glt.*`` instruments.

    ``kind``:
      * ``"quantile"`` — windowed q-quantile of histogram ``metric``
        compared against ``objective`` (ms, usually).
      * ``"ratio"`` — windowed ``metric`` delta over the windowed
        ``metric + denom`` delta (bad events over total events),
        objective = the budgeted bad fraction.
      * ``"gauge"`` — instantaneous gauge value against ``objective``.

    ``comparison`` is the HEALTHY direction (``"<="``: healthy while
    value <= objective).  ``windows`` is a sequence of
    ``(window_seconds, burn_threshold)``; ALL must exceed to fire.
    ``shed_frac`` rides into the alert payload for admission-control
    consumers.
    """
    name: str
    metric: str
    objective: float
    kind: str = "quantile"
    q: float = 0.99
    denom: Optional[str] = None
    comparison: str = "<="
    windows: Tuple[Tuple[float, float], ...] = DEFAULT_WINDOWS
    shed_frac: float = 0.5

    def __post_init__(self):
        if self.kind not in ("quantile", "ratio", "gauge"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.comparison not in ("<=", ">="):
            raise ValueError(f"comparison must be <= or >=, "
                             f"got {self.comparison!r}")
        if self.kind == "ratio" and not self.denom:
            raise ValueError(f"ratio spec {self.name!r} needs denom")
        if self.objective <= 0:
            raise ValueError(f"objective must be > 0 for burn math "
                             f"(spec {self.name!r})")
        if not self.windows:
            raise ValueError(f"spec {self.name!r} has no windows")


def spec_from_dict(d: Mapping[str, Any]) -> SloSpec:
    """Parse the declarative form documented in docs/observability.md:

        {"name": "serving_p99", "metric": "glt.serving.e2e_ms",
         "kind": "quantile", "q": 0.99, "objective": 50.0,
         "comparison": "<=", "windows": [[30, 1.0], [5, 1.0]]}
    """
    d = dict(d)
    if "windows" in d:
        d["windows"] = tuple((float(w), float(t)) for w, t in d["windows"])
    return SloSpec(**d)


def default_specs(serving_p99_ms: float = 100.0,
                  reject_budget: float = 0.10,
                  step_ms: float = 1000.0,
                  store_hit_rate: float = 0.5) -> List[SloSpec]:
    """The fleet objectives ISSUE 13 names, over existing instruments."""
    return [
        SloSpec(name="serving_p99",
                metric="glt.serving.e2e_ms", kind="quantile", q=0.99,
                objective=serving_p99_ms, comparison="<="),
        SloSpec(name="serving_rejects",
                metric="glt.serving.rejected_overload", kind="ratio",
                denom="glt.serving.requests",
                objective=reject_budget, comparison="<="),
        SloSpec(name="train_step",
                metric="glt.train.block_ms", kind="quantile", q=0.95,
                objective=step_ms, comparison="<="),
        SloSpec(name="store_hit_rate",
                metric="glt.store.hit_rate", kind="gauge",
                objective=store_hit_rate, comparison=">="),
    ]


class _History:
    """Per-spec sample history: (monotonic t, state) tuples, pruned to
    the spec's longest window."""

    def __init__(self, horizon_s: float):
        self.horizon_s = horizon_s
        self.samples: List[Tuple[float, Any]] = []

    def push(self, t: float, state: Any) -> None:
        self.samples.append((t, state))
        cutoff = t - self.horizon_s - 1.0
        while len(self.samples) > 2 and self.samples[1][0] <= cutoff:
            self.samples.pop(0)

    def at_or_before(self, t: float) -> Optional[Tuple[float, Any]]:
        best = None
        for s in self.samples:
            if s[0] <= t:
                best = s
            else:
                break
        return best


class SloMonitor:
    """Evaluate :class:`SloSpec` objectives on a sampling loop.

    ``tick()`` is the whole evaluation pass and is public so tests and
    CI smoke steps drive it deterministically (with an injected ``now``
    to simulate minutes in microseconds); ``start()`` runs it on a
    daemon thread at ``interval_s``.  Alerts go to the flight recorder,
    the ``glt.slo.*`` instruments, and ``on_alert(alert_dict)``.
    """

    def __init__(self, specs: Sequence[SloSpec],
                 interval_s: float = 1.0,
                 on_alert: Optional[Callable[[dict], None]] = None,
                 delta_interval_s: float = 30.0):
        self.specs = list(specs)
        self.interval_s = float(interval_s)
        self.on_alert = on_alert
        self.delta_interval_s = float(delta_interval_s)
        self._hist: Dict[str, _History] = {
            s.name: _History(max(w for w, _ in s.windows))
            for s in self.specs}
        self._firing: Dict[str, bool] = {s.name: False for s in self.specs}
        self._gauges = {
            s.name: _metrics.gauge(
                "glt.slo.firing", "1 while the SLO is in burn alert",
                labels={"slo": s.name})
            for s in self.specs}
        self._last_eval: Dict[str, dict] = {}
        self._last_delta_t: Optional[float] = None
        self._last_snapshot: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ----------------------------------------------------------
    def _observe(self, spec: SloSpec) -> Optional[Any]:
        """Read the spec's instrument state (None: instrument absent)."""
        reg = _metrics.REGISTRY
        if spec.kind == "quantile":
            for inst in reg.instruments():
                if (isinstance(inst, _metrics.Histogram)
                        and inst.full_name == spec.metric):
                    with inst._lock:
                        return tuple(inst._counts)
            return None
        snap = None
        if spec.kind == "ratio":
            snap = _metrics.snapshot()
            bad = snap.get(spec.metric)
            good = snap.get(spec.denom)
            if bad is None and good is None:
                return None
            return (float(bad or 0.0), float(good or 0.0))
        snap = _metrics.snapshot()
        v = snap.get(spec.metric)
        return None if v is None else float(v)

    def _window_value(self, spec: SloSpec, hist: _History,
                      now: float, window_s: float) -> Optional[float]:
        """The spec's measured value over [now - window_s, now]."""
        cur = hist.at_or_before(now)
        if cur is None:
            return None
        if spec.kind == "gauge":
            return float(cur[1])
        past = hist.at_or_before(now - window_s)
        if past is None or past[0] == cur[0]:
            return None
        if spec.kind == "quantile":
            delta = [c - p for c, p in zip(cur[1], past[1])]
            if any(d < 0 for d in delta):     # reset mid-window
                return None
            return _metrics.quantile_from_counts(
                self._buckets_of(spec), delta, spec.q)
        bad = cur[1][0] - past[1][0]
        total = bad + (cur[1][1] - past[1][1])
        if bad < 0 or total <= 0:
            return None
        return bad / total

    def _buckets_of(self, spec: SloSpec) -> Tuple[float, ...]:
        for inst in _metrics.REGISTRY.instruments():
            if (isinstance(inst, _metrics.Histogram)
                    and inst.full_name == spec.metric):
                return inst.buckets
        return _metrics.DEFAULT_BUCKETS_MS

    def _burn(self, spec: SloSpec, value: float) -> float:
        if spec.comparison == "<=":
            return value / spec.objective
        # ">=": burn grows as the value falls below the objective.
        if value <= 0:
            return float("inf")
        return spec.objective / value

    # -- evaluation --------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One sample + evaluation pass; returns alerts EMITTED this
        pass (state transitions only, not steady firing)."""
        now = time.monotonic() if now is None else float(now)
        _M_TICKS.inc()
        emitted: List[dict] = []
        with self._lock:
            for spec in self.specs:
                state = self._observe(spec)
                hist = self._hist[spec.name]
                if state is not None:
                    hist.push(now, state)
                burns: Dict[str, Optional[float]] = {}
                values: Dict[str, Optional[float]] = {}
                all_exceeded = bool(spec.windows)
                for window_s, threshold in spec.windows:
                    v = self._window_value(spec, hist, now, window_s)
                    key = f"{window_s:g}s"
                    values[key] = v
                    if v is None:
                        burns[key] = None
                        all_exceeded = False
                        continue
                    b = self._burn(spec, v)
                    burns[key] = round(b, 4)
                    if not b > threshold:
                        all_exceeded = False
                was = self._firing[spec.name]
                self._last_eval[spec.name] = {
                    "firing": all_exceeded, "burn": burns,
                    "value": values,
                }
                if all_exceeded == was:
                    continue
                self._firing[spec.name] = all_exceeded
                alert = {
                    "slo": spec.name,
                    "state": "firing" if all_exceeded else "resolved",
                    "metric": spec.metric,
                    "objective": spec.objective,
                    "comparison": spec.comparison,
                    "burn": burns,
                    "value": values,
                    "shed_frac": spec.shed_frac if all_exceeded else 0.0,
                }
                emitted.append(alert)
        for alert in emitted:
            self._gauges[alert["slo"]].set(
                1.0 if alert["state"] == "firing" else 0.0)
            if alert["state"] == "firing":
                _M_ALERTS.inc()
            _flight.record("slo.alert", **alert)
            if self.on_alert is not None:
                try:
                    self.on_alert(alert)
                except Exception:  # noqa: BLE001 — the monitor must live
                    pass
        self._record_metric_deltas(now)
        return emitted

    def _record_metric_deltas(self, now: float) -> None:
        """Periodic ``metrics.delta`` flight events: the top changed
        counters since the last delta tick (bounded, so the ring holds
        trend context without drowning the discrete events)."""
        if (self._last_delta_t is not None
                and now - self._last_delta_t < self.delta_interval_s):
            return
        snap = _metrics.snapshot()
        prev, self._last_snapshot = self._last_snapshot, snap
        self._last_delta_t = now
        if not prev:
            return
        changed = {k: round(v - prev.get(k, 0.0), 4)
                   for k, v in snap.items()
                   if abs(v - prev.get(k, 0.0)) > 1e-12}
        if changed:
            top = dict(sorted(changed.items(),
                              key=lambda kv: -abs(kv[1]))[:12])
            _flight.record("metrics.delta", deltas=top)

    # -- queries / lifecycle -----------------------------------------------
    def states(self) -> Dict[str, dict]:
        """Last evaluation per spec (the health table wire ops serve)."""
        with self._lock:
            return {k: dict(v) for k, v in self._last_eval.items()}

    def firing(self) -> List[str]:
        with self._lock:
            return [k for k, v in self._firing.items() if v]

    def start(self) -> "SloMonitor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="glt-slo-monitor")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — sampling must not die
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0 + self.interval_s)
