"""Per-device HBM accounting: gauges, owner classification, leak watch.

Every obs layer so far watches the engine from the host; this module
reads what the *device* reports about itself and publishes it through
the same ``glt.*`` registry the rest of the stack already scrapes:

* :func:`publish_device_stats` — ``glt.device.*`` gauges per device
  (``bytes_in_use``, ``peak_bytes``, ``largest_alloc``, ``num_allocs``,
  plus any pool-level keys the backend exposes) from
  ``device.memory_stats()``.  Backends that return ``None`` (CPU — the
  tier-1 environment) publish **no gauges and never raise**: absent
  data reads as absent, not as zero.
* :func:`snapshot` — classifies ``jax.live_arrays()`` by **owner**
  using shape+dtype fingerprints registered at allocation sites
  (:func:`register_owner`: feature cache, stager, params, serving
  buckets).  Unmatched arrays land in ``other`` so the report always
  sums to the live total.
* :class:`LeakWatch` — epoch-boundary growth detector.  Live bytes
  (``memory_stats()['bytes_in_use']`` where available, the summed
  ``jax.live_arrays()`` sizes otherwise — so the watch works on CPU)
  growing monotonically across ``epochs`` consecutive boundaries is a
  leak suspect: ``device.leak_suspect`` flight event +
  ``glt.device.leak_suspect`` gauge with the growth run length.  The
  gauge clears the moment an epoch stops growing.

Module-level code is stdlib-only; jax imports are lazy and every entry
point degrades to a no-op when jax is absent or a backend call fails —
telemetry must never take the engine down.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from . import flight as _flight
from . import metrics as _metrics

#: memory_stats keys published 1:1 as ``glt.device.<key>`` when present.
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_alloc_size", "num_allocs", "bytes_reserved",
              "largest_free_block_bytes", "pool_bytes", "peak_pool_bytes")
#: ``memory_stats`` spellings vary per backend; map to our gauge names.
_STAT_ALIASES = {"peak_bytes_in_use": "peak_bytes",
                 "largest_alloc_size": "largest_alloc"}

_lock = threading.Lock()
#: ``(shape, dtype) -> owner`` fingerprints, registered at allocation
#: sites.  First registration wins (a fingerprint is only useful while
#: it is unambiguous; later claimants keep their site-local name out).
_owners: Dict[Tuple[Tuple[int, ...], str], str] = {}


def _canon_dtype(dtype) -> str:
    # ``jnp.float32`` (a type), ``np.dtype('float32')``, and the string
    # "float32" must all land on one spelling or fingerprints never
    # match across registration/census sites.
    try:
        import numpy as np
        return str(np.dtype(dtype))
    except Exception:  # noqa: BLE001
        return str(dtype)


def _fingerprint(shape, dtype) -> Tuple[Tuple[int, ...], str]:
    return tuple(int(s) for s in shape), _canon_dtype(dtype)


def register_owner(owner: str, array: Any = None,
                   shape: Optional[Tuple[int, ...]] = None,
                   dtype: Any = None) -> None:
    """Claim a shape+dtype fingerprint for ``owner`` (never raises).

    Call at the allocation site with either the array itself or its
    ``shape``/``dtype``; :func:`snapshot` then attributes any live
    array matching the fingerprint to this owner.
    """
    try:
        if array is not None:
            shape, dtype = array.shape, array.dtype
        fp = _fingerprint(shape, dtype)
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return
    with _lock:
        _owners.setdefault(fp, str(owner))


def owners() -> Dict[Tuple[Tuple[int, ...], str], str]:
    with _lock:
        return dict(_owners)


def reset_owners_for_tests() -> None:
    with _lock:
        _owners.clear()


def _live_arrays() -> List[Any]:
    try:
        import jax
        return list(jax.live_arrays())
    except Exception:  # noqa: BLE001
        return []


def _device_stats() -> List[Tuple[str, Dict[str, float]]]:
    """``[(device_str, memory_stats), ...]`` for devices that report."""
    try:
        import jax
        devices = jax.devices()
    except Exception:  # noqa: BLE001
        return []
    out: List[Tuple[str, Dict[str, float]]] = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if stats:
            out.append((str(dev), dict(stats)))
    return out


def publish_device_stats() -> Dict[str, float]:
    """Set ``glt.device.*`` gauges from ``device.memory_stats()``.

    Returns what was published (flat ``{gauge{device=}: value}``).
    Empty — with NO gauges registered — on backends whose
    ``memory_stats()`` is ``None`` (CPU) or when jax is absent.
    """
    published: Dict[str, float] = {}
    for dev, stats in _device_stats():
        for key in _STAT_KEYS:
            if key not in stats:
                continue
            name = "glt.device." + _STAT_ALIASES.get(key, key)
            try:
                v = float(stats[key])
            except (TypeError, ValueError):
                continue
            g = _metrics.gauge(name, "device memory accounting "
                                     "(memory_stats passthrough)",
                               labels={"device": dev})
            g.set(v)
            published[g.full_name] = v
    return published


def peak_bytes_in_use() -> Optional[int]:
    """Max ``peak_bytes_in_use`` across reporting devices, else None.

    None (not 0) on CPU — bench.py prunes unmeasured metrics rather
    than publishing a fake zero peak.
    """
    best: Optional[int] = None
    for _, stats in _device_stats():
        v = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if v is None:
            continue
        best = int(v) if best is None else max(best, int(v))
    return best


def live_bytes() -> int:
    """Total live-array bytes: device-reported where possible, the
    summed ``jax.live_arrays()`` sizes otherwise (CPU fallback)."""
    reported = [s.get("bytes_in_use") for _, s in _device_stats()]
    reported = [v for v in reported if v is not None]
    if reported:
        return int(sum(reported))
    total = 0
    for arr in _live_arrays():
        try:
            total += int(arr.nbytes)
        except Exception:  # noqa: BLE001
            pass
    return total


def snapshot() -> Dict[str, Any]:
    """Live-array census classified by registered owner fingerprints.

    ``{"total": {count, bytes}, "owners": {owner: {count, bytes}},
    "devices": {device: stats...}}`` — ``other`` absorbs every live
    array no fingerprint claims, so owners always sum to the total.
    Empty-but-well-formed when jax is absent.
    """
    with _lock:
        fps = dict(_owners)
    by_owner: Dict[str, Dict[str, int]] = {}
    total_n = 0
    total_b = 0
    for arr in _live_arrays():
        try:
            fp = _fingerprint(arr.shape, arr.dtype)
            nbytes = int(arr.nbytes)
        except Exception:  # noqa: BLE001
            continue
        owner = fps.get(fp, "other")
        slot = by_owner.setdefault(owner, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
        total_n += 1
        total_b += nbytes
    return {
        "total": {"count": total_n, "bytes": total_b},
        "owners": by_owner,
        "devices": {dev: stats for dev, stats in _device_stats()},
    }


class LeakWatch:
    """Monotonic live-bytes growth across epoch boundaries.

    Call :meth:`observe_epoch` once per epoch.  ``epochs`` consecutive
    boundary-to-boundary increases flag a suspect; the gauge carries
    the current growth-run length (0 when healthy) so dashboards see
    both the binary state and how long the climb has lasted.
    """

    def __init__(self, epochs: int = 3, min_growth_bytes: int = 1):
        self.epochs = max(int(epochs), 1)
        self.min_growth_bytes = max(int(min_growth_bytes), 1)
        self._last: Optional[int] = None
        self._run = 0
        self._lock = threading.Lock()
        self._gauge = _metrics.gauge(
            "glt.device.leak_suspect",
            "consecutive epochs of live-bytes growth "
            "(>= leak-watch threshold => suspect)")

    def observe_epoch(self, live: Optional[int] = None) -> Dict[str, Any]:
        """Record one epoch boundary; returns the watch state."""
        try:
            live = live_bytes() if live is None else int(live)
        except Exception:  # noqa: BLE001
            return {"live_bytes": None, "run": 0, "suspect": False}
        with self._lock:
            grew = (self._last is not None
                    and live - self._last >= self.min_growth_bytes)
            self._run = self._run + 1 if grew else 0
            self._last = live
            run = self._run
        suspect = run >= self.epochs
        self._gauge.set(run if suspect else 0)
        if suspect:
            _flight.record("device.leak_suspect", live_bytes=live,
                           growth_epochs=run, threshold=self.epochs)
        return {"live_bytes": live, "run": run, "suspect": suspect}

    def reset(self) -> None:
        with self._lock:
            self._last = None
            self._run = 0
        self._gauge.set(0)


#: Process-default watch, wired at the scanned-epoch boundary
#: (models/train.py); tests construct their own instances.
_default_watch = LeakWatch()


def observe_epoch() -> Dict[str, Any]:
    """Epoch-boundary hook: default leak watch + device gauges."""
    publish_device_stats()
    return _default_watch.observe_epoch()
