"""Runtime recompile telemetry: count and time XLA compilations.

gltlint GLT003 catches recompile *hazards* statically (unhashable
static args, python scalars re-traced per call); this module closes the
loop at runtime — every XLA compilation the process performs is
counted, timed, and attributed to a **labelled program** so a steady
state that should compile zero times per epoch is a measurable claim
(``compile_count_epoch`` in bench output, tracked DOWN by regress.py
with a ``<= 0`` aspiration).

Two cooperating pieces:

* **Monitoring hook.**  ``jax.monitoring`` fires a duration event per
  backend compilation (``/jax/core/compile/backend_compile_duration``)
  but carries no program identity.  :func:`install` registers one
  listener (idempotent, lazy — no jax import until first use).
* **Label seam.**  A thread-local label stack supplies the identity the
  hook lacks: wrap a jit *call site* (where compilation actually
  happens — first call, or a shape/dtype miss) in
  :func:`label`/``wrap(fn, program)`` and every compilation triggered
  under it lands in ``glt.compile.count{program=...}`` /
  ``glt.compile.ms{program=...}``.  Unwrapped compilations count under
  ``program=unlabelled``.

On top of the per-program counts rides the **recompile-storm
detector**: the same program key compiled more than ``STORM_K`` times
inside ``STORM_WINDOW_S`` seconds raises a ``compile.storm`` flight
event and sets ``glt.compile.storm{program=...}`` — the runtime
signature of the bucket-churn / python-scalar-key bugs GLT003 hunts in
source.  ``glt.compile.recompiles`` (re-compilations of an
already-seen label) over ``glt.compile.first`` (first-time
compilations) is the SLO-able ratio (:func:`storm_ratio_spec`).

Module-level code is stdlib-only (the :mod:`.roofline` pattern); jax
imports happen inside :func:`install`.  All window math uses
``time.monotonic()`` (GLT015).
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Callable, Deque, Dict, Optional

from . import flight as _flight
from . import metrics as _metrics

#: Storm threshold: strictly more than K backend compilations of one
#: program label inside the window is a storm.  One ``jit`` call fires
#: 2-3 backend_compile events (the main program plus small helper
#: programs), so a healthy first compile lands well under K=8 while a
#: per-call re-tracing bug produces dozens per epoch.
STORM_K = 8
STORM_WINDOW_S = 60.0

#: The jax.monitoring event that marks one backend compilation.
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

_M_FIRST = _metrics.counter(
    "glt.compile.first", "first-time XLA compilations (all programs)")
_M_RECOMPILES = _metrics.counter(
    "glt.compile.recompiles",
    "re-compilations of an already-compiled program label")

_tls = threading.local()
_lock = threading.Lock()
_installed = False
_install_failed = False
#: cumulative compile count per program label (monotonic; read by
#: :func:`counts` for the bench/CI "second epoch compiles zero" check).
_counts: Dict[str, int] = {}
#: recent compile stamps per label (storm window) + whether a storm was
#: already reported for the current burst.
_stamps: Dict[str, Deque[float]] = {}
_storm_reported: Dict[str, bool] = {}


def current_label() -> str:
    """The innermost active program label (``unlabelled`` outside any)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else "unlabelled"


def install() -> bool:
    """Register the jax.monitoring compile listener (idempotent).

    Returns True when the listener is (already) active, False when jax
    or its monitoring API is unavailable — callers never need to care.
    """
    global _installed, _install_failed
    if _installed:
        return True
    if _install_failed:
        return False
    with _lock:
        if _installed:
            return True
        try:
            from jax import monitoring as _monitoring
            _monitoring.register_event_duration_secs_listener(_on_event)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            _install_failed = True
            return False
        _installed = True
    return True


def _on_event(event: str, duration_s: float, **kw) -> None:
    if not event.endswith(_COMPILE_EVENT_SUFFIX):
        return
    try:
        _note_compile(current_label(), float(duration_s) * 1000.0)
    except Exception:  # noqa: BLE001 — inside the runtime's hot hook
        pass


def _note_compile(program: str, dur_ms: float,
                  now: Optional[float] = None) -> None:
    now = time.monotonic() if now is None else now
    _metrics.counter("glt.compile.count",
                     "XLA compilations per labelled program",
                     labels={"program": program}).inc()
    _metrics.histogram("glt.compile.ms",
                       "XLA compilation wall time per labelled program",
                       labels={"program": program}).observe(dur_ms)
    with _lock:
        seen = _counts.get(program, 0)
        _counts[program] = seen + 1
        dq = _stamps.setdefault(
            program, collections.deque())
        dq.append(now)
        while dq and now - dq[0] > STORM_WINDOW_S:
            dq.popleft()
        storm = len(dq) > STORM_K
        if not storm:
            _storm_reported[program] = False
        report = storm and not _storm_reported.get(program, False)
        if report:
            _storm_reported[program] = True
        burst = len(dq)
    if seen:
        _M_RECOMPILES.inc()
    else:
        _M_FIRST.inc()
    if report:
        _metrics.gauge("glt.compile.storm",
                       "recompile storm in progress (burst size)",
                       labels={"program": program}).set(burst)
        _flight.record("compile.storm", program=program, count=burst,
                       window_s=STORM_WINDOW_S, threshold=STORM_K)


@contextlib.contextmanager
def label(program: str):
    """Attribute compilations inside the block to ``program``.

    Wrap the *call site* of a jit'd function (compilation happens on
    the first call for each shape/dtype signature, not at decoration).
    Costs a thread-local append/pop when the listener is installed.
    """
    install()
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(str(program))
    try:
        yield
    finally:
        stack.pop()


def wrap(fn: Callable, program: str) -> Callable:
    """``fn`` with every call running under ``label(program)``."""
    def wrapper(*args, **kwargs):
        with label(program):
            return fn(*args, **kwargs)
    wrapper.__name__ = getattr(fn, "__name__", "wrapped")
    return wrapper


def counts(program: Optional[str] = None):
    """Cumulative compile counts: ``{label: n}``, or one label's n."""
    with _lock:
        if program is not None:
            return _counts.get(program, 0)
        return dict(_counts)


def total_compiles() -> int:
    with _lock:
        return sum(_counts.values())


def storm_ratio_spec(objective: float = 0.2, **kw):
    """An :class:`~glt_tpu.obs.slo.SloSpec` over the recompile fraction.

    Ratio semantics match slo.py: ``metric`` is the bad counter,
    ``denom`` the good one, windowed value = bad / (bad + good).  A
    steady-state process recompiles nothing, so any sustained fraction
    above ``objective`` burns.
    """
    from .slo import SloSpec
    return SloSpec(name=kw.pop("name", "compile_storm"),
                   metric="glt.compile.recompiles",
                   denom="glt.compile.first",
                   objective=objective, kind="ratio",
                   comparison="<=", **kw)


def reset_for_tests() -> None:
    """Clear label-seam state (counts/stamps), not the listener."""
    with _lock:
        _counts.clear()
        _stamps.clear()
        _storm_reported.clear()
