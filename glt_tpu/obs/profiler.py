"""Triggered ``jax.profiler`` capture: traces when something is wrong.

Always-on XLA tracing is too heavy for production; never-on tracing
means the trace you need exists only for the run you didn't profile.
This module makes capture **event-driven**: a short, bounded
``jax.profiler`` trace fires exactly when an SLO burns, a step latency
spikes, or an operator asks over the wire — and every capture is
indexed in the flight ring so the postmortem knows which trace belongs
to which incident (``python -m glt_tpu.obs merge`` folds the index
into the merged timeline).

* :func:`capture` — the balanced primitive: ``start_trace`` with
  ``stop_trace`` in ``finally`` (gltlint GLT016 enforces this shape
  tree-wide), optional ``millis`` floor so a trigger path can grab a
  fixed-length window with ``with capture(d, millis=50): pass``.
* :class:`TriggeredProfiler` — rate-limited trigger sink
  (``min_interval_s`` between captures, ``max_captures`` per process)
  with a per-capture index; :meth:`slo_on_alert` adapts it onto the
  :class:`~glt_tpu.obs.slo.SloMonitor` ``on_alert`` seam (one capture
  per firing transition, resolved transitions pass through untouched).
* :class:`SpikeDetector` — the step-latency trigger: observes the same
  stream ``glt.train.block_ms`` records and fires when one block runs
  ``factor``× over the trailing median.
* Module arming — :func:`arm` / :func:`maybe_arm_from_env`
  (``GLT_PROFILE_TRIGGER_DIR``) install a process-default profiler;
  :func:`spike_observe` is the near-zero-cost hook the train loop
  calls per block (a global read + branch while disarmed).

The on-demand path is the ``profile_capture`` wire op on DistServer;
``RemoteServerConnection.profile_capture()`` degrades to ``None``
against a pre-14 server (the mixed-version contract every wire op
follows).  Module-level code is stdlib-only; jax imports live inside
:func:`capture`.
"""
from __future__ import annotations

import collections
import contextlib
import os
import re
import statistics
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from . import flight as _flight
from . import metrics as _metrics

#: Upper bound on a single bounded capture: triggers must never turn
#: into minutes of tracing on a serving host.
MAX_CAPTURE_MILLIS = 2000.0

_M_CAPTURES = _metrics.counter(
    "glt.profiler.captures", "profiler captures completed")
_M_SUPPRESSED = _metrics.counter(
    "glt.profiler.suppressed",
    "profiler triggers suppressed by rate limiting")
_M_SPIKES = _metrics.counter(
    "glt.profiler.spikes", "step-latency spikes detected")


@contextlib.contextmanager
def capture(log_dir: str, millis: Optional[float] = None,
            reason: str = "manual"):
    """Balanced profiler capture into ``log_dir``.

    ``start_trace`` on entry, ``stop_trace`` in ``finally`` — the shape
    GLT016 requires.  With ``millis``, the capture lasts at least that
    long (the trigger paths use ``with capture(d, millis=50): pass``).
    Indexed in the flight ring as a ``profiler.capture`` event.
    """
    from jax import profiler as _jprof
    os.makedirs(log_dir, exist_ok=True)
    t0 = time.monotonic()
    _jprof.start_trace(log_dir)
    try:
        yield log_dir
        if millis is not None:
            remaining = min(float(millis),
                            MAX_CAPTURE_MILLIS) / 1e3 - (
                                time.monotonic() - t0)
            if remaining > 0:
                time.sleep(remaining)
    finally:
        try:
            _jprof.stop_trace()
        finally:
            dur_ms = (time.monotonic() - t0) * 1e3
            _M_CAPTURES.inc()
            _flight.record("profiler.capture", dir=str(log_dir),
                           reason=str(reason), ms=round(dur_ms, 3))


def capture_index(events: Iterable[dict]) -> List[dict]:
    """The ``profiler.capture`` events of a flight event stream —
    the per-incident trace index ``obs merge`` folds into merged
    dumps."""
    return [dict(e) for e in events
            if isinstance(e, dict) and e.get("kind") == "profiler.capture"]


class TriggeredProfiler:
    """Rate-limited capture sink for alert/spike/wire triggers.

    One bounded capture per trigger, at most one per
    ``min_interval_s`` and ``max_captures`` per process — an SLO that
    stays burning produces one trace per firing, not a trace storm on
    top of a latency storm.
    """

    def __init__(self, base_dir: str, millis: float = 50.0,
                 min_interval_s: float = 60.0, max_captures: int = 16):
        self.base_dir = str(base_dir)
        self.millis = min(float(millis), MAX_CAPTURE_MILLIS)
        self.min_interval_s = float(min_interval_s)
        self.max_captures = int(max_captures)
        self.captures: List[Dict[str, Any]] = []
        self._last_t: Optional[float] = None
        self._seq = 0
        self._lock = threading.Lock()

    def trigger(self, reason: str,
                now: Optional[float] = None) -> Optional[str]:
        """Run one bounded capture; returns its dir, or None when
        rate-limited, capped, or the capture itself failed (telemetry
        never raises into the trigger site)."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if (self._last_t is not None
                    and now - self._last_t < self.min_interval_s):
                _M_SUPPRESSED.inc()
                return None
            if len(self.captures) >= self.max_captures:
                _M_SUPPRESSED.inc()
                return None
            self._last_t = now
            self._seq += 1
            seq = self._seq
        slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason))[:64]
        log_dir = os.path.join(self.base_dir, f"capture_{seq:03d}_{slug}")
        try:
            with capture(log_dir, millis=self.millis, reason=reason):
                pass
        except Exception as e:  # noqa: BLE001 — must not raise upward
            _flight.record("profiler.error", reason=str(reason),
                           error=repr(e))
            return None
        entry = {"dir": log_dir, "reason": str(reason), "seq": seq}
        with self._lock:
            self.captures.append(entry)
        return log_dir

    def slo_on_alert(self, downstream: Optional[Callable] = None
                     ) -> Callable[[dict], None]:
        """An ``SloMonitor(on_alert=...)`` adapter: capture once per
        firing transition, then forward the alert to ``downstream``
        (e.g. ``ServingFront.slo_alert``) untouched."""
        def on_alert(alert: dict) -> None:
            try:
                if alert.get("state") == "firing":
                    self.trigger("slo:" + str(alert.get("slo", "?")))
            finally:
                if downstream is not None:
                    downstream(alert)
        return on_alert


class SpikeDetector:
    """Step-latency spike trigger over the ``glt.train.block_ms``
    stream: one block ``factor``× over the trailing median fires."""

    def __init__(self, profiler: Optional[TriggeredProfiler] = None,
                 factor: float = 4.0, min_samples: int = 16,
                 window: int = 64):
        self.profiler = profiler
        self.factor = float(factor)
        self.min_samples = int(min_samples)
        self._recent: collections.deque = collections.deque(
            maxlen=int(window))
        self._lock = threading.Lock()

    def observe(self, ms: float) -> bool:
        """Feed one block latency; True when it is a spike."""
        ms = float(ms)
        with self._lock:
            baseline = (statistics.median(self._recent)
                        if len(self._recent) >= self.min_samples
                        else None)
            self._recent.append(ms)
        spike = baseline is not None and ms > self.factor * max(
            baseline, 1e-3)
        if spike:
            _M_SPIKES.inc()
            _flight.record("profiler.spike", ms=round(ms, 3),
                           baseline_ms=round(baseline, 3),
                           factor=self.factor)
            if self.profiler is not None:
                self.profiler.trigger(f"latency_spike_{ms:.0f}ms")
        return spike


# -- process-default arming -------------------------------------------------
_armed: Optional[TriggeredProfiler] = None
_spike: Optional[SpikeDetector] = None


def arm(base_dir: str, millis: float = 50.0, min_interval_s: float = 60.0,
        max_captures: int = 16, spike_factor: float = 4.0,
        spike_min_samples: int = 16) -> TriggeredProfiler:
    """Install the process-default profiler + spike detector."""
    global _armed, _spike
    prof = TriggeredProfiler(base_dir, millis=millis,
                             min_interval_s=min_interval_s,
                             max_captures=max_captures)
    _armed = prof
    _spike = SpikeDetector(profiler=prof, factor=spike_factor,
                           min_samples=spike_min_samples)
    _flight.record("profiler.armed", dir=str(base_dir), millis=millis)
    return prof


def disarm() -> None:
    global _armed, _spike
    _armed = None
    _spike = None


def armed() -> Optional[TriggeredProfiler]:
    return _armed


def maybe_arm_from_env() -> Optional[TriggeredProfiler]:
    """Arm from ``GLT_PROFILE_TRIGGER_DIR`` if set and not yet armed."""
    if _armed is None:
        base = os.environ.get("GLT_PROFILE_TRIGGER_DIR")
        if base:
            return arm(base)
    return _armed


def spike_observe(ms: float) -> bool:
    """Per-block hook (train loop): global read + branch when
    disarmed."""
    det = _spike
    if det is None:
        return False
    return det.observe(ms)
