"""Wire-protocol verification (GLT024-026): the static **op table**.

The distributed tier speaks a hand-rolled framed RPC: JSON control
requests carrying an ``"op"`` key, dispatched server-side by a chain of
``if op == "...":`` branches (``dist_server.DistServer._handle`` /
``_serve_conn``), answered with JSON dicts or binary frames, and failed
with structured ``{"error":..., "code":...}`` objects that the client
classifies into typed/retryable/fatal.  Nothing ties the two endpoints
together at build time — exactly the drift surface this pass closes.

``extract_op_table`` recovers the contract statically from both sides:

* **server branches** — every function with two or more
  ``op == "<str>"`` (or ``req["op"] == "<str>"``) equality tests is a
  dispatch function; each branch contributes the op name, the union of
  returned dict-literal keys (response keys), and the reply frame kind
  (a branch that mentions a ``_KIND_MSG``/``_KIND_SUB`` constant
  answers with that binary frame instead of JSON);
* **client sites** — every ``*.request(op="<str>", ...)`` call and
  every dict literal containing a constant ``"op"`` key (the
  ``request(**req)`` / raw ``_exchange`` spellings), contributing the
  request key set;
* **protocol versions** — the dispatch branch returning a constant
  ``"protocol"`` key is the hello handshake and fixes the current
  protocol number; a module-level ``POST_HELLO_OPS`` frozenset beside
  the dispatch declares which ops only a current-protocol server
  understands (``min_protocol = 1``; everything else is 0).

Three rules read the table:

* **GLT024 unmatched-wire-op** — a client op with no server branch, or
  a server branch no in-tree client ever sends (endpoint drift);
* **GLT025 unclassified-error-code** — an error ``code`` constructed in
  a dispatch module that no client-side classifier recognizes (an
  explicit ``== "<code>"`` comparison, an ``*_CODES`` set literal, or
  an exception class's ``code`` attribute) — such a code silently falls
  into the generic-fatal path and breaks the exactly-once failover
  discipline, which distinguishes retryable transport from structured
  server verdicts;
* **GLT026 missing-mixed-version-fallback** — a client call site of a
  ``POST_HELLO_OPS`` op outside a ``try`` that catches the unknown-op
  fatal answer (``RuntimeError``) — the house contract degrades those
  to ``None`` / a legacy pin instead of surfacing a new failure mode
  against an older server.

``--format=optable`` dumps the extracted table as the markdown matrix
embedded in docs/distributed.md (CI diffs the two).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .report import Finding
from .rules import Rule, register
from .visitor import ModuleInfo, dotted_expr

# Reserved request keys that ride along every op (trace propagation)
# rather than belonging to one op's schema.
_WIRE_META_PREFIX = "#"

_FRAME_BY_KIND_NAME = {"_KIND_MSG": "msg", "_KIND_SUB": "sub"}


@dataclass
class ClientSite:
    """One place a request for ``op`` is constructed client-side."""
    module: ModuleInfo
    node: ast.AST                  # the call or the dict literal
    scope_node: Optional[ast.AST]  # enclosing function def (for GLT026)
    keys: Set[str] = field(default_factory=set)


@dataclass
class ServerBranch:
    """One ``op == "..."`` dispatch branch."""
    module: ModuleInfo
    node: ast.AST                  # the comparison's If (or the Compare)
    frame: str = "json"
    response_keys: Set[str] = field(default_factory=set)
    response_open: bool = False    # a return spreads **something


@dataclass
class WireOp:
    """One op's merged two-endpoint contract."""
    op: str
    client_sites: List[ClientSite] = field(default_factory=list)
    server: Optional[ServerBranch] = None
    min_protocol: int = 0

    @property
    def frame(self) -> str:
        return self.server.frame if self.server is not None else "json"

    @property
    def request_keys(self) -> Set[str]:
        out: Set[str] = set()
        for site in self.client_sites:
            out |= site.keys
        return out

    @property
    def response_keys(self) -> Set[str]:
        return set(self.server.response_keys) if self.server else set()


@dataclass
class OpTable:
    """The whole extracted protocol, plus the error-code inventory."""
    ops: Dict[str, WireOp] = field(default_factory=dict)
    protocol: int = 0              # current version, from the hello reply
    server_modules: List[ModuleInfo] = field(default_factory=list)
    # error codes: where each server-side code string is constructed,
    # and the set of codes any client-side classifier recognizes
    constructed_codes: List[Tuple[str, ModuleInfo, ast.AST]] = field(
        default_factory=list)
    recognized_codes: Set[str] = field(default_factory=set)

    def wire_op(self, name: str) -> WireOp:
        if name not in self.ops:
            self.ops[name] = WireOp(name)
        return self.ops[name]


# -- extraction -------------------------------------------------------------

def _op_compare_str(node: ast.AST) -> Optional[str]:
    """The string constant of an ``op == "<str>"`` / ``req["op"] ==
    "<str>"`` equality test, else None."""
    if not (isinstance(node, ast.Compare) and len(node.ops) == 1
            and isinstance(node.ops[0], ast.Eq)):
        return None
    left, right = node.left, node.comparators[0]
    if (isinstance(left, ast.Constant)
            and isinstance(left.value, str)):
        left, right = right, left
    if not (isinstance(right, ast.Constant)
            and isinstance(right.value, str)):
        return None
    if isinstance(left, ast.Name) and left.id == "op":
        return right.value
    if (isinstance(left, ast.Subscript)
            and isinstance(left.slice, ast.Constant)
            and left.slice.value == "op"):
        return right.value
    return None


def _const_dict_keys(d: ast.Dict) -> Tuple[Set[str], bool]:
    """(constant string keys, has-dynamic-or-spread-entries)."""
    keys: Set[str] = set()
    open_ended = False
    for k in d.keys:
        if k is None:                       # **spread
            open_ended = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            if not k.value.startswith(_WIRE_META_PREFIX):
                keys.add(k.value)
        else:
            open_ended = True
    return keys, open_ended


def _branch_facts(branch_body: List[ast.stmt]) -> ServerBranch:
    """Frame kind + response keys of one dispatch branch body (the
    statements dominated by the ``op == ...`` test)."""
    facts = ServerBranch(module=None, node=None)  # filled by caller
    for stmt in branch_body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Name)
                    and node.id in _FRAME_BY_KIND_NAME):
                facts.frame = _FRAME_BY_KIND_NAME[node.id]
            if isinstance(node, ast.Return) and isinstance(
                    node.value, ast.Dict):
                keys, open_ended = _const_dict_keys(node.value)
                facts.response_keys |= keys
                facts.response_open |= open_ended
    return facts


def _dispatch_branches(fn: ast.AST) -> List[Tuple[str, ast.If]]:
    """All ``op == "<str>"`` branch tests inside one function body."""
    out: List[Tuple[str, ast.If]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            opname = _op_compare_str(node.test)
            if opname is not None:
                out.append((opname, node))
    return out


def _scan_server(module: ModuleInfo, table: OpTable) -> None:
    is_server = False
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        branches = _dispatch_branches(fn)
        if len(branches) < 2:
            continue
        is_server = True
        for opname, if_node in branches:
            facts = _branch_facts(if_node.body)
            facts.module, facts.node = module, if_node
            wire = table.wire_op(opname)
            if wire.server is None:
                wire.server = facts
            else:                           # split across functions
                wire.server.response_keys |= facts.response_keys
                if facts.frame != "json":
                    wire.server.frame = facts.frame
            if "protocol" in facts.response_keys:
                table.protocol = max(
                    table.protocol, _const_protocol(if_node) or 0)
    if is_server:
        table.server_modules.append(module)


def _const_protocol(if_node: ast.If) -> Optional[int]:
    """The constant value returned under a ``"protocol"`` key."""
    for node in ast.walk(if_node):
        if not (isinstance(node, ast.Return)
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if (isinstance(k, ast.Constant) and k.value == "protocol"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, int)):
                return v.value
    return None


def _scan_clients(module: ModuleInfo, table: OpTable) -> None:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            opname, keys = _request_call_op(node)
        elif isinstance(node, ast.Dict):
            opname, keys = _dict_literal_op(node)
        else:
            continue
        if opname is None:
            continue
        site = ClientSite(module, node, _enclosing_def(module, node),
                          keys=keys)
        table.wire_op(opname).client_sites.append(site)


def _request_call_op(call: ast.Call
                     ) -> Tuple[Optional[str], Set[str]]:
    """``*.request(op="<str>", key=..., _opt=...)`` spellings."""
    fname = (call.func.attr if isinstance(call.func, ast.Attribute)
             else call.func.id if isinstance(call.func, ast.Name)
             else None)
    if fname != "request":
        return None, set()
    opname = None
    keys: Set[str] = set()
    for kw in call.keywords:
        if kw.arg is None:
            continue                        # **req — dict literal scan
        if kw.arg == "op":
            if (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)):
                opname = kw.value.value
        elif not kw.arg.startswith("_"):
            keys.add(kw.arg)
    return opname, keys


def _dict_literal_op(d: ast.Dict) -> Tuple[Optional[str], Set[str]]:
    """A request built as a dict literal: ``{"op": "<str>", ...}``."""
    opname = None
    for k, v in zip(d.keys, d.values):
        if (isinstance(k, ast.Constant) and k.value == "op"
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            opname = v.value
    if opname is None:
        return None, set()
    keys, _open = _const_dict_keys(d)
    keys.discard("op")
    return opname, {k for k in keys if not k.startswith("_")}


def _enclosing_def(module: ModuleInfo,
                   node: ast.AST) -> Optional[ast.AST]:
    cur = module.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = module.parents.get(cur)
    return None


def _post_hello_ops(table: OpTable) -> Set[str]:
    """The declared ``POST_HELLO_OPS`` frozenset of the server module:
    ops only a current-protocol server answers (older servers reply
    with the unknown-op fatal error)."""
    gated: Set[str] = set()
    for module in table.server_modules:
        for stmt in ast.iter_child_nodes(module.tree):
            if not isinstance(stmt, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name)
                       and t.id == "POST_HELLO_OPS"
                       for t in stmt.targets):
                continue
            for sub in ast.walk(stmt.value):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)):
                    gated.add(sub.value)
    return gated


# -- error-code inventory ----------------------------------------------------

def _mentions_code(expr: ast.AST) -> bool:
    """Does this expression read an error code?  ``code``,
    ``resp.get("code")``, ``e.code``, ``resp["code"]``."""
    if isinstance(expr, ast.Name):
        return expr.id == "code"
    if isinstance(expr, ast.Attribute):
        return expr.attr == "code"
    if isinstance(expr, ast.Subscript):
        return (isinstance(expr.slice, ast.Constant)
                and expr.slice.value == "code")
    if isinstance(expr, ast.Call):
        return (isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "get"
                and bool(expr.args)
                and isinstance(expr.args[0], ast.Constant)
                and expr.args[0].value == "code")
    return False


def _scan_recognized_codes(module: ModuleInfo, table: OpTable) -> None:
    for node in ast.walk(module.tree):
        # 1. explicit comparison / membership against a code expression
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            left, right = node.left, node.comparators[0]
            if _mentions_code(left):
                if (isinstance(node.ops[0], (ast.Eq, ast.NotEq))
                        and isinstance(right, ast.Constant)
                        and isinstance(right.value, str)):
                    table.recognized_codes.add(right.value)
            elif (_mentions_code(right)
                  and isinstance(node.ops[0], (ast.Eq, ast.NotEq))
                  and isinstance(left, ast.Constant)
                  and isinstance(left.value, str)):
                table.recognized_codes.add(left.value)
        # 2. *_CODES set/frozenset literals (FATAL_CODES and friends)
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id.endswith("_CODES")
                for t in node.targets):
            for sub in ast.walk(node.value):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)):
                    table.recognized_codes.add(sub.value)
        # 3. exception classes carrying a class-level ``code`` attr
        #    (serving.errors: SERVING_CODES is built from these)
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "code"
                                for t in stmt.targets)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)):
                    table.recognized_codes.add(stmt.value.value)


def _scan_constructed_codes(module: ModuleInfo, table: OpTable) -> None:
    """Server-side code constructions: ``code="<str>"`` kwargs,
    assignments to a bare ``code`` name, and ``"code": "<str>"`` dict
    entries — only inside dispatch modules."""
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "code":
                    for c in _str_constants(kw.value):
                        table.constructed_codes.append(
                            (c, module, kw.value))
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "code"
                   for t in node.targets):
                for c in _str_constants(node.value):
                    table.constructed_codes.append((c, module, node))
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Constant) and k.value == "code"):
                    for c in _str_constants(v):
                        table.constructed_codes.append((c, module, v))


def _str_constants(expr: ast.AST) -> List[str]:
    """String constants that can flow into a code value.  The
    attribute-name argument of ``getattr(e, "code", default)`` is a
    field selector, not a code — only the default can flow."""
    skip: Set[int] = set()
    for n in ast.walk(expr):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "getattr" and len(n.args) >= 2):
            skip.add(id(n.args[1]))
    return [n.value for n in ast.walk(expr)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and id(n) not in skip]


def extract_op_table(project) -> OpTable:
    """Build (and memoize on the project) the two-endpoint op table."""
    cached = getattr(project, "_wire_op_table", None)
    if cached is not None:
        return cached
    table = OpTable()
    for name in sorted(project.modules):
        _scan_server(project.modules[name], table)
    for name in sorted(project.modules):
        module = project.modules[name]
        _scan_clients(module, table)
        _scan_recognized_codes(module, table)
    for module in table.server_modules:
        _scan_constructed_codes(module, table)
    gated = _post_hello_ops(table)
    for opname in gated:
        wire = table.wire_op(opname)
        wire.min_protocol = max(table.protocol, 1)
    project._wire_op_table = table
    return table


def format_op_table(table: OpTable) -> str:
    """The markdown matrix embedded in docs/distributed.md (the CI
    drift check diffs this output against the committed block)."""
    lines = [
        "| op | frame | min protocol | request keys | response keys |",
        "|---|---|---|---|---|",
    ]
    for opname in sorted(table.ops):
        wire = table.ops[opname]
        req = ", ".join(sorted(wire.request_keys)) or "—"
        resp = ", ".join(sorted(wire.response_keys))
        if wire.server is not None and wire.server.response_open:
            resp = resp + ", …" if resp else "…"
        if wire.frame != "json" and not resp:
            resp = f"({wire.frame} frame)"
        lines.append(
            f"| `{opname}` | {wire.frame} | {wire.min_protocol} "
            f"| {req} | {resp or '—'} |")
    return "\n".join(lines)


# -- the rules ---------------------------------------------------------------

@register
class UnmatchedWireOp(Rule):
    name = "unmatched-wire-op"
    code = "GLT024"
    description = ("a wire op constructed on one endpoint with no "
                   "counterpart on the other (client/server drift)")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        if project is None:
            return []
        table = extract_op_table(project)
        if not table.server_modules:
            return []                      # no dispatch in this file set
        any_client = any(w.client_sites for w in table.ops.values())
        out: List[Finding] = []
        for opname in sorted(table.ops):
            wire = table.ops[opname]
            if wire.server is None:
                for site in wire.client_sites:
                    if site.module is module:
                        out.append(self.finding(
                            module, site.node,
                            f"client sends op '{opname}' but no server "
                            f"dispatch branch handles it — a current "
                            f"server answers with the unknown-op fatal "
                            f"error"))
            elif not wire.client_sites and any_client:
                if wire.server.module is module:
                    out.append(self.finding(
                        module, wire.server.node,
                        f"server handles op '{opname}' but no in-tree "
                        f"client ever sends it — dead dispatch branch "
                        f"or an endpoint that drifted"))
        return out


@register
class UnclassifiedErrorCode(Rule):
    name = "unclassified-error-code"
    code = "GLT025"
    description = ("a server-side error code no client classifier "
                   "recognizes (falls into the generic-fatal path)")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        if project is None:
            return []
        table = extract_op_table(project)
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for codename, mod, node in table.constructed_codes:
            if mod is not module:
                continue
            if codename in table.recognized_codes:
                continue
            key = (codename, getattr(node, "lineno", 0))
            if key in seen:
                continue
            seen.add(key)
            out.append(self.finding(
                module, node,
                f"error code '{codename}' is constructed here but no "
                f"client classifier recognizes it (no typed mapping, "
                f"no *_CODES membership, no explicit comparison) — it "
                f"degrades to an opaque RuntimeError and the failover "
                f"discipline cannot tell it from a transport fault"))
        return out


@register
class MissingMixedVersionFallback(Rule):
    name = "missing-mixed-version-fallback"
    code = "GLT026"
    description = ("a post-hello op sent without handling the "
                   "unknown-op fatal answer of an older server")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        if project is None:
            return []
        table = extract_op_table(project)
        out: List[Finding] = []
        for opname in sorted(table.ops):
            wire = table.ops[opname]
            if wire.min_protocol < 1:
                continue
            for site in wire.client_sites:
                if site.module is not module:
                    continue
                if self._degrades(module, site):
                    continue
                out.append(self.finding(
                    module, site.node,
                    f"op '{opname}' requires protocol "
                    f">= {wire.min_protocol}, but this send does not "
                    f"handle the unknown-op fatal answer of an older "
                    f"server (wrap it in try/except RuntimeError and "
                    f"degrade to None or pin the peer legacy)"))
        return out

    def _degrades(self, module: ModuleInfo, site: ClientSite) -> bool:
        if _inside_runtime_try(module, site.node):
            return True
        # A request dict built outside the try and sent via
        # ``request(**req)`` / ``_exchange(...)`` inside it: accept the
        # fallback if any send call in the same function is guarded.
        if isinstance(site.node, ast.Dict) and site.scope_node is not None:
            for node in ast.walk(site.scope_node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("request", "_exchange")
                        and _inside_runtime_try(module, node)):
                    return True
        return False


_RUNTIME_NAMES = {"RuntimeError", "Exception", "BaseException"}


def _inside_runtime_try(module: ModuleInfo, node: ast.AST) -> bool:
    """Is ``node`` inside the body of a ``try`` whose handlers catch
    ``RuntimeError`` (directly, via a tuple, or as ``Exception``)?"""
    cur = node
    parent = module.parents.get(cur)
    while parent is not None:
        if isinstance(parent, ast.Try) and _in_try_body(parent, cur):
            for handler in parent.handlers:
                if _handler_catches_runtime(handler):
                    return True
        cur, parent = parent, module.parents.get(parent)
    return False


def _in_try_body(try_node: ast.Try, child: ast.AST) -> bool:
    return any(child is stmt for stmt in try_node.body)


def _handler_catches_runtime(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                        # bare except
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        name = e.id if isinstance(e, ast.Name) else (
            dotted_expr(e) or "").rsplit(".", 1)[-1]
        if name in _RUNTIME_NAMES:
            return True
    return False
