"""Per-function effect summaries, computed bottom-up over the call graph.

For every function in the project the engine derives one
:class:`Summary` answering the questions the transitive rules ask:

* **may-block** — does calling this function possibly park the calling
  thread? (socket ``recv``/``accept``/``connect``/``sendall``,
  ``time.sleep``, ``subprocess`` waits, and the GLT007 class: zero-arg
  ``.get()``/``.join()``/``.wait()`` plus timeout-polling ``.get()``
  loops).  A scope running the GLT007 timeout-and-recheck pattern (a
  liveness probe in scope) is *not* a blocking source for the poll class
  — its waits are bounded by the recheck loop (``bounded_get``).
* **acquires** — which locks (``module.Class.attr`` /
  ``module.NAME`` ids from the symbol table) it may take, directly or
  transitively.
* **host-sync params** — which of its parameters, if traced, reach a
  host transfer/coercion (``np.asarray``, ``int()``, ``.item()``, ...)
  — the GLT001-transitive seed.
* **consumes-key params** — which parameters are consumed as PRNG keys
  (passed to a drawing ``jax.random.*`` call, directly or transitively)
  — the GLT002-transitive seed.
* **launches-collective** — whether a ``jax.lax.p*`` collective runs
  inside (recorded for diagnostics / ``--profile`` output).

Summaries compose along the SCC condensation of the call graph
(callees first); recursive components iterate to a bounded fixpoint.
Effect chains carry a depth and are cut off at :data:`MAX_CHAIN_DEPTH`.
Lock *pairs* — "held A while acquiring B" — are collected into one
global table (`EffectEngine.pairs`) that GLT008 reads.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .callgraph import CallEdge, CallGraph
from .symbols import ClassSymbol, FunctionSymbol, Project, Symbol
from .visitor import (
    FunctionScope,
    ModuleInfo,
    assign_targets,
    dotted_expr,
    traced_names,
    walk_own,
)

# -- the effect vocabulary (shared with rules.py) ---------------------------

HOST_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.copy", "numpy.frombuffer",
    "numpy.ascontiguousarray", "jax.device_get",
}
COERCIONS = {"int", "float", "bool", "complex"}
SYNC_METHODS = {"item", "tolist", "to_py", "block_until_ready"}

KEY_SOURCES = {
    "jax.random.PRNGKey", "jax.random.key", "jax.random.split",
    "jax.random.fold_in", "jax.random.clone", "jax.random.wrap_key_data",
}
# Deriving fresh keys from a base key is the sanctioned way to reuse it.
NON_CONSUMING = {"jax.random.split", "jax.random.fold_in",
                 "jax.random.clone", "jax.random.key_data"}

COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmax", "jax.lax.pmin", "jax.lax.pmean",
    "jax.lax.ppermute", "jax.lax.all_to_all", "jax.lax.all_gather",
    "jax.lax.pshuffle", "jax.lax.axis_index",
}

# Dotted call names that park the calling thread.
BLOCKING_CALLS = {
    "time.sleep": "sleep",
    "socket.create_connection": "connect",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
}
# Method spellings that park the calling thread regardless of receiver.
BLOCKING_METHODS = {
    "recv": "recv", "recv_into": "recv", "recvfrom": "recv",
    "sendall": "send", "accept": "accept", "connect": "connect",
    "communicate": "subprocess",
}
# Synchronous disk-read entry points (GLT014): dotted calls that hit
# storage on the calling thread.  MMAP_CALLS additionally taint the
# assigned name — slicing a memmap is a page-fault disk read even
# though no call appears at the slice site.
DISK_CALLS = {
    "numpy.load": "np.load", "numpy.fromfile": "np.fromfile",
    "numpy.loadtxt": "np.loadtxt", "numpy.memmap": "np.memmap",
    "mmap.mmap": "mmap.mmap",
}
MMAP_CALLS = {"numpy.memmap", "mmap.mmap"}
# File-object read method spellings (receiver-agnostic, like
# BLOCKING_METHODS): .read()/.readinto()/.readline(s)().
DISK_READ_METHODS = {"read", "readinto", "readline", "readlines"}
# Zero-argument spellings of the GLT007 hang class.
WAIT_METHODS = {"get": "get", "join": "join", "wait": "wait"}
# Kinds exempted in a scope that runs the timeout-and-recheck pattern.
POLL_KINDS = frozenset({"get", "join", "wait"})
# A call to any of these (bare name or attribute) marks the scope as a
# liveness-rechecking poll loop; `alive` covers bounded_get-style probe
# parameters.
LIVENESS_NAMES = {"is_alive", "is_set", "poll", "alive"}

MAX_CHAIN_DEPTH = 12
_MAX_BLOCK_SITES = 3
_SCC_FIXPOINT_ROUNDS = 4


@dataclass(frozen=True)
class BlockSite:
    kind: str        # 'recv' | 'send' | 'sleep' | 'get' | ... | 'call'
    line: int
    detail: str      # human chain: "sock.recv()" / "_connect() -> ..."
    depth: int


@dataclass(frozen=True)
class SyncSite:
    line: int
    detail: str
    depth: int


@dataclass(frozen=True)
class PairSite:
    path: str
    line: int
    fid: str
    detail: str


@dataclass(frozen=True)
class Summary:
    """Composable, context-free effect summary of one function."""
    blocking: Tuple[BlockSite, ...] = ()
    disk: Tuple[BlockSite, ...] = ()
    acquires: FrozenSet[str] = frozenset()
    sync_params: Tuple[Tuple[str, SyncSite], ...] = ()
    key_params: FrozenSet[str] = frozenset()
    liveness: bool = False
    collective: bool = False

    def sync_param_map(self) -> Dict[str, SyncSite]:
        return dict(self.sync_params)


EMPTY_SUMMARY = Summary()


@dataclass
class CallFact:
    node: ast.Call
    callee: Optional[Symbol]
    line: int
    held: Tuple[str, ...]


@dataclass
class ScopeFacts:
    """Direct (intraprocedural) facts about one function scope."""
    fid: str
    module: ModuleInfo
    scope: FunctionScope
    blocks: List[Tuple[BlockSite, Tuple[str, ...]]] = field(
        default_factory=list)
    disk: List[BlockSite] = field(default_factory=list)
    calls: List[CallFact] = field(default_factory=list)
    acquisitions: List[Tuple[str, int]] = field(default_factory=list)
    pairs: List[Tuple[str, str, int]] = field(default_factory=list)
    liveness: bool = False
    collective: bool = False
    influences: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    sync_sites: Dict[str, SyncSite] = field(default_factory=dict)
    key_params: Set[str] = field(default_factory=set)
    type_env: Dict[str, ClassSymbol] = field(default_factory=dict)


def _callee_positional_params(sym: FunctionSymbol,
                              call: ast.Call) -> List[str]:
    """The callee's positional parameter names as seen from this call
    site (bound-method calls skip ``self``/``cls``)."""
    params = sym.scope.params
    if (params[:1] in (["self"], ["cls"])
            and isinstance(call.func, ast.Attribute)):
        return params[1:]
    return params


def _first_line(node: ast.AST) -> int:
    return getattr(node, "lineno", 1)


class EffectEngine:
    """Builds :class:`ScopeFacts` per function, then composes them into
    :class:`Summary` objects bottom-up over the SCC-condensed call graph."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.facts: Dict[str, ScopeFacts] = {}
        self.summaries: Dict[str, Summary] = {}
        self.pairs: Dict[Tuple[str, str], PairSite] = {}
        for name in sorted(project.modules):
            m = project.modules[name]
            for scope in m.scopes:
                if isinstance(scope.node, ast.Lambda):
                    continue
                fid = project.fid_of(scope)
                if fid is None:
                    continue
                self.facts[fid] = self._collect_facts(m, scope, fid)
        edges = [
            CallEdge(fid, self._symbol_fid(cf.callee), cf.line)
            for fid, f in self.facts.items()
            for cf in f.calls
            if cf.callee is not None
            and self._symbol_fid(cf.callee) is not None
        ]
        self.graph = CallGraph(self.facts.keys(), edges)
        for scc in self.graph.sccs():          # callees-first
            rounds = 1 if len(scc) == 1 else _SCC_FIXPOINT_ROUNDS
            for _ in range(rounds):
                changed = False
                for fid in scc:
                    if fid in self.facts and self._compute(fid):
                        changed = True
                if not changed:
                    break

    # -- public ------------------------------------------------------------
    def summary_for(self, sym: Optional[Symbol]) -> Summary:
        fid = self._symbol_fid(sym)
        if fid is None:
            return EMPTY_SUMMARY
        return self.summaries.get(fid, EMPTY_SUMMARY)

    def _symbol_fid(self, sym: Optional[Symbol]) -> Optional[str]:
        if isinstance(sym, FunctionSymbol):
            return sym.fid
        if isinstance(sym, ClassSymbol):     # constructor call
            init = sym.methods.get("__init__")
            return init.fid if init is not None else None
        return None

    # -- fact collection -----------------------------------------------------
    def _collect_facts(self, module: ModuleInfo, scope: FunctionScope,
                       fid: str) -> ScopeFacts:
        facts = ScopeFacts(fid, module, scope)
        facts.type_env = self._build_type_env(module, scope)
        self._walk_body(facts, scope.node.body, (), frozenset(), 0)
        self._sync_and_key_facts(facts)
        self._disk_facts(facts)
        if facts.liveness:
            # GLT007 exemption: a liveness-rechecking scope's poll waits
            # are bounded by the recheck loop, not hang sources.
            facts.blocks = [(b, held) for b, held in facts.blocks
                            if b.kind not in POLL_KINDS]
        return facts

    def _build_type_env(self, module: ModuleInfo, scope: FunctionScope
                        ) -> Dict[str, ClassSymbol]:
        env: Dict[str, ClassSymbol] = {}
        for node in walk_own(scope.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            sym = self.project.resolve_call(module, scope, node.value)
            if not isinstance(sym, ClassSymbol):
                continue
            for t in node.targets:
                d = dotted_expr(t)
                if d is not None:
                    env[d] = sym
        return env

    # the linear walk: statements in source order, lock-hold tracking
    def _walk_body(self, facts: ScopeFacts, body: Sequence[ast.stmt],
                   held: Tuple[str, ...], held_exprs: FrozenSet[str],
                   loop_depth: int) -> None:
        held = tuple(held)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held, new_exprs = held, held_exprs
                for item in stmt.items:
                    self._scan_exprs(facts, item.context_expr, new_held,
                                     loop_depth)
                    lid = self.project.lock_id(
                        facts.module, facts.scope, item.context_expr,
                        facts.type_env)
                    if lid is not None:
                        facts.acquisitions.append((lid, stmt.lineno))
                        for outer in new_held:
                            if outer != lid:
                                facts.pairs.append(
                                    (outer, lid, stmt.lineno))
                        new_held = new_held + (lid,)
                        d = dotted_expr(item.context_expr)
                        if d is not None:
                            new_exprs = new_exprs | {d}
                self._walk_body(facts, stmt.body, new_held, new_exprs,
                                loop_depth)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_exprs(facts, stmt.iter, held, loop_depth)
                self._walk_body(facts, stmt.body, held, held_exprs,
                                loop_depth + 1)
                self._walk_body(facts, stmt.orelse, held, held_exprs,
                                loop_depth)
                continue
            if isinstance(stmt, ast.While):
                self._scan_exprs(facts, stmt.test, held, loop_depth + 1)
                self._walk_body(facts, stmt.body, held, held_exprs,
                                loop_depth + 1)
                self._walk_body(facts, stmt.orelse, held, held_exprs,
                                loop_depth)
                continue
            if isinstance(stmt, ast.If):
                self._scan_exprs(facts, stmt.test, held, loop_depth)
                self._walk_body(facts, stmt.body, held, held_exprs,
                                loop_depth)
                self._walk_body(facts, stmt.orelse, held, held_exprs,
                                loop_depth)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_body(facts, stmt.body, held, held_exprs,
                                loop_depth)
                for h in stmt.handlers:
                    self._walk_body(facts, h.body, held, held_exprs,
                                    loop_depth)
                self._walk_body(facts, stmt.orelse, held, held_exprs,
                                loop_depth)
                self._walk_body(facts, stmt.finalbody, held, held_exprs,
                                loop_depth)
                continue
            # explicit lock.acquire()/.release() adjust the held set for
            # the *following* statements of this body
            adj = self._acquire_release(facts, stmt)
            if adj is not None:
                lid, is_acquire = adj
                if is_acquire:
                    facts.acquisitions.append((lid, stmt.lineno))
                    for outer in held:
                        if outer != lid:
                            facts.pairs.append((outer, lid, stmt.lineno))
                    held = held + (lid,)
                elif lid in held:
                    held = tuple(x for x in held if x != lid)
                continue
            self._scan_exprs(facts, stmt, held, loop_depth,
                             held_exprs=held_exprs)

    def _acquire_release(self, facts: ScopeFacts, stmt: ast.stmt
                         ) -> Optional[Tuple[str, bool]]:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in ("acquire", "release")):
            return None
        lid = self.project.lock_id(facts.module, facts.scope,
                                   stmt.value.func.value, facts.type_env)
        if lid is None:
            return None
        return lid, stmt.value.func.attr == "acquire"

    def _scan_exprs(self, facts: ScopeFacts, node: ast.AST,
                    held: Tuple[str, ...], loop_depth: int,
                    held_exprs: FrozenSet[str] = frozenset()) -> None:
        for sub in walk_own(node):
            if isinstance(sub, ast.Call):
                self._visit_call(facts, sub, held, loop_depth, held_exprs)
        if isinstance(node, ast.Call):       # walk_own skips the root
            self._visit_call(facts, node, held, loop_depth, held_exprs)

    def _visit_call(self, facts: ScopeFacts, call: ast.Call,
                    held: Tuple[str, ...], loop_depth: int,
                    held_exprs: FrozenSet[str]) -> None:
        module = facts.module
        name = module.call_name(call)
        attr = (call.func.attr
                if isinstance(call.func, ast.Attribute) else None)
        bare = call.func.id if isinstance(call.func, ast.Name) else None
        if (attr in LIVENESS_NAMES or bare in LIVENESS_NAMES
                or any(kw.arg == "alive" for kw in call.keywords)):
            facts.liveness = True
        if name in COLLECTIVES:
            facts.collective = True
        if name in DISK_CALLS:
            facts.disk.append(
                BlockSite("disk", call.lineno, f"{DISK_CALLS[name]}()", 0))
        elif attr in DISK_READ_METHODS:
            facts.disk.append(
                BlockSite("disk", call.lineno, f".{attr}()", 0))
        kind = None
        detail = None
        if name in BLOCKING_CALLS:
            kind, detail = BLOCKING_CALLS[name], f"{name}()"
        elif attr in BLOCKING_METHODS:
            kind, detail = BLOCKING_METHODS[attr], f".{attr}()"
        elif attr in WAIT_METHODS and not call.args and not call.keywords:
            kind, detail = WAIT_METHODS[attr], f".{attr}() [no timeout]"
        elif (attr == "get" and loop_depth > 0
              and any(kw.arg == "timeout" for kw in call.keywords)):
            # timeout-polling get in a loop: bounded per wake, unbounded
            # overall — a hang source unless a liveness probe rechecks.
            kind, detail = "get", f".{attr}(timeout=...) poll loop"
        if kind is not None:
            recv = (dotted_expr(call.func.value)
                    if isinstance(call.func, ast.Attribute) else None)
            if not (kind == "wait" and recv is not None
                    and recv in held_exprs):
                # (condition.wait() on the held Condition itself is the
                # sanctioned monitor pattern, not a blocking hazard)
                facts.blocks.append(
                    (BlockSite(kind, call.lineno, detail, 0), held))
        callee = self.project.resolve_call(module, facts.scope, call,
                                           facts.type_env)
        if callee is not None:
            facts.calls.append(
                CallFact(call, callee, call.lineno, held))

    # -- disk-read facts (GLT014) -------------------------------------------
    def _disk_facts(self, facts: ScopeFacts) -> None:
        """Taint names assigned from mmap constructors and record their
        subscript loads as disk sites: slicing a memmap page-faults to
        storage with no call expression at the read site."""
        module, scope = facts.module, facts.scope
        mapped: Set[str] = set()
        for node in walk_own(scope.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = getattr(node, "value", None)
                if (isinstance(value, ast.Call)
                        and module.call_name(value) in MMAP_CALLS):
                    mapped.update(assign_targets(node))
        if not mapped:
            return
        for node in walk_own(scope.node):
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in mapped):
                facts.disk.append(BlockSite(
                    "disk", node.lineno,
                    f"{node.value.id}[...] (mmap page fault)", 0))

    # -- intraprocedural dataflow: host-sync params + key params ------------
    def _sync_and_key_facts(self, facts: ScopeFacts) -> None:
        module, scope = facts.module, facts.scope
        params = [p for p in scope.params if p not in ("self", "cls")]
        infl: Dict[str, FrozenSet[str]] = {
            p: frozenset([p]) for p in params}

        def influence_of(expr: ast.AST) -> FrozenSet[str]:
            out: FrozenSet[str] = frozenset()
            for n in traced_names(expr):
                out |= infl.get(n, frozenset())
            return out

        for _ in range(2):                   # two passes settle chains
            for node in walk_own(scope.node):
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    value = getattr(node, "value", None)
                    if value is None:
                        continue
                    src = influence_of(value)
                    if src:
                        for t in assign_targets(node):
                            infl[t] = infl.get(t, frozenset()) | src
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    src = influence_of(node.iter)
                    if src and isinstance(node.target, ast.Name):
                        infl[node.target.id] = (
                            infl.get(node.target.id, frozenset()) | src)
        facts.influences = infl
        for node in walk_own(scope.node):
            if not isinstance(node, ast.Call):
                continue
            name = module.call_name(node)
            args = list(node.args) + [kw.value for kw in node.keywords]
            consumed: FrozenSet[str] = frozenset()
            detail = None
            if name in HOST_SYNC_CALLS or name in COERCIONS:
                for a in args:
                    consumed |= influence_of(a)
                detail = f"{name}()"
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in SYNC_METHODS):
                consumed = influence_of(node.func.value)
                detail = f".{node.func.attr}()"
            if detail is not None:
                for p in consumed:
                    facts.sync_sites.setdefault(
                        p, SyncSite(node.lineno, detail, 0))
            # direct PRNG-key consumption
            if (name is not None and name.startswith("jax.random.")
                    and name not in NON_CONSUMING):
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in infl \
                            and a.id in params:
                        facts.key_params.add(a.id)
                for kw in node.keywords:
                    if (isinstance(kw.value, ast.Name)
                            and kw.value.id in params):
                        facts.key_params.add(kw.value.id)

    # -- summary composition -------------------------------------------------
    def _compute(self, fid: str) -> bool:
        facts = self.facts[fid]
        blocking: List[BlockSite] = [b for b, _held in facts.blocks]
        disk: List[BlockSite] = list(facts.disk)
        acquires: Set[str] = {lid for lid, _line in facts.acquisitions}
        sync_params: Dict[str, SyncSite] = dict(facts.sync_sites)
        key_params: Set[str] = set(facts.key_params)
        for outer, inner, line in facts.pairs:
            self._record_pair(outer, inner, facts, line,
                              f"'{outer}' held, then '{inner}' acquired "
                              f"in {fid}")
        params = [p for p in facts.scope.params
                  if p not in ("self", "cls")]
        for cf in facts.calls:
            csum = self.summary_for(cf.callee)
            if csum is EMPTY_SUMMARY:
                continue
            short = (cf.callee.short
                     if isinstance(cf.callee, FunctionSymbol)
                     else cf.callee.name)
            if csum.blocking:
                b = csum.blocking[0]
                if b.depth + 1 <= MAX_CHAIN_DEPTH:
                    blocking.append(BlockSite(
                        "call", cf.line,
                        f"{short}() -> {b.detail}", b.depth + 1))
            if csum.disk:
                d = csum.disk[0]
                if d.depth + 1 <= MAX_CHAIN_DEPTH:
                    disk.append(BlockSite(
                        "disk", cf.line,
                        f"{short}() -> {d.detail}", d.depth + 1))
            for outer in cf.held:
                for inner in csum.acquires:
                    if outer != inner:
                        self._record_pair(
                            outer, inner, facts, cf.line,
                            f"'{outer}' held in {fid} while calling "
                            f"{short}() which acquires '{inner}'")
            acquires |= csum.acquires
            if isinstance(cf.callee, (FunctionSymbol, ClassSymbol)):
                self._bind_call_effects(
                    facts, cf, csum, short, params, sync_params,
                    key_params)
        blocking.sort(key=lambda b: (b.depth, b.line))
        disk.sort(key=lambda b: (b.depth, b.line))
        summary = Summary(
            blocking=tuple(blocking[:_MAX_BLOCK_SITES]),
            disk=tuple(disk[:_MAX_BLOCK_SITES]),
            acquires=frozenset(acquires),
            sync_params=tuple(sorted(sync_params.items())),
            key_params=frozenset(key_params),
            liveness=facts.liveness,
            collective=facts.collective or any(
                self.summary_for(cf.callee).collective
                for cf in facts.calls),
        )
        if self.summaries.get(fid) == summary:
            return False
        self.summaries[fid] = summary
        return True

    def _bind_call_effects(self, facts: ScopeFacts, cf: CallFact,
                           csum: Summary, short: str,
                           params: List[str],
                           sync_params: Dict[str, SyncSite],
                           key_params: Set[str]) -> None:
        """Map a callee's parameter-keyed effects back through the call
        site's argument binding onto this function's parameters."""
        callee = cf.callee
        if isinstance(callee, ClassSymbol):
            init = callee.methods.get("__init__")
            if init is None:
                return
            pos = init.scope.params[1:]      # skip self
        else:
            pos = _callee_positional_params(callee, cf.node)
        callee_sync = csum.sync_param_map()

        def bind(arg: ast.expr, pname: str) -> None:
            if pname in callee_sync:
                site = callee_sync[pname]
                if site.depth + 1 <= MAX_CHAIN_DEPTH:
                    for q in traced_names(arg):
                        for p in facts.influences.get(q, ()):  # params
                            sync_params.setdefault(p, SyncSite(
                                cf.line,
                                f"{short}(param '{pname}') -> "
                                f"{site.detail}",
                                site.depth + 1))
            if (pname in csum.key_params and isinstance(arg, ast.Name)
                    and arg.id in params):
                key_params.add(arg.id)

        for i, arg in enumerate(cf.node.args):
            if i < len(pos):
                bind(arg, pos[i])
        for kw in cf.node.keywords:
            if kw.arg is not None:
                bind(kw.value, kw.arg)

    def _record_pair(self, outer: str, inner: str, facts: ScopeFacts,
                     line: int, detail: str) -> None:
        key = (outer, inner)
        site = PairSite(facts.module.path, line, facts.fid, detail)
        cur = self.pairs.get(key)
        if cur is None or (site.path, site.line) < (cur.path, cur.line):
            self.pairs[key] = site
