"""gltlint rules: the TPU/JAX + concurrency hazards this engine hits.

Each rule is a class with a ``check(module, project=None) -> [Finding]``
method, registered in ``RULES`` by name.  Severities: ERROR findings gate
CI (non-zero exit), WARNINGs report but pass.  ``project`` — the
project-wide symbol table / call graph / effect summaries
(analysis/symbols.py) — is provided whenever the CLI analyzes a file
set; rules use it to follow effects through calls (GLT001/GLT002 become
transitive, GLT008/GLT009 — analysis/concurrency.py — are built on it).
Without a project a rule degrades to its intraprocedural behavior.

The intraprocedural analyses are deliberately linear/flow-light:
statements are walked in source order, ``if`` branches fork analysis
state, loops are traversed once.  That trades soundness for a near-zero
false-positive rate on this codebase — every rule here was calibrated by
running it over ``glt_tpu`` and inspecting each hit.  The
interprocedural layer keeps that bias: unresolvable calls contribute no
effects rather than worst-case guesses.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .effects import (
    COERCIONS,
    DISK_CALLS,
    DISK_READ_METHODS,
    HOST_SYNC_CALLS,
    MMAP_CALLS,
    SYNC_METHODS,
)
from .effects import KEY_SOURCES as _KEY_SOURCES_IMPORTED
from .effects import NON_CONSUMING as _NON_CONSUMING_IMPORTED
from .report import Finding, Severity
from .symbols import FunctionSymbol
from .visitor import (
    JIT_NAMES,
    FunctionScope,
    ModuleInfo,
    assign_targets,
    dotted_expr,
    names_loaded,
    param_names,
    traced_names,
    walk_own,
)

RULES: Dict[str, type] = {}


def register(cls):
    RULES[cls.name] = cls
    return cls


class Rule:
    """Base rule; subclasses set name/code/severity/description."""
    name: str = ""
    code: str = ""
    severity: Severity = Severity.ERROR
    description: str = ""

    def finding(self, module: ModuleInfo, node: ast.AST, message: str
                ) -> Finding:
        return Finding(path=module.path, line=node.lineno,
                       col=node.col_offset + 1, rule=self.name,
                       code=self.code, severity=self.severity,
                       message=message)

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        raise NotImplementedError


# Shared AST helpers live in visitor.py; local aliases keep this module's
# rule bodies terse.
_walk_own = walk_own
_dotted = dotted_expr
_traced_names = traced_names


def _expr_names(node: ast.AST) -> Set[str]:
    """Names + self-attribute dotted strings read inside ``node``."""
    out = names_loaded(node)
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute):
            d = _dotted(n)
            if d is not None:
                out.add(d)
    return out


# ---------------------------------------------------------------------------
# GLT001 host-sync-in-jit
# ---------------------------------------------------------------------------

def compute_jit_taint(module: ModuleInfo
                      ) -> Dict[FunctionScope, Set[str]]:
    """Traced-value sets for every jit-context scope in the module.

    Fixpoint so transitively-jitted helpers see their caller's taint
    (their params are traced only if the call site passes traced values —
    static sizing helpers called with Python config stay clean).
    """
    taint_by_scope: Dict[FunctionScope, Set[str]] = {}
    for _ in range(4):
        changed = False
        for scope in module.scopes:   # DFS order: parents first
            if not module.in_jit_context(scope):
                continue
            taint = _seed_taint(module, scope, taint_by_scope)
            if scope.parent in taint_by_scope:
                taint |= taint_by_scope[scope.parent]
            # two linear passes propagate taint through assignments
            for _ in range(2):
                for node in _walk_own(scope.node):
                    if isinstance(node, (ast.Assign, ast.AnnAssign,
                                         ast.AugAssign)):
                        value = node.value
                        if value is not None and (_traced_names(value)
                                                  & taint):
                            taint |= set(assign_targets(node))
            if taint_by_scope.get(scope) != taint:
                taint_by_scope[scope] = taint
                changed = True
        if not changed:
            break
    return taint_by_scope


def _seed_taint(module: ModuleInfo, scope: FunctionScope,
                taint_by_scope: Dict[FunctionScope, Set[str]]
                ) -> Set[str]:
    """Initial traced-value set: all params for direct jit roots, only
    traced-at-the-call-site params for transitive ones."""
    if scope.transitive_call is None:
        # `self`/`cls` are bound (or closure-captured) at jit time,
        # never traced — counting them floods attribute reads.
        return set(scope.params) - scope.static_args - {"self", "cls"}
    caller, call = scope.transitive_call
    caller_taint = taint_by_scope.get(caller, set())
    params = scope.params
    # bound method call (self.f(...)): positional args bind past self
    if params[:1] == ["self"] and isinstance(call.func, ast.Attribute):
        pos = params[1:]
    else:
        pos = params
    seed: Set[str] = set()
    for i, arg in enumerate(call.args):
        if i < len(pos) and (_traced_names(arg) & caller_taint):
            seed.add(pos[i])
    for kw in call.keywords:
        if kw.arg in params and (_traced_names(kw.value) & caller_taint):
            seed.add(kw.arg)
    return seed - scope.static_args


@register
class HostSyncInJit(Rule):
    """Host transfers/synchronisation on traced values inside jit.

    ``np.asarray``/``np.array``/``jax.device_get``/``.item()``/``int()``/
    ``float()``/``bool()`` on a traced value either fails at trace time
    (TracerArrayConversionError) or — worse, via callbacks — inserts a
    device->host sync into the sampling hot path, serialising the TPU
    against the host exactly as BGL measured for GNN data pipelines.

    With a project, the check is transitive across modules: a call from a
    jit context that passes a traced value into another module's function
    whose effect summary says that parameter reaches a host sync
    (directly or through further calls) is flagged at the call site, with
    the chain in the message.
    """
    name = "host-sync-in-jit"
    code = "GLT001"
    severity = Severity.ERROR
    description = ("numpy conversion / Python scalar coercion of a traced "
                   "value inside a jit/shard_map context (transitive "
                   "through project calls)")

    HOST_CALLS = HOST_SYNC_CALLS
    COERCIONS = COERCIONS
    SYNC_METHODS = SYNC_METHODS

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        taint_by_scope = compute_jit_taint(module)
        for scope in module.scopes:
            if not module.in_jit_context(scope):
                continue
            taint = taint_by_scope.get(scope, set())
            for node in _walk_own(scope.node):
                if not isinstance(node, ast.Call):
                    continue
                findings.extend(self._check_call(module, scope, node, taint))
                if project is not None and taint:
                    findings.extend(self._check_cross_module(
                        module, scope, node, taint, project))
        return findings

    def _check_cross_module(self, module: ModuleInfo, scope: FunctionScope,
                            call: ast.Call, taint: Set[str],
                            project) -> List[Finding]:
        """Follow the call into another module's effect summary."""
        sym = project.resolve_call(module, scope, call)
        if not isinstance(sym, FunctionSymbol) or sym.module is module:
            return []          # same-module helpers: the pass above
        if sym.module.in_jit_context(sym.scope):
            return []          # callee's own module pass reports inside
        summary = project.effects.summary_for(sym)
        sync = summary.sync_param_map()
        if not sync:
            return []
        params = sym.scope.params
        if params[:1] == ["self"] and isinstance(call.func, ast.Attribute):
            pos = params[1:]
        else:
            pos = params
        hits = []
        for i, arg in enumerate(call.args):
            if i < len(pos) and pos[i] in sync \
                    and (_traced_names(arg) & taint):
                hits.append((pos[i], arg))
        for kw in call.keywords:
            if kw.arg in sync and (_traced_names(kw.value) & taint):
                hits.append((kw.arg, kw.value))
        out = []
        for p, arg in hits[:1]:     # one finding per call site
            site = sync[p]
            var = sorted(_traced_names(arg) & taint)[0]
            out.append(self.finding(
                module, call,
                f"traced value '{var}' flows into '{sym.short}' whose "
                f"parameter '{p}' reaches {site.detail} "
                f"({sym.module.path}:{site.line}) — host sync inside jit "
                f"context '{scope.name}'; keep the helper jnp-pure or "
                f"hoist the call to host code"))
        return out

    def _check_call(self, module: ModuleInfo, scope: FunctionScope,
                    call: ast.Call, taint: Set[str]) -> List[Finding]:
        name = module.call_name(call)
        args = list(call.args) + [kw.value for kw in call.keywords]
        touched = set().union(*[_traced_names(a) for a in args]) if args else set()
        where = (f"in jit context '{scope.name}' ({scope.jit_reason})"
                 if scope.jit_reason else f"in jit context '{scope.name}'")
        if name in self.HOST_CALLS and (touched & taint):
            var = sorted(touched & taint)[0]
            return [self.finding(
                module, call,
                f"{name}() on traced value '{var}' {where}: forces a "
                f"device->host transfer (or TracerArrayConversionError); "
                f"use jnp/lax ops instead")]
        if name in self.COERCIONS and (touched & taint):
            var = sorted(touched & taint)[0]
            return [self.finding(
                module, call,
                f"{name}() on traced value '{var}' {where}: concretises "
                f"the tracer (ConcretizationTypeError at trace time); "
                f"hoist to host code or keep it an array")]
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in self.SYNC_METHODS
                and (_traced_names(call.func.value) & taint)):
            var = sorted(_traced_names(call.func.value) & taint)[0]
            return [self.finding(
                module, call,
                f".{call.func.attr}() on traced value '{var}' {where}: "
                f"host sync point inside the compiled program")]
        return []


# ---------------------------------------------------------------------------
# GLT002 prng-key-reuse
# ---------------------------------------------------------------------------

_KEY_SOURCES = _KEY_SOURCES_IMPORTED
# Deriving fresh keys from a base key is the sanctioned way to reuse it.
_NON_CONSUMING = _NON_CONSUMING_IMPORTED
_KEY_PARAM_HINTS = ("key", "rng", "prng")


def _looks_like_key_param(name: str) -> bool:
    low = name.lower()
    return (low in ("key", "rng", "prngkey", "prng_key", "base_key")
            or low.endswith("_key") or low.endswith("_rng")
            or low.endswith("_keys"))


@register
class PrngKeyReuse(Rule):
    """The same PRNG key consumed by two sampling calls.

    jax.random is counter-based: passing one key to two draws yields
    *identical* randomness — on the sampler hot path that silently
    correlates hops/batches (every neighbor draw repeats).  A key may be
    consumed once; reuse requires an intervening ``split``/``fold_in``.

    With a project, call sites resolving to project functions consult the
    callee's effect summary: only arguments bound to parameters the
    callee actually consumes as keys (directly or transitively) count as
    consumption — a helper that merely ``split``s its key argument is as
    safe as ``jax.random.split`` itself, and a consuming helper two
    modules away still burns the key.  Unresolvable calls keep the
    conservative behavior (any call consumes).
    """
    name = "prng-key-reuse"
    code = "GLT002"
    severity = Severity.ERROR
    description = ("a PRNG key passed to two consuming calls (callee "
                   "effect summaries decide consumption) without an "
                   "intervening jax.random.split/fold_in")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        self._project = project
        for scope in module.scopes:
            if isinstance(scope.node, ast.Lambda):
                continue
            self._scope = scope
            state: Dict[str, int] = {
                p: 0 for p in scope.params if _looks_like_key_param(p)}
            self._run(module, scope.node.body, state, findings)
        return findings

    # -- branch-aware linear interpreter ----------------------------------
    def _run(self, module: ModuleInfo, body: Sequence[ast.stmt],
             state: Dict[str, int], findings: List[Finding]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                s1, s2 = dict(state), dict(state)
                self._run(module, stmt.body, s1, findings)
                self._run(module, stmt.orelse, s2, findings)
                # conservative merge: a use must happen on *every* path to
                # count against later statements
                state.clear()
                for var in set(s1) & set(s2):
                    state[var] = min(s1[var], s2[var])
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._visit_exprs(module, stmt, state, findings,
                                  skip_body=True)
                self._run(module, stmt.body, state, findings)
                self._run(module, stmt.orelse, state, findings)
                continue
            if isinstance(stmt, ast.Try):
                self._run(module, stmt.body, state, findings)
                for h in stmt.handlers:
                    self._run(module, h.body, dict(state), findings)
                self._run(module, stmt.orelse, state, findings)
                self._run(module, stmt.finalbody, state, findings)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._visit_exprs(module, stmt, state, findings,
                                  skip_body=True)
                self._run(module, stmt.body, state, findings)
                continue
            self._visit_exprs(module, stmt, state, findings)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._apply_assign(module, stmt, state)

    def _consuming_arg_ids(self, module: ModuleInfo,
                           node: ast.Call) -> Optional[Set[int]]:
        """With a resolved callee summary: the ``id()``s of the argument
        nodes bound to key-consuming parameters.  None means the call is
        unresolvable — treat every argument as consuming (conservative)."""
        if self._project is None:
            return None
        sym = self._project.resolve_call(module, self._scope, node)
        if not isinstance(sym, FunctionSymbol):
            return None
        summary = self._project.effects.summary_for(sym)
        params = sym.scope.params
        if params[:1] == ["self"] and isinstance(node.func, ast.Attribute):
            pos = params[1:]
        else:
            pos = params
        consuming: Set[int] = set()
        for i, arg in enumerate(node.args):
            if i < len(pos) and pos[i] in summary.key_params:
                consuming.add(id(arg))
        for kw in node.keywords:
            if kw.arg in summary.key_params:
                consuming.add(id(kw.value))
        return consuming

    def _visit_exprs(self, module: ModuleInfo, stmt: ast.stmt,
                     state: Dict[str, int], findings: List[Finding],
                     skip_body: bool = False) -> None:
        nodes: Iterator[ast.AST]
        if skip_body:
            nodes = iter(())
            for field in ("test", "iter", "items", "target"):
                sub = getattr(stmt, field, None)
                if sub is not None:
                    sub_list = sub if isinstance(sub, list) else [sub]
                    nodes = iter(list(nodes) + [
                        n for s in sub_list
                        for n in ast.walk(s if not hasattr(s, "context_expr")
                                          else s.context_expr)])
        else:
            nodes = _walk_own(stmt)
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            name = module.call_name(node)
            if name in _NON_CONSUMING:
                continue
            consuming = self._consuming_arg_ids(module, node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in state:
                    if consuming is not None and id(arg) not in consuming:
                        continue     # callee provably derives, not draws
                    state[arg.id] += 1
                    if state[arg.id] == 2:
                        findings.append(self.finding(
                            module, node,
                            f"PRNG key '{arg.id}' consumed a second time "
                            f"(same randomness as its first use); derive a "
                            f"fresh key with jax.random.split/fold_in "
                            f"before this call"))

    def _apply_assign(self, module: ModuleInfo, stmt: ast.stmt,
                      state: Dict[str, int]) -> None:
        targets = assign_targets(stmt)
        value = getattr(stmt, "value", None)
        is_key_src = (isinstance(value, ast.Call)
                      and module.call_name(value) in _KEY_SOURCES)
        for t in targets:
            if is_key_src:
                state[t] = 0            # fresh key: uses reset
            elif t in state:
                del state[t]            # overwritten with a non-key value


# ---------------------------------------------------------------------------
# GLT003 recompile-hazard
# ---------------------------------------------------------------------------

@register
class RecompileHazard(Rule):
    """Python scalars closure-captured into a jit target.

    ``jax.jit(lambda x: x * n)`` bakes ``n`` into the traced program as a
    compile-time constant: every distinct value of ``n`` (a batch width, a
    ``.shape[0]``, a fanout) triggers a full recompile — the PyGraph
    failure mode, silent on TPU until the profile shows nothing but
    compilation.  Pass the scalar as a (possibly static) argument instead.
    """
    name = "recompile-hazard"
    code = "GLT003"
    severity = Severity.WARNING
    description = ("a Python scalar captured by a jitted closure without "
                   "static_argnums/static_argnames")

    _SCALAR_CALLS = {"int", "float", "len", "round", "min", "max"}

    def check(self, module: ModuleInfo, project=None
              ) -> List[Finding]:
        findings: List[Finding] = []
        for scope in module.scopes:
            if isinstance(scope.node, ast.Lambda):
                continue
            scalars = self._scalar_locals(module, scope)
            if not scalars:
                continue
            for node in _walk_own(scope.node):
                if not isinstance(node, ast.Call):
                    continue
                if module.call_name(node) not in JIT_NAMES:
                    continue
                findings.extend(
                    self._check_jit_call(module, scope, node, scalars))
        return findings

    def _scalar_locals(self, module: ModuleInfo, scope: FunctionScope
                       ) -> Set[str]:
        """Locals assigned from obviously-Python-scalar expressions."""
        scalars: Set[str] = set()
        for node in _walk_own(scope.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            if node.value is not None and self._is_scalarish(module,
                                                             node.value):
                scalars |= set(assign_targets(node))
        return scalars

    def _is_scalarish(self, module: ModuleInfo, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, (int, float)) and not isinstance(
                expr.value, bool)
        if isinstance(expr, ast.Call):
            return module.call_name(expr) in self._SCALAR_CALLS
        if isinstance(expr, ast.Attribute):
            return expr.attr in ("shape", "ndim", "size")
        if isinstance(expr, ast.Subscript):
            return (isinstance(expr.value, ast.Attribute)
                    and expr.value.attr == "shape")
        if isinstance(expr, ast.BinOp):
            return (self._is_scalarish(module, expr.left)
                    or self._is_scalarish(module, expr.right))
        return False

    def _check_jit_call(self, module: ModuleInfo, scope: FunctionScope,
                        call: ast.Call, scalars: Set[str]) -> List[Finding]:
        has_static = any(kw.arg in ("static_argnums", "static_argnames")
                         for kw in call.keywords)
        if has_static or not call.args:
            return []
        target = call.args[0]
        fn_node = None
        if isinstance(target, ast.Lambda):
            fn_node = target
        elif isinstance(target, ast.Name):
            for child in module.scopes:
                if (child.parent is scope and child.name == target.id
                        and not isinstance(child.node, ast.Lambda)):
                    fn_node = child.node
                    break
        if fn_node is None:
            return []
        body = (fn_node.body if isinstance(fn_node, ast.Lambda)
                else fn_node)
        free = names_loaded(body) - set(param_names(fn_node))
        if not isinstance(fn_node, ast.Lambda):
            for node in _walk_own(fn_node):
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    free -= set(assign_targets(node))
        captured = sorted(free & scalars)
        if not captured:
            return []
        return [self.finding(
            module, call,
            f"jit target closes over Python scalar(s) "
            f"{', '.join(repr(c) for c in captured)}: each distinct value "
            f"recompiles the program; pass as an argument (traced) or mark "
            f"static_argnums/static_argnames")]


# ---------------------------------------------------------------------------
# GLT004 int64-id-truncation
# ---------------------------------------------------------------------------

@register
class Int64IdTruncation(Rule):
    """int64 node/edge ids fed to jnp without an explicit dtype.

    JAX disables x64 by default: ``jnp.asarray(ids_int64)`` silently
    truncates to int32.  Ids above 2**31 (papers100M edge ids already
    qualify) wrap negative and index garbage rows.  Either pass an
    explicit dtype (acknowledging the narrowing) or relabel ids into
    int32 range first.
    """
    name = "int64-id-truncation"
    code = "GLT004"
    severity = Severity.ERROR
    description = ("np.int64 values flowing into jnp.asarray/array with no "
                   "explicit dtype (silent int32 truncation under default "
                   "x64-disabled JAX)")

    _SINKS = {"jax.numpy.asarray", "jax.numpy.array"}

    def check(self, module: ModuleInfo, project=None
              ) -> List[Finding]:
        findings: List[Finding] = []
        module_taint = self._collect_taint(module, module.tree, set())
        self._scan(module, module.tree, module_taint, findings,
                   skip_scopes=True)
        for scope in module.scopes:
            taint = self._collect_taint(module, scope.node,
                                        set(module_taint))
            self._scan(module, scope.node, taint, findings,
                       skip_scopes=False)
        return findings

    def _collect_taint(self, module: ModuleInfo, root: ast.AST,
                       seed: Set[str]) -> Set[str]:
        taint = set(seed)
        for _ in range(2):
            for node in _walk_own(root):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if node.value is None:
                    continue
                if (self._is_int64_expr(module, node.value)
                        or self._propagates(module, node.value, taint)):
                    for t in assign_targets(node):
                        taint.add(t)
                    # also self.x targets
                    tgts = (node.targets if isinstance(node, ast.Assign)
                            else [node.target])
                    for t in tgts:
                        d = _dotted(t)
                        if d is not None and "." in d:
                            taint.add(d)
        return taint

    def _propagates(self, module: ModuleInfo, expr: ast.expr,
                    taint: Set[str]) -> bool:
        """Does int64-ness flow from a tainted name into this value?

        Structural operations (copies, indexing, arithmetic, ``np.*``
        reshuffles, ``.reshape()``-style methods on tainted values) keep
        the dtype; results of arbitrary user functions do not inherit it
        — assuming they did floods every consumer of an id array.
        Comparisons/boolean ops yield bools, never ids.
        """
        if isinstance(expr, (ast.Name, ast.Attribute)):
            d = _dotted(expr)
            return d in taint if d is not None else False
        if isinstance(expr, ast.Subscript):
            return self._propagates(module, expr.value, taint)
        if isinstance(expr, ast.BinOp):
            return (self._propagates(module, expr.left, taint)
                    or self._propagates(module, expr.right, taint))
        if isinstance(expr, ast.UnaryOp):
            return self._propagates(module, expr.operand, taint)
        if isinstance(expr, ast.IfExp):
            return (self._propagates(module, expr.body, taint)
                    or self._propagates(module, expr.orelse, taint))
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._propagates(module, el, taint)
                       for el in expr.elts)
        if isinstance(expr, ast.Starred):
            return self._propagates(module, expr.value, taint)
        if isinstance(expr, ast.Call):
            name = module.call_name(expr) or ""
            args = list(expr.args) + [kw.value for kw in expr.keywords]
            any_tainted = any(self._propagates(module, a, taint)
                              for a in args)
            if name.startswith("numpy.") and not name.startswith(
                    "numpy.random."):
                return any_tainted
            # dtype-preserving method on a tainted value: x.reshape(...)
            if (isinstance(expr.func, ast.Attribute)
                    and expr.func.attr in ("reshape", "ravel", "copy",
                                           "flatten", "squeeze",
                                           "transpose", "take", "clip")
                    and self._propagates(module, expr.func.value, taint)):
                return True
            return False
        return False

    def _is_int64_expr(self, module: ModuleInfo, expr: ast.expr) -> bool:
        """Does the expression *introduce* int64-ness?"""
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                if module.imports.resolve(node) in ("numpy.int64",
                                                    "numpy.uint64"):
                    return True
            if isinstance(node, ast.Call):
                # .astype(np.int64) / .astype("int64")
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype" and node.args):
                    a = node.args[0]
                    if (module.imports.resolve(a) in ("numpy.int64",
                                                      "numpy.uint64")
                            or (isinstance(a, ast.Constant)
                                and a.value in ("int64", "uint64"))):
                        return True
                # np.*(..., dtype=np.int64)
                for kw in node.keywords:
                    if kw.arg == "dtype" and (
                            module.imports.resolve(kw.value)
                            in ("numpy.int64", "numpy.uint64")
                            or (isinstance(kw.value, ast.Constant)
                                and kw.value.value in ("int64", "uint64"))):
                        return True
        return False

    def _scan(self, module: ModuleInfo, root: ast.AST, taint: Set[str],
              findings: List[Finding], skip_scopes: bool) -> None:
        walker = (_walk_own(root) if skip_scopes else ast.walk(root))
        for node in walker:
            if not isinstance(node, ast.Call):
                continue
            if module.call_name(node) not in self._SINKS:
                continue
            if len(node.args) >= 2:            # positional dtype
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            hit = self._is_int64_expr(module, arg)
            tainted = (sorted(_expr_names(arg) & taint)
                       if self._propagates(module, arg, taint) else [])
            if hit or tainted:
                what = (f"'{tainted[0]}'" if tainted
                        else "an int64 expression")
                findings.append(self.finding(
                    module, node,
                    f"jnp conversion of int64 ids ({what}) without an "
                    f"explicit dtype: silently truncates to int32 under "
                    f"default x64-disabled JAX; pass dtype= (or relabel "
                    f"into int32 range first)"))


# ---------------------------------------------------------------------------
# GLT005 nondeterministic-default-rng
# ---------------------------------------------------------------------------

@register
class NondeterministicDefaultRng(Rule):
    """Unseeded ``np.random.default_rng()`` in library code.

    OS-entropy seeding makes sampling unreproducible across runs and —
    worse on a pod — *divergent across hosts*, so "identical" per-host
    programs sample different subgraphs and collective shapes drift.
    Always seed from configuration (and fold in the epoch/host index).
    """
    name = "nondeterministic-default-rng"
    code = "GLT005"
    severity = Severity.WARNING
    description = "np.random.default_rng() with no seed argument"

    _RNG = {"numpy.random.default_rng", "numpy.random.Generator",
            "numpy.random.RandomState"}

    def check(self, module: ModuleInfo, project=None
              ) -> List[Finding]:
        findings: List[Finding] = []
        # fresh-generator-inline-draw: default_rng(seed).permutation(x)
        # where `seed` is a parameter of the enclosing function replays
        # the identical stream on every call — the repeated-permutation-
        # across-epochs bug class (a constant literal seed is a one-shot
        # deterministic fixture; a per-call-varying seed expression is a
        # deliberate stream; a bare parameter is the same value every
        # call of this function).
        for scope in module.scopes:
            params = set(scope.params)
            for node in _walk_own(scope.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Call)
                        and module.call_name(node.func.value) in self._RNG
                        and node.func.value.args):
                    continue
                seed_arg = node.func.value.args[0]
                if (isinstance(seed_arg, ast.Name)
                        and seed_arg.id in params):
                    findings.append(self.finding(
                        module, node,
                        f"fresh Generator from parameter "
                        f"'{seed_arg.id}' drawn inline "
                        f"(.{node.func.attr}()): every call of "
                        f"'{scope.name}' replays the identical stream — "
                        f"across epochs that repeats the exact "
                        f"permutation; thread a stateful Generator "
                        f"through instead"))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = module.call_name(node)
            if name not in self._RNG:
                continue
            unseeded = not node.args and not node.keywords
            if not unseeded and node.args:
                a = node.args[0]
                unseeded = isinstance(a, ast.Constant) and a.value is None
            if unseeded:
                findings.append(self.finding(
                    module, node,
                    f"{name}() without a seed: draws from OS entropy — "
                    f"unreproducible, and divergent across pod hosts; "
                    f"thread a seeded Generator through instead"))
        return findings


# ---------------------------------------------------------------------------
# GLT006 shadowed-jit-donation
# ---------------------------------------------------------------------------

@register
class ShadowedJitDonation(Rule):
    """A buffer read again after being donated to a jitted call.

    ``donate_argnums`` hands the argument's buffer to XLA for reuse; the
    original array is *deleted*.  A later read raises
    RuntimeError("Array has been deleted") on TPU — but passes silently
    on CPU backends where donation is a no-op, so only the lint (or the
    pod) catches it.
    """
    name = "shadowed-jit-donation"
    code = "GLT006"
    severity = Severity.ERROR
    description = ("an array used again after being passed through "
                   "donate_argnums")

    def check(self, module: ModuleInfo, project=None
              ) -> List[Finding]:
        donors = self._collect_donors(module)
        if not donors:
            return []
        findings: List[Finding] = []
        for scope in module.scopes:
            if isinstance(scope.node, ast.Lambda):
                continue
            self._run(module, scope.node.body, donors, {}, findings)
        self._run(module, module.tree.body, donors, {}, findings)
        return findings

    def _collect_donors(self, module: ModuleInfo) -> Dict[str, Set[int]]:
        """callable name -> donated positional indices (module-wide)."""
        donors: Dict[str, Set[int]] = {}
        for scope in module.scopes:
            if scope.donate_argnums:
                donors[scope.name] = set(scope.donate_argnums)
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if not (isinstance(value, ast.Call)
                    and module.call_name(value) in JIT_NAMES):
                continue
            donated = {el for kw in value.keywords
                       if kw.arg == "donate_argnums"
                       for el in _iter_const_ints(kw.value)}
            if not donated:
                continue
            tgts = (node.targets if isinstance(node, ast.Assign)
                    else [node.target])
            for t in tgts:
                d = _dotted(t)
                if d is not None:
                    donors[d] = set(donated)
        return donors

    def _run(self, module: ModuleInfo, body: Sequence[ast.stmt],
             donors: Dict[str, Set[int]],
             dead: Dict[str, Tuple[int, str]],
             findings: List[Finding]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                s1, s2 = dict(dead), dict(dead)
                self._run(module, stmt.body, donors, s1, findings)
                self._run(module, stmt.orelse, donors, s2, findings)
                dead.clear()
                dead.update(s1)
                dead.update(s2)      # dead on either path counts
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While,
                                 ast.With, ast.AsyncWith, ast.Try)):
                for sub in (getattr(stmt, "body", []) or []):
                    self._run(module, [sub], donors, dead, findings)
                for sub in (getattr(stmt, "orelse", []) or []):
                    self._run(module, [sub], donors, dead, findings)
                for h in getattr(stmt, "handlers", ()) or ():
                    self._run(module, h.body, donors, dict(dead), findings)
                for sub in (getattr(stmt, "finalbody", []) or []):
                    self._run(module, [sub], donors, dead, findings)
                continue
            # 1) reads of already-donated buffers (before this statement's
            #    own donation processing)
            donating_calls = [n for n in _walk_own(stmt)
                              if isinstance(n, ast.Call)
                              and self._donor_name(n, donors) is not None]
            donated_arg_nodes: Set[int] = set()
            for call in donating_calls:
                name = self._donor_name(call, donors)
                for idx in donors[name]:
                    if idx < len(call.args) and isinstance(call.args[idx],
                                                           ast.Name):
                        donated_arg_nodes.add(id(call.args[idx]))
            for node in _walk_own(stmt):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in dead
                        and id(node) not in donated_arg_nodes):
                    line, fn = dead[node.id]
                    findings.append(self.finding(
                        module, node,
                        f"'{node.id}' used after being donated to "
                        f"'{fn}' (line {line}): donated buffers are "
                        f"deleted on TPU (RuntimeError); copy first or "
                        f"drop the reuse"))
                    del dead[node.id]          # report once per donation
            # 2) this statement's donations
            for call in donating_calls:
                name = self._donor_name(call, donors)
                for idx in donors[name]:
                    if idx < len(call.args) and isinstance(call.args[idx],
                                                           ast.Name):
                        dead[call.args[idx].id] = (call.lineno, name)
            # 3) reassignments resurrect
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                for t in assign_targets(stmt):
                    dead.pop(t, None)

    @staticmethod
    def _donor_name(call: ast.Call, donors: Dict[str, Set[int]]
                    ) -> Optional[str]:
        d = _dotted(call.func)
        return d if d in donors else None


# ---------------------------------------------------------------------------
# GLT007 unbounded-blocking-get
# ---------------------------------------------------------------------------

@register
class UnboundedBlockingGet(Rule):
    """``queue.Queue.get()`` / ``Thread.join()`` that can block forever.

    The distributed hang class: a consumer blocked in a no-timeout
    ``.get()`` waits forever once its producer thread/process dies between
    its last put and the get — nothing will ever arrive, and nothing
    raises.  Same shape for a no-timeout ``.join()`` on a thread wedged on
    a bounded queue.  Library code must either bound the wait (``timeout=``)
    or recheck liveness while polling (``channel.base.bounded_get``); a
    wait proven bounded by construction takes a justified suppression.
    """
    name = "unbounded-blocking-get"
    code = "GLT007"
    severity = Severity.ERROR
    description = ("a blocking .get()/.join() call with no timeout and no "
                   "liveness recheck in the enclosing function")

    # Zero-argument spellings only: dict.get(key), "".join(parts),
    # thread.join(5) all carry arguments and are not the blocking form.
    _BLOCKING = {"get", "join"}
    # A scope that probes peer liveness is running the timeout-and-recheck
    # pattern; its waits are bounded by the recheck loop.
    _LIVENESS = {"is_alive", "is_set", "poll"}

    def check(self, module: ModuleInfo, project=None
              ) -> List[Finding]:
        findings: List[Finding] = []
        regions = [module.tree] + [
            s.node for s in module.scopes
            if not isinstance(s.node, ast.Lambda)]
        for node in regions:
            calls = [n for n in _walk_own(node)
                     if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Attribute)]
            if any(c.func.attr in self._LIVENESS for c in calls):
                continue
            for call in calls:
                if (call.func.attr in self._BLOCKING
                        and not call.args and not call.keywords):
                    findings.append(self.finding(
                        module, call,
                        f".{call.func.attr}() with no timeout and no "
                        f"liveness check in scope: blocks forever if the "
                        f"producer/thread died — pass timeout= in a "
                        f"recheck loop (see channel.base.bounded_get), or "
                        f"suppress with a bounded-wait justification"))
        return findings


# ---------------------------------------------------------------------------
# GLT010 span-in-traced-code
# ---------------------------------------------------------------------------

@register
class SpanInTracedCode(Rule):
    """``glt_tpu.obs`` span/metric host calls inside jit-traced functions.

    The obs library is host-side: a ``span()`` / ``Counter.inc()`` inside
    a jit-traced function executes ONCE at trace time and then vanishes
    from the compiled program — the span measures tracing, the counter
    counts compilations, and both silently stop moving as soon as the
    cached executable is reused.  Instrument at the host call boundary
    (loaders, epoch drivers, dispatch wrappers) and fence device work
    with ``span.fence(out)`` instead.

    Flagged spellings, inside any scope :meth:`ModuleInfo.in_jit_context`
    marks traced:

      * any call resolving (through the import map) into ``glt_tpu.obs``
        — ``span(...)``, ``obs.span(...)``, ``metrics.counter(...)``;
      * ``.inc()/.observe()/.set()/.time()/.fence()`` on a name assigned
        from an obs factory in this module (module-level ``_M = ...`` or
        ``self._m = ...`` instruments) or chained directly off one
        (``metrics.counter("x").inc()``).

    ``.at[i].set(v)`` and other non-obs receivers never match: the
    receiver must trace back to an obs import or an obs-built name.
    """
    name = "span-in-traced-code"
    code = "GLT010"
    severity = Severity.ERROR
    description = ("glt_tpu.obs span/metric call inside a jit-traced "
                   "function (host side effects vanish under trace)")

    _OBS_PREFIX = "glt_tpu.obs"
    _METHODS = {"inc", "observe", "set", "time", "fence"}

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        instruments = self._instrument_names(module)
        findings: List[Finding] = []
        for scope in module.scopes:
            if not module.in_jit_context(scope):
                continue
            for node in _walk_own(scope.node):
                if not isinstance(node, ast.Call):
                    continue
                message = self._obs_call(module, node, instruments)
                if message:
                    findings.append(self.finding(module, node, message))
        return findings

    def _is_obs_path(self, dotted: Optional[str]) -> bool:
        return bool(dotted) and (
            dotted == self._OBS_PREFIX
            or dotted.startswith(self._OBS_PREFIX + "."))

    def _instrument_names(self, module: ModuleInfo) -> Set[str]:
        """Names (plain or ``self.x`` dotted) assigned from an obs
        factory call anywhere in the module."""
        out: Set[str] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and self._is_obs_path(module.call_name(value))):
                continue
            out |= set(assign_targets(node))
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                d = _dotted(t)
                if d:
                    out.add(d)
        return out

    def _obs_call(self, module: ModuleInfo, call: ast.Call,
                  instruments: Set[str]) -> Optional[str]:
        resolved = module.call_name(call)
        if self._is_obs_path(resolved):
            return (f"{resolved}() inside a jit-traced function: the host "
                    f"call runs once at trace time and vanishes from the "
                    f"compiled program — instrument the host dispatch "
                    f"loop instead (span.fence(out) observes device time)")
        func = call.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in self._METHODS):
            return None
        receiver = _dotted(func.value)
        if receiver is not None and receiver in instruments:
            return (f".{func.attr}() on obs instrument {receiver!r} "
                    f"inside a jit-traced function: the host side effect "
                    f"vanishes under trace — move it to the host loop")
        inner = func.value
        while isinstance(inner, ast.Attribute):
            inner = inner.value
        if (isinstance(inner, ast.Call)
                and self._is_obs_path(module.call_name(inner))):
            return (f".{func.attr}() chained off an obs factory inside a "
                    f"jit-traced function: the host side effect vanishes "
                    f"under trace — move it to the host loop")
        return None


# ---------------------------------------------------------------------------
# GLT011 non-atomic-state-publish
# ---------------------------------------------------------------------------

@register
class NonAtomicStatePublish(Rule):
    """``open(path, "w")`` publishing state without tmp + ``os.replace``.

    The durable-state discipline (glt_tpu.ckpt.store, channel/native.py):
    anything another process may read — checkpoints, manifests, trace
    exports, bench/report artifacts — is written fully under a private
    tmp name and published with ONE atomic rename.  A direct write to
    the final path is a torn-read window: a reader (or a crash) midway
    through the write observes a half-written file that parses as
    garbage or, worse, parses cleanly as truncated state.

    Flagged: ``open()`` in write/create mode (``w``/``x``/``a`` modes)
    on a path that is not visibly a tmp name (no ``tmp``/``temp`` in the
    path expression), in an enclosing function that never publishes via
    ``os.replace``/``os.rename``/``shutil.move``.  A function that does
    rename-publish is trusted for all its writes (the tmp file it writes
    may be named by any expression); genuinely process-private files
    take a tmp-ish name or a justified suppression.
    """
    name = "non-atomic-state-publish"
    code = "GLT011"
    severity = Severity.ERROR
    description = ("direct open(path, 'w') write without the tmp + "
                   "os.replace atomic-publish discipline")

    _PUBLISH = {"os.replace", "os.rename", "shutil.move"}
    _WRITE_MODES = ("w", "x", "a")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        regions = [module.tree] + [
            s.node for s in module.scopes
            if not isinstance(s.node, ast.Lambda)]
        for region in regions:
            calls = [n for n in _walk_own(region)
                     if isinstance(n, ast.Call)]
            if any((module.call_name(c) or _dotted(c.func))
                   in self._PUBLISH for c in calls):
                continue
            for call in calls:
                mode = self._write_mode(call)
                if mode is None:
                    continue
                path_src = ast.unparse(call.args[0]) if call.args else ""
                low = path_src.lower()
                if "tmp" in low or "temp" in low:
                    continue
                findings.append(self.finding(
                    module, call,
                    f"open({path_src}, {mode!r}) writes the final path "
                    f"directly: a reader (or this process, killed "
                    f"mid-write) can observe a torn file — write to a "
                    f".tmp- sibling and publish with one os.replace "
                    f"(the glt_tpu.ckpt.store discipline), or name the "
                    f"path tmp-ish if it is truly process-private"))
        return findings

    def _write_mode(self, call: ast.Call) -> Optional[str]:
        if not (isinstance(call.func, ast.Name)
                and call.func.id == "open" and call.args):
            return None
        mode = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)):
            return None
        return (mode.value if any(ch in mode.value
                                  for ch in self._WRITE_MODES) else None)


# ---------------------------------------------------------------------------
# GLT012 unbounded-queue-put
# ---------------------------------------------------------------------------

@register
class UnboundedQueuePut(Rule):
    """``queue.Queue()`` built without a ``maxsize`` bound.

    The backpressure hole the serving/server paths must not have: an
    unbounded queue between a fast producer (accepting connections,
    admitting requests) and a slower consumer grows until the process
    OOMs — under overload the correct behavior is a bounded queue whose
    ``put_nowait``/``Full`` turns into a structured ``Overloaded``
    rejection (glt_tpu.serving.front) or a stop-aware ``bounded_put``
    (channel.base).  Flags ``queue.Queue()`` / ``LifoQueue`` /
    ``PriorityQueue`` constructed with no ``maxsize`` (or an explicit
    ``maxsize<=0``, which stdlib treats as infinite), and
    ``queue.SimpleQueue()`` (unboundable by design).  Multiprocessing
    queues are out of scope: they are sized by their pipe buffers and
    used as small task queues here.
    """
    name = "unbounded-queue-put"
    code = "GLT012"
    severity = Severity.ERROR
    description = ("queue.Queue() constructed without a positive maxsize "
                   "bound (unbounded growth under backpressure)")

    _BOUNDED_CLASSES = {"queue.Queue", "queue.LifoQueue",
                        "queue.PriorityQueue"}
    _UNBOUNDABLE = {"queue.SimpleQueue"}

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = module.call_name(node)
            if name in self._UNBOUNDABLE:
                findings.append(self.finding(
                    module, node,
                    f"{name}() cannot be bounded: under backpressure it "
                    f"grows without limit — use queue.Queue(maxsize=N) "
                    f"with put_nowait -> structured rejection instead"))
                continue
            if name not in self._BOUNDED_CLASSES:
                continue
            size = None
            if node.args:
                size = node.args[0]
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    size = kw.value
            if size is None:
                findings.append(self.finding(
                    module, node,
                    f"{name}() without maxsize is unbounded: a stalled "
                    f"consumer lets it grow until OOM — pass "
                    f"maxsize=<bound> and handle queue.Full as "
                    f"backpressure (reject/drop), or justify with a "
                    f"suppression"))
            elif (isinstance(size, ast.Constant)
                    and isinstance(size.value, int) and size.value <= 0):
                findings.append(self.finding(
                    module, node,
                    f"{name}(maxsize={size.value}) is the unbounded "
                    f"spelling (stdlib treats <=0 as infinite); pass a "
                    f"positive bound"))
        return findings


# ---------------------------------------------------------------------------
# GLT013 dispatch-in-epoch-loop
# ---------------------------------------------------------------------------

@register
class DispatchInEpochLoop(Rule):
    """Per-batch host round-trips inside an epoch driver's batch loop.

    The fused-epoch contract (glt_tpu/models/train.py "The fused
    epoch"): an epoch driver dispatches compiled programs and fetches
    device values ONCE at the epoch boundary — a device->host fetch
    (``jax.device_get`` / ``np.asarray`` / ``.item()`` /
    ``block_until_ready`` / ``int()``/``float()`` coercions) inside the
    per-batch loop puts a tunnel round trip on every batch's critical
    path and silently reverts the scanned route to serialized per-batch
    latency (the 161 ms/batch vs 49 ms pipelined split bench.py
    documents).  This is the static guard that keeps the fusion win
    from regressing.

    Scope (calibrated on this tree): ``for``/``while`` bodies of
    functions named ``run_*epoch*`` — the epoch-driver naming
    convention (``run_scanned_epoch``, ``run_scanned_dist_epoch``,
    ``_ColdStagePipeline.run_epoch``).  Direct fetches are always
    flagged; with a project, calls into helpers whose effect summary
    reaches a host sync are flagged too (the round trip hidden one call
    deep).  Deliberate syncs — a checkpoint hook that must capture
    post-block-exact state — carry a justified suppression.
    """
    name = "dispatch-in-epoch-loop"
    code = "GLT013"
    severity = Severity.ERROR
    description = ("device->host fetch inside an epoch driver's batch "
                   "loop (per-batch tunnel round trip on the critical "
                   "path)")

    _EPOCH_NAME = "epoch"
    _EPOCH_PREFIXES = ("run_", "_run_")
    _FETCH_CALLS = (set(HOST_SYNC_CALLS)
                    | {"jax.block_until_ready", "jax.device_get"})

    @classmethod
    def _is_epoch_driver(cls, name: str) -> bool:
        return (cls._EPOCH_NAME in name
                and name.startswith(cls._EPOCH_PREFIXES))

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        for scope in module.scopes:
            if not self._is_epoch_driver(scope.name):
                continue
            for loop in _walk_own(scope.node):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    if isinstance(node, ast.Call):
                        f = self._check_call(module, scope, node, project)
                        if f is not None:
                            findings.append(f)
        return findings

    def _check_call(self, module: ModuleInfo, scope, call: ast.Call,
                    project) -> Optional[Finding]:
        name = module.call_name(call)
        if name in self._FETCH_CALLS:
            return self.finding(
                module, call,
                f"'{name}' inside the batch loop of epoch driver "
                f"'{scope.name}' fetches device state every batch — "
                f"accumulate device values and fetch ONCE after the "
                f"loop (one concat + one host read), or justify with a "
                f"suppression")
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in SYNC_METHODS):
            return self.finding(
                module, call,
                f".{call.func.attr}() inside the batch loop of epoch "
                f"driver '{scope.name}' is a per-batch device sync — "
                f"hoist the fetch out of the loop or justify with a "
                f"suppression")
        if name in COERCIONS and call.args \
                and not isinstance(call.args[0], ast.Constant):
            return self.finding(
                module, call,
                f"'{name}(...)' inside the batch loop of epoch driver "
                f"'{scope.name}': coercing a device value is a blocking "
                f"fetch per batch — keep losses as device arrays and "
                f"reduce once after the loop")
        # One call deep: a helper whose effect summary reaches a host
        # sync (project-wide pass only).
        if project is not None:
            sym = project.resolve_call(module, scope, call)
            if isinstance(sym, FunctionSymbol):
                summary = project.effects.summary_for(sym)
                sync = summary.sync_param_map()
                if sync:
                    p, site = next(iter(sorted(sync.items())))
                    return self.finding(
                        module, call,
                        f"'{sym.short}' called in the batch loop of "
                        f"epoch driver '{scope.name}' reaches a host "
                        f"sync through parameter '{p}' "
                        f"({sym.module.path}:{site.line}) — a hidden "
                        f"per-batch round trip; fetch after the epoch "
                        f"instead")
        return None


# ---------------------------------------------------------------------------
# GLT014 blocking-io-in-epoch-loop
# ---------------------------------------------------------------------------

@register
class BlockingIOInEpochLoop(Rule):
    """Synchronous disk reads inside an epoch driver's batch loop.

    The disk tier's contract (docs/storage.md): storage I/O belongs on
    the DRAM stager's background threads, hinted ahead of the sampler —
    a synchronous read (``np.load``/``np.fromfile``, slicing a
    ``np.memmap``, a file object's ``.read()``) inside the per-batch
    loop of a ``run_*epoch*`` driver puts device-idle milliseconds on
    every batch: the demand-fault path the stage-ahead hook exists to
    avoid.  Staging threads are out of scope by construction — they are
    not epoch drivers.

    Direct reads are always flagged; with a project, calls into helpers
    whose effect summary reaches a disk read (``DiskFeatureStore.
    gather_into`` -> ``_read_chunk`` -> memmap slice) are flagged one
    call deep.  Deliberate synchronous reads — the degraded fallback a
    failed stage leaves behind — carry a justified suppression.
    """
    name = "blocking-io-in-epoch-loop"
    code = "GLT014"
    severity = Severity.ERROR
    description = ("synchronous disk read inside an epoch driver's "
                   "batch loop (device idles behind storage; stage "
                   "ahead on the DRAM stager's threads instead)")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        for scope in module.scopes:
            if not DispatchInEpochLoop._is_epoch_driver(scope.name):
                continue
            mapped = self._mmap_names(module, scope)
            for loop in _walk_own(scope.node):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    f = self._check_node(module, scope, node, mapped,
                                         project)
                    if f is not None:
                        findings.append(f)
        return findings

    @staticmethod
    def _mmap_names(module: ModuleInfo, scope) -> set:
        """Names assigned from mmap constructors anywhere in the scope
        (the constructor is usually hoisted above the loop; the reads
        are the slices inside it)."""
        mapped = set()
        for node in _walk_own(scope.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = getattr(node, "value", None)
                if (isinstance(value, ast.Call)
                        and module.call_name(value) in MMAP_CALLS):
                    mapped.update(assign_targets(node))
        return mapped

    def _check_node(self, module: ModuleInfo, scope, node: ast.AST,
                    mapped: set, project) -> Optional[Finding]:
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in mapped):
            return self.finding(
                module, node,
                f"slicing memmap '{node.value.id}' inside the batch "
                f"loop of epoch driver '{scope.name}' page-faults to "
                f"storage per batch — stage the rows ahead "
                f"(DramStager.stage_ahead) or justify with a "
                f"suppression")
        if not isinstance(node, ast.Call):
            return None
        name = module.call_name(node)
        if name in DISK_CALLS:
            return self.finding(
                module, node,
                f"'{name}' inside the batch loop of epoch driver "
                f"'{scope.name}' reads storage on the dispatch thread "
                f"every batch — stage ahead on the DRAM stager's "
                f"threads, or justify with a suppression")
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in DISK_READ_METHODS):
            return self.finding(
                module, node,
                f".{node.func.attr}() inside the batch loop of epoch "
                f"driver '{scope.name}' is a synchronous file read per "
                f"batch — move it to a staging thread or justify with "
                f"a suppression")
        # One call deep: a helper whose effect summary reaches a disk
        # read (project-wide pass only).
        if project is not None:
            sym = project.resolve_call(module, scope, node)
            if isinstance(sym, FunctionSymbol):
                summary = project.effects.summary_for(sym)
                if summary.disk:
                    d = summary.disk[0]
                    return self.finding(
                        module, node,
                        f"'{sym.short}' called in the batch loop of "
                        f"epoch driver '{scope.name}' reaches a disk "
                        f"read ({d.detail}, {sym.module.path}:{d.line})"
                        f" — a synchronous storage hit per batch; "
                        f"stage ahead instead")
        return None


@register
class WallClockDuration(Rule):
    """Durations measured by differencing ``time.time()`` readings.

    ``time.time()`` is the WALL clock: NTP slews/steps it, a VM
    migration jumps it, and a leap smear stretches it — a duration
    computed as the difference of two wall readings can come out
    negative or wildly wrong, and these numbers feed SLO histograms
    and retry backoffs.  Durations belong on ``time.monotonic()`` /
    ``time.perf_counter()`` (the convention everywhere in this tree).

    Flagged: a ``-`` expression whose BOTH operands are wall readings —
    direct ``time.time()`` calls or names/attributes assigned from one
    in the same scope.  Subtracting a wall reading from a wall-derived
    *timestamp* (``time.time() - os.path.getmtime(p)``, checkpoint
    mtimes, event ``ts`` fields) is NOT flagged: comparing two wall
    timestamps is what the wall clock is for; only a wall-vs-wall
    *interval* pretends to be a stopwatch.
    """
    name = "wall-clock-duration"
    code = "GLT015"
    severity = Severity.ERROR
    description = ("duration computed from two time.time() readings "
                   "(wall clock steps under NTP/migration; use "
                   "time.monotonic() or time.perf_counter())")

    _WALL = "time.time"

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        for scope in module.scopes:
            wall = self._wall_names(module, scope)
            for node in _walk_own(scope.node):
                if (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.Sub)
                        and self._is_wall(module, node.left, wall)
                        and self._is_wall(module, node.right, wall)):
                    findings.append(self.finding(
                        module, node,
                        f"duration from two time.time() readings in "
                        f"'{scope.name}' — the wall clock slews and "
                        f"steps; time a span with time.monotonic() or "
                        f"time.perf_counter(), or justify with a "
                        f"suppression"))
        return findings

    def _wall_names(self, module: ModuleInfo, scope) -> Set[str]:
        """Names / self-attributes assigned from ``time.time()`` in the
        scope (the ``t0 = time.time()`` half of the anti-pattern)."""
        wall: Set[str] = set()
        for node in _walk_own(scope.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = getattr(node, "value", None)
                if (isinstance(value, ast.Call)
                        and module.call_name(value) == self._WALL):
                    wall.update(assign_targets(node))
        return wall

    def _is_wall(self, module: ModuleInfo, node: ast.expr,
                 wall: Set[str]) -> bool:
        if (isinstance(node, ast.Call)
                and module.call_name(node) == self._WALL):
            return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = _dotted(node)
            return d is not None and d in wall
        return False


# ---------------------------------------------------------------------------
# GLT016 unbalanced-profiler-capture
# ---------------------------------------------------------------------------

@register
class UnbalancedProfilerCapture(Rule):
    """``jax.profiler.start_trace`` without a guaranteed stop.

    A profiler trace left open skews every measurement after it and, on
    TPU, pins the trace buffer until process exit; an exception between
    ``start_trace`` and ``stop_trace`` leaks the capture exactly when
    the run is most worth tracing.  The stop must be UNCONDITIONAL — in
    a ``finally`` block — or the capture should go through the balanced
    context manager :func:`glt_tpu.obs.profiler.capture` (which carries
    the try/finally inside).

    Accepted shapes (both used in this tree):

    * the start inside a ``try`` whose ``finally`` stops, and
    * the start immediately before a ``try`` in the same statement
      list whose ``finally`` stops (the contextmanager idiom:
      ``start_trace(d); try: yield; finally: stop_trace()``).

    ``start_server`` pairs with ``stop_server`` the same way.
    """
    name = "unbalanced-profiler-capture"
    code = "GLT016"
    severity = Severity.ERROR
    description = ("jax.profiler.start_trace/start_server without the "
                   "matching stop in a finally (use try/finally or "
                   "glt_tpu.obs.profiler.capture())")

    _PAIRS = {
        "jax.profiler.start_trace": "jax.profiler.stop_trace",
        "jax.profiler.start_server": "jax.profiler.stop_server",
    }

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        # module.scopes holds only function scopes; a module-level bare
        # start (scripts, __main__ blocks) leaks the same way.
        roots = [(module.tree, "<module>")] + [
            (s.node, s.name) for s in module.scopes]
        for root, scope_name in roots:
            starts: List[ast.Call] = []
            trys: List[ast.Try] = []
            for node in _walk_own(root):
                if (isinstance(node, ast.Call)
                        and module.call_name(node) in self._PAIRS):
                    starts.append(node)
                elif isinstance(node, ast.Try):
                    trys.append(node)
            if not starts:
                continue
            start_ids = {id(n) for n in starts}
            balanced: Set[int] = set()
            # Shape 1: start inside a try whose finally has the stop.
            for t in trys:
                stops = self._final_stops(module, t)
                if not stops:
                    continue
                for part in (t.body, t.handlers, t.orelse):
                    for stmt in part:
                        for n in ast.walk(stmt):
                            if (id(n) in start_ids and
                                    self._PAIRS[module.call_name(n)]
                                    in stops):
                                balanced.add(id(n))
            # Shape 2: start before a try (same statement list) whose
            # finally has the stop — the contextmanager idiom.
            # (walk_own yields children only, so include the root node:
            # its .body is the outermost statement list.)
            for holder in [root, *_walk_own(root)]:
                for field in ("body", "orelse", "finalbody"):
                    stmts = getattr(holder, field, None)
                    if not isinstance(stmts, list):
                        continue
                    self._scan_block(module, stmts, start_ids, balanced)
            for n in starts:
                if id(n) in balanced:
                    continue
                name = module.call_name(n)
                findings.append(self.finding(
                    module, n,
                    f"{name}() in '{scope_name}' without "
                    f"{self._PAIRS[name].split('.')[-1]}() in a finally "
                    f"— an exception leaks the capture; wrap in "
                    f"try/finally or use glt_tpu.obs.profiler.capture()"))
        return findings

    def _final_stops(self, module: ModuleInfo, t: ast.Try) -> Set[str]:
        stops: Set[str] = set()
        for stmt in t.finalbody:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    name = module.call_name(n)
                    if name in self._PAIRS.values():
                        stops.add(name)
        return stops

    def _scan_block(self, module: ModuleInfo, stmts: List[ast.stmt],
                    start_ids: Set[int], balanced: Set[int]) -> None:
        for i, stmt in enumerate(stmts):
            pending = [n for n in ast.walk(stmt)
                       if id(n) in start_ids and id(n) not in balanced]
            if not pending:
                continue
            later_stops: Set[str] = set()
            for nxt in stmts[i + 1:]:
                if isinstance(nxt, ast.Try):
                    later_stops |= self._final_stops(module, nxt)
            for n in pending:
                if self._PAIRS[module.call_name(n)] in later_stops:
                    balanced.add(id(n))


# ---------------------------------------------------------------------------
# GLT022 lossy-dtype-narrowing
# ---------------------------------------------------------------------------

@register
class LossyDtypeNarrowing(Rule):
    """Narrowing ``.astype`` casts on feature-path arrays outside the
    codec module.

    Feature compression is centralized in ``glt_tpu/store/quant.py``:
    its codecs carry per-column scale/zero metadata in the store
    manifest and meet a bounded-error contract, and the gather
    epilogues widen back to the logical dtype on-chip.  A bare
    ``x.astype(np.float16)`` / ``.astype(jnp.bfloat16)`` /
    ``.astype("int8")`` elsewhere silently discards precision with no
    metadata to undo it — the error neither shows up in the manifest
    nor in the parity suites that compare the raw and compressed arms.
    Route narrowing through a quant codec (or keep it inside
    ``store/quant.py`` where the contract is tested).
    """
    name = "lossy-dtype-narrowing"
    code = "GLT022"
    severity = Severity.ERROR
    description = ("bare narrowing .astype() on arrays outside "
                   "store/quant.py (precision silently discarded with no "
                   "codec metadata to dequantize)")

    # Sub-f32 floats and sub-i32 ints: casts that drop mantissa or
    # range.  int32 itself stays legal — ids are relabeled into int32
    # range deliberately (GLT004 owns that hazard).
    _NARROW = {
        "numpy.float16", "jax.numpy.float16",
        "jax.numpy.bfloat16", "ml_dtypes.bfloat16",
        "numpy.int8", "jax.numpy.int8",
        "numpy.uint8", "jax.numpy.uint8",
        "numpy.int16", "jax.numpy.int16",
        "numpy.uint16", "jax.numpy.uint16",
        "jax.numpy.float8_e4m3fn", "jax.numpy.float8_e5m2",
        "ml_dtypes.float8_e4m3fn", "ml_dtypes.float8_e5m2",
    }
    _NARROW_STRINGS = {
        "float16", "bfloat16", "int8", "uint8", "int16", "uint16",
        "float8_e4m3fn", "float8_e5m2",
    }
    _EXEMPT_SUFFIX = ("store/quant.py", "store\\quant.py")

    def _narrow_target(self, module: ModuleInfo,
                       arg: ast.expr) -> Optional[str]:
        resolved = module.imports.resolve(arg)
        if resolved in self._NARROW:
            return resolved
        if (isinstance(arg, ast.Constant)
                and arg.value in self._NARROW_STRINGS):
            return str(arg.value)
        # np.dtype("float16") / jnp.dtype(...) wrappers
        if isinstance(arg, ast.Call):
            name = module.call_name(arg) or ""
            if name in ("numpy.dtype", "jax.numpy.dtype") and arg.args:
                return self._narrow_target(module, arg.args[0])
        return None

    def check(self, module: ModuleInfo, project=None
              ) -> List[Finding]:
        path = module.path.replace("\\", "/")
        if path.endswith("store/quant.py") or getattr(
                module, "module_name", "").endswith("store.quant"):
            return []
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args):
                continue
            target = self._narrow_target(module, node.args[0])
            if target is None:
                continue
            findings.append(self.finding(
                module, node,
                f"narrowing cast .astype({target}) outside "
                f"store/quant.py: precision is dropped with no codec "
                f"metadata to dequantize — encode through a "
                f"glt_tpu.store.quant codec instead"))
        return findings


# ---------------------------------------------------------------------------
# GLT023 unjittered-retry-loop
# ---------------------------------------------------------------------------

@register
class UnjitteredRetryLoop(Rule):
    """Constant-duration sleep inside a network retry loop.

    A retry loop that catches transport errors and then sleeps a fixed
    constant re-synchronizes every client that failed together: when a
    replica dies, all of its in-flight callers observe the reset within
    milliseconds of each other, all sleep exactly X seconds, and all
    hammer the successor in the same instant — a retry storm that turns
    one failure into rolling overload.  Every retry path in this tree
    (``subgraph_with_retry``, ``RemoteServerConnection``,
    ``FleetRouter`` failover) paces as
    ``min(cap, base * 2**attempt) * (0.5 + 0.5 * rng.random())`` —
    exponential backoff with full-range jitter — so a failed cohort
    decorrelates instead of marching in lockstep.

    Flagged: a ``time.sleep(X)`` or ``<event>.wait(X)`` whose duration
    is a compile-time constant (literals and arithmetic over literals),
    inside a ``while``/``for`` loop that also catches a transport-class
    exception (the ``OSError``/``ConnectionError`` family,
    ``TimeoutError``, ``EOFError``, ``socket.*``, ``*ProtocolError``).
    A duration with any computed component — a name, an attribute, a
    call — is clean: that computation is exactly where backoff and
    jitter live.  Loops that catch only ``Exception`` (heartbeat/poll
    loops pacing themselves, not re-contacting a failed peer) are not
    retry loops and stay clean.
    """
    name = "unjittered-retry-loop"
    code = "GLT023"
    severity = Severity.ERROR
    description = ("constant-duration sleep in a network retry loop "
                   "(failed cohort retries in lockstep — use jittered "
                   "exponential backoff)")

    _NETWORK_EXCS = {
        "OSError", "IOError", "ConnectionError", "ConnectionResetError",
        "ConnectionRefusedError", "ConnectionAbortedError",
        "BrokenPipeError", "TimeoutError", "EOFError",
        "socket.timeout", "socket.error", "socket.gaierror",
        "socket.herror",
    }

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        flagged: Set[int] = set()
        roots = [module.tree] + [s.node for s in module.scopes]
        for root in roots:
            for node in _walk_own(root):
                if not isinstance(node, (ast.While, ast.For)):
                    continue
                if not self._has_network_handler(module, node):
                    continue
                for call in _walk_own(node):
                    if (isinstance(call, ast.Call)
                            and id(call) not in flagged
                            and self._is_const_sleep(module, call)):
                        flagged.add(id(call))
                        findings.append(self.finding(
                            module, call,
                            f"constant sleep in a loop retrying "
                            f"transport errors — every caller that "
                            f"failed together retries together; pace "
                            f"with jittered exponential backoff "
                            f"(min(cap, base * 2**attempt) * random "
                            f"jitter)"))
        return findings

    # -- helpers ----------------------------------------------------------
    def _has_network_handler(self, module: ModuleInfo,
                             loop: ast.AST) -> bool:
        for node in _walk_own(loop):
            if not isinstance(node, ast.ExceptHandler):
                continue
            types = node.type
            if types is None:
                continue    # bare except: a poll loop, not a retry loop
            elts = types.elts if isinstance(types, ast.Tuple) else [types]
            if any(self._is_network_exc(module, e) for e in elts):
                return True
        return False

    def _is_network_exc(self, module: ModuleInfo, expr: ast.expr) -> bool:
        d = _dotted(expr)
        if d is None:
            return False
        resolved = module.imports.resolve(expr) or d
        if d in self._NETWORK_EXCS or resolved in self._NETWORK_EXCS:
            return True
        return d.split(".")[-1].endswith("ProtocolError")

    def _is_const_sleep(self, module: ModuleInfo, call: ast.Call) -> bool:
        if not call.args or call.keywords:
            return False
        name = module.call_name(call)
        is_sleep = name == "time.sleep"
        is_wait = (isinstance(call.func, ast.Attribute)
                   and call.func.attr == "wait")
        if not (is_sleep or is_wait):
            return False
        return self._const_duration(call.args[0])

    def _const_duration(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, (int, float)) \
                and not isinstance(node.value, bool)
        if isinstance(node, ast.UnaryOp):
            return self._const_duration(node.operand)
        if isinstance(node, ast.BinOp):
            return (self._const_duration(node.left)
                    and self._const_duration(node.right))
        return False


def _iter_const_ints(node: ast.expr) -> Iterator[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for el in node.elts:
            yield from _iter_const_ints(el)


def all_rules() -> List[Rule]:
    return [cls() for cls in RULES.values()]


# The concurrency rules (GLT008/GLT009), the Pallas device-program model
# (GLT017-019, kernelmodel.py), the shard_map collective checks
# (GLT020/021, spmd.py), the wire-protocol verification (GLT024-026,
# protocol.py), and the thread-safety pass (GLT027, threads.py) live in
# their own modules but register into the same RULES table; importing
# here completes the registry for every entry point (cli, tests,
# programmatic use).
from . import concurrency  # noqa: E402,F401  (registration side effect)
from . import kernelmodel  # noqa: E402,F401  (registration side effect)
from . import spmd  # noqa: E402,F401  (registration side effect)
from . import protocol  # noqa: E402,F401  (registration side effect)
from . import threads  # noqa: E402,F401  (registration side effect)
