"""Findings, severities, suppression parsing and report formatting.

A finding is one (rule, location, message) triple.  Suppression is comment
driven, pylint style but namespaced to this tool so the two never collide:

* ``# gltlint: disable=rule-a,rule-b`` on the offending line silences those
  rules for that line only;
* ``# gltlint: disable-next=rule-a`` on the line above silences the line
  below (for lines whose trailing comment space is already spoken for);
* ``# gltlint: disable-file=rule-a`` anywhere in the file silences the rule
  for the whole file;
* the rule list may use rule names (``host-sync-in-jit``) or codes
  (``GLT001``), and ``all`` matches every rule.

Suppressions should carry a justification comment — the CI gate treats a
bare suppression the same as a justified one, but reviewers should not.
"""
from __future__ import annotations

import enum
import io
import json
import os
import re
import tokenize
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Set, Tuple


class Severity(enum.IntEnum):
    """Per-rule severity; only ERROR findings fail the CI gate."""
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error" / "warning" in reports
        return self.name.lower()


@dataclass(frozen=True)
class Finding:
    """One diagnostic emitted by a rule."""
    path: str
    line: int
    col: int
    rule: str          # rule name, e.g. "host-sync-in-jit"
    code: str          # rule code, e.g. "GLT001"
    severity: Severity
    message: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{str(self.severity).upper()} {self.code} "
                f"[{self.rule}] {self.message}")


_SUPPRESS_RE = re.compile(
    r"#\s*gltlint:\s*(disable|disable-next|disable-file)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclass
class Suppressions:
    """Per-file suppression table parsed from comments."""
    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    whole_file: Set[str] = field(default_factory=set)

    @classmethod
    def from_source(cls, source: str) -> "Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            comments: List[Tuple[int, str]] = [
                (tok.start[0], tok.string) for tok in tokens
                if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return sup
        for line, text in comments:
            for m in _SUPPRESS_RE.finditer(text):
                kind = m.group(1)
                rules = {r.strip().lower()
                         for r in m.group(2).split(",") if r.strip()}
                if kind == "disable-file":
                    sup.whole_file |= rules
                elif kind == "disable-next":
                    sup.by_line.setdefault(line + 1, set()).update(rules)
                else:
                    sup.by_line.setdefault(line, set()).update(rules)
        return sup

    def is_suppressed(self, finding: Finding) -> bool:
        keys = {"all", finding.rule.lower(), finding.code.lower()}
        if keys & self.whole_file:
            return True
        return bool(keys & self.by_line.get(finding.line, set()))


def apply_suppressions(findings: Iterable[Finding],
                       suppressions: Suppressions) -> List[Finding]:
    return [f for f in findings if not suppressions.is_suppressed(f)]


def format_report(findings: List[Finding]) -> str:
    """Human-readable report: findings sorted by location + a summary."""
    lines = [f.format() for f in
             sorted(findings, key=lambda f: (f.path, f.line, f.col))]
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    n_warn = len(findings) - n_err
    if findings:
        lines.append("")
    lines.append(f"gltlint: {n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)


def format_json(findings: List[Finding]) -> str:
    """Machine-readable report: ``{"findings": [...], "summary": ...}``."""
    items = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        d = asdict(f)
        d["severity"] = str(f.severity)
        items.append(d)
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    return json.dumps({
        "findings": items,
        "summary": {"errors": n_err, "warnings": len(findings) - n_err},
    }, indent=2)


def _gh_escape(text: str, prop: bool = False) -> str:
    """GitHub workflow-command escaping (data vs property positions)."""
    out = text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if prop:
        out = out.replace(":", "%3A").replace(",", "%2C")
    return out


def format_github(findings: List[Finding]) -> str:
    """GitHub Actions workflow commands: one ``::error``/``::warning``
    annotation per finding (renders inline on the PR diff), plus the
    human summary line for the job log."""
    lines = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        level = "error" if f.severity is Severity.ERROR else "warning"
        title = _gh_escape(f"{f.code} {f.rule}", prop=True)
        lines.append(
            f"::{level} file={_gh_escape(f.path, prop=True)},"
            f"line={f.line},col={f.col},title={title}"
            f"::{_gh_escape(f.message)}")
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    lines.append(f"gltlint: {n_err} error(s), "
                 f"{len(findings) - n_err} warning(s)")
    return "\n".join(lines)


# -- baseline ----------------------------------------------------------------
#
# A baseline lets a new (or newly-strengthened) rule land before the tree
# is fully clean: record today's findings, gate only on findings NOT in
# the record.  Keys deliberately exclude line/column numbers (and mask
# digits inside messages) so unrelated edits that shift code do not
# resurrect baselined findings.

def finding_key(f: Finding) -> str:
    return f"{f.path}|{f.code}|{re.sub(r'[0-9]+', '#', f.message)}"


def load_baseline(path: str) -> Set[str]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a gltlint baseline file")
    return set(data["findings"])


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    keys = sorted({finding_key(f) for f in findings})
    # Atomic publish (GLT011): CI reads the committed baseline while a
    # developer may be regenerating it — never expose a torn file.
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": keys}, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)


def split_by_baseline(findings: List[Finding], baseline: Set[str]
                      ) -> Tuple[List[Finding], int]:
    """(new findings, number suppressed by the baseline)."""
    fresh = [f for f in findings if finding_key(f) not in baseline]
    return fresh, len(findings) - len(fresh)
