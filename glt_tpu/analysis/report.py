"""Findings, severities, suppression parsing and report formatting.

A finding is one (rule, location, message) triple.  Suppression is comment
driven, pylint style but namespaced to this tool so the two never collide:

* ``# gltlint: disable=rule-a,rule-b`` on the offending line silences those
  rules for that line only;
* ``# gltlint: disable-next=rule-a`` on the line above silences the line
  below (for lines whose trailing comment space is already spoken for);
* ``# gltlint: disable-file=rule-a`` anywhere in the file silences the rule
  for the whole file;
* the rule list may use rule names (``host-sync-in-jit``) or codes
  (``GLT001``), and ``all`` matches every rule.

Suppressions should carry a justification comment — the CI gate treats a
bare suppression the same as a justified one, but reviewers should not.
"""
from __future__ import annotations

import enum
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Set, Tuple


class Severity(enum.IntEnum):
    """Per-rule severity; only ERROR findings fail the CI gate."""
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error" / "warning" in reports
        return self.name.lower()


@dataclass(frozen=True)
class Finding:
    """One diagnostic emitted by a rule."""
    path: str
    line: int
    col: int
    rule: str          # rule name, e.g. "host-sync-in-jit"
    code: str          # rule code, e.g. "GLT001"
    severity: Severity
    message: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{str(self.severity).upper()} {self.code} "
                f"[{self.rule}] {self.message}")


_SUPPRESS_RE = re.compile(
    r"#\s*gltlint:\s*(disable|disable-next|disable-file)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclass
class Suppressions:
    """Per-file suppression table parsed from comments."""
    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    whole_file: Set[str] = field(default_factory=set)

    @classmethod
    def from_source(cls, source: str) -> "Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            comments: List[Tuple[int, str]] = [
                (tok.start[0], tok.string) for tok in tokens
                if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return sup
        for line, text in comments:
            for m in _SUPPRESS_RE.finditer(text):
                kind = m.group(1)
                rules = {r.strip().lower()
                         for r in m.group(2).split(",") if r.strip()}
                if kind == "disable-file":
                    sup.whole_file |= rules
                elif kind == "disable-next":
                    sup.by_line.setdefault(line + 1, set()).update(rules)
                else:
                    sup.by_line.setdefault(line, set()).update(rules)
        return sup

    def is_suppressed(self, finding: Finding) -> bool:
        keys = {"all", finding.rule.lower(), finding.code.lower()}
        if keys & self.whole_file:
            return True
        return bool(keys & self.by_line.get(finding.line, set()))


def apply_suppressions(findings: Iterable[Finding],
                       suppressions: Suppressions) -> List[Finding]:
    return [f for f in findings if not suppressions.is_suppressed(f)]


def format_report(findings: List[Finding]) -> str:
    """Human-readable report: findings sorted by location + a summary."""
    lines = [f.format() for f in
             sorted(findings, key=lambda f: (f.path, f.line, f.col))]
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    n_warn = len(findings) - n_err
    if findings:
        lines.append("")
    lines.append(f"gltlint: {n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)
