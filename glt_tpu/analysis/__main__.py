"""``python -m glt_tpu.analysis`` entry point."""
import sys

from .cli import main

sys.exit(main())
