"""SPMD collective verification inside shard_map bodies: GLT020/021.

Every ``jax.shard_map`` body in ``parallel/`` is one SPMD program: all
shards execute the same trace, and every collective (``lax.psum``,
``lax.all_to_all``, ``lax.ppermute``, ...) is a rendezvous — a shard
that skips one leaves the others blocked in the runtime with no Python
frame to debug.  Two hazards are statically checkable:

* **GLT020 divergent-collective** — a collective under control flow
  (``lax.cond`` / ``lax.switch`` / Python ``if`` / ``lax.while_loop``)
  whose predicate data-depends on a *shard-local* value.  Shard-local
  taint seeds from ``lax.axis_index`` results and propagates through
  assignments; values that pass through a *replicating* collective
  (``psum``/``pmean``/``pmax``/``pmin``/``all_gather``) are uniform
  again and launder the taint — the ``nvalid = psum(...)`` skip-step
  guard in dist_train is the calibrated negative.  Findings carry the
  dependence chain (variable, axis_index origin line) because the
  deadlock reproduces only on multi-shard hardware.

* **GLT021 unknown-axis-name** — a collective or ``PartitionSpec``
  whose ``axis_name`` does not resolve to an axis bound by the
  enclosing ``shard_map``'s mesh.  Axis sets come from ``Mesh(...,
  axis_names)`` / ``jax.make_mesh`` construction; parametrically-built
  meshes (``multihost.global_mesh(axis_name)``) are *open* and produce
  no findings — only a literal/constant mismatch (the classic renamed
  ``('host', 'chip')`` refactor leaving a stale ``'shard'`` string)
  fires.  String constants resolve through the project symbol table
  (module constants included), matching the engine's calibrated-quiet
  contract: unresolvable means silent, not worst-case.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .kernelmodel import const_value
from .report import Finding, Severity
from .rules import Rule, register
from .symbols import FunctionSymbol
from .visitor import (
    SHARD_MAP_NAMES,
    FunctionScope,
    ModuleInfo,
    _unwrap_traced_target,
)

# canonical collective name -> index of its axis_name argument
_COLLECTIVES = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
}
# Collectives whose *result* is identical on every shard: they launder
# shard-local taint (psum_scatter/ppermute/all_to_all do NOT — their
# outputs differ per shard).
_REPLICATING = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather",
}
_COND_NAMES = {"jax.lax.cond", "jax.lax.switch"}
_WHILE = "jax.lax.while_loop"
_FORI = "jax.lax.fori_loop"
_MESH_NAMES = {"jax.sharding.Mesh", "jax.interpreters.pxla.Mesh",
               "jax.experimental.maps.Mesh"}
_MAKE_MESH = {"jax.make_mesh", "jax.sharding.make_mesh"}
_PSPEC_NAMES = {"jax.sharding.PartitionSpec"}


def _is_axis_index(module: ModuleInfo, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and module.call_name(node) == "jax.lax.axis_index")


def _collective_calls(module: ModuleInfo, root: ast.AST
                      ) -> List[Tuple[ast.Call, str, int]]:
    out = []
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            name = module.call_name(node)
            if name in _COLLECTIVES:
                out.append((node, name, _COLLECTIVES[name]))
    return out


# ---------------------------------------------------------------------------
# GLT020 divergent-collective
# ---------------------------------------------------------------------------

def _assign_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(_assign_names(el))
        return out
    return []


def _tainted_reads(module: ModuleInfo, expr: ast.AST, taint: Set[str]
                   ) -> Optional[str]:
    """First tainted Name read in ``expr``, skipping subtrees whose value
    is replicated by a reducing collective (taint laundering)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            name = module.call_name(node)
            if name in _REPLICATING:
                continue            # uniform result: do not descend
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in taint:
            return node.id
        stack.extend(ast.iter_child_nodes(node))
    return None


def _unit_taint(module: ModuleInfo, unit: ast.AST
                ) -> Dict[str, Tuple[str, int]]:
    """Shard-local variables in a top-level scope's whole subtree:
    ``{name: (seed description, seed line)}``.  Seeded by
    ``lax.axis_index`` results, propagated through assignments (nested
    defs included — closures share the namespace)."""
    origin: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(unit):
        if isinstance(node, ast.Assign):
            for sub in ast.walk(node.value):
                if _is_axis_index(module, sub):
                    for name in _assign_names(node.targets[0]) if \
                            len(node.targets) == 1 else \
                            [n for t in node.targets
                             for n in _assign_names(t)]:
                        origin.setdefault(
                            name, (f"lax.axis_index at line {sub.lineno}",
                                   sub.lineno))
                    break
    for _ in range(3):               # shallow chains; fixpoint fast
        changed = False
        for node in ast.walk(unit):
            if not isinstance(node, ast.Assign):
                continue
            hit = _tainted_reads(module, node.value, set(origin))
            if hit is None:
                continue
            for t in node.targets:
                for name in _assign_names(t):
                    if name not in origin:
                        origin[name] = (
                            f"'{hit}' <- {origin[hit][0]}",
                            origin[hit][1])
                        changed = True
        if not changed:
            break
    return origin


def _scope_by_name(module: ModuleInfo, unit: ast.AST,
                   name: str) -> Optional[ast.AST]:
    for node in ast.walk(unit):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    for node in module.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _branch_bodies(module: ModuleInfo, unit: ast.AST,
                   exprs: List[ast.expr]) -> List[ast.AST]:
    out: List[ast.AST] = []
    for e in exprs:
        if isinstance(e, ast.Lambda):
            out.append(e.body)
        elif isinstance(e, ast.Name):
            fn = _scope_by_name(module, unit, e.id)
            if fn is not None:
                out.append(fn)
        elif isinstance(e, ast.Call):  # partial(fn, ...) and friends
            out.append(e)
    return out


def _has_collective(module: ModuleInfo, roots: List[ast.AST]) -> bool:
    return any(_collective_calls(module, r) for r in roots)


@register
class DivergentCollective(Rule):
    """Collectives under shard-dependent control flow deadlock."""
    name = "divergent-collective"
    code = "GLT020"
    severity = Severity.ERROR
    description = ("a collective under lax.cond/switch/while or Python "
                   "control flow whose predicate depends on a "
                   "shard-local value (lax.axis_index taint): shards "
                   "diverge and the rendezvous deadlocks")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        if "axis_index" not in module.source:
            return findings
        for scope in module.scopes:
            if scope.parent is not None:
                continue
            unit = scope.node
            taint = _unit_taint(module, unit)
            if not taint:
                continue
            findings.extend(self._check_unit(module, unit, taint))
        return findings

    def _flag(self, module, node, pred_text, hit, taint, where):
        desc, line = taint[hit]
        return self.finding(
            module, node,
            f"collective inside {where} whose predicate "
            f"'{pred_text}' depends on shard-local '{hit}' "
            f"({desc}, seeded at line {line}): shards take different "
            f"branches and the collective rendezvous deadlocks — hoist "
            f"the collective out of the branch or make the predicate "
            f"uniform (reduce it with psum/pmax first)")

    def _check_unit(self, module: ModuleInfo, unit: ast.AST,
                    taint: Dict[str, Tuple[str, int]]) -> List[Finding]:
        findings: List[Finding] = []
        names = set(taint)
        for node in ast.walk(unit):
            if isinstance(node, (ast.If, ast.While)):
                hit = _tainted_reads(module, node.test, names)
                if hit is None:
                    continue
                bodies: List[ast.AST] = list(node.body) + list(node.orelse)
                if _has_collective(module, bodies):
                    findings.append(self._flag(
                        module, node, ast.unparse(node.test), hit, taint,
                        "a Python branch"))
            elif isinstance(node, ast.Call):
                name = module.call_name(node)
                if name in _COND_NAMES and node.args:
                    hit = _tainted_reads(module, node.args[0], names)
                    if hit is None:
                        continue
                    branches = _branch_bodies(module, unit, node.args[1:])
                    if _has_collective(module, branches):
                        findings.append(self._flag(
                            module, node, ast.unparse(node.args[0]), hit,
                            taint, name.rsplit('.', 1)[-1]))
                elif name == _WHILE and len(node.args) >= 2:
                    cond = _branch_bodies(module, unit, node.args[:1])
                    hit = None
                    for c in cond:
                        hit = _tainted_reads(module, c, names)
                        if hit:
                            break
                    if hit is None:
                        continue
                    body = _branch_bodies(module, unit, node.args[1:2])
                    if _has_collective(module, body):
                        findings.append(self._flag(
                            module, node,
                            ast.unparse(node.args[0]), hit, taint,
                            "lax.while_loop (shard-dependent trip "
                            "count)"))
                elif name == _FORI and len(node.args) >= 3:
                    hit = (_tainted_reads(module, node.args[0], names)
                           or _tainted_reads(module, node.args[1], names))
                    if hit is None:
                        continue
                    body = _branch_bodies(module, unit, node.args[2:3])
                    if _has_collective(module, body):
                        findings.append(self._flag(
                            module, node,
                            ast.unparse(node.args[0]) + ", "
                            + ast.unparse(node.args[1]), hit, taint,
                            "lax.fori_loop (shard-dependent trip "
                            "count)"))
        return findings


# ---------------------------------------------------------------------------
# GLT021 unknown-axis-name
# ---------------------------------------------------------------------------

def _axis_literal(module: ModuleInfo, expr: Optional[ast.expr],
                  project) -> Optional[Set[str]]:
    """Axis names an expression statically resolves to, else None
    (parametric/unknown — calibrated-quiet)."""
    if expr is None:
        return None
    val = const_value(module, expr, project)
    if isinstance(val, str):
        return {val}
    if isinstance(val, tuple) and val \
            and all(isinstance(v, str) for v in val):
        return set(val)
    return None


def _mesh_axes(module: ModuleInfo, scope, expr: ast.expr,
               project) -> Optional[Set[str]]:
    """Axis set bound by a mesh expression, else None (open mesh)."""
    call = expr
    if isinstance(expr, ast.Name):
        cur = scope
        while cur is not None:
            for node in ast.walk(cur.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in node.targets):
                    call = node.value
            cur = cur.parent
        if call is expr:
            for node in module.tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in node.targets):
                    call = node.value
    if not isinstance(call, ast.Call):
        return None
    name = module.call_name(call)
    axis_expr: Optional[ast.expr] = None
    if name in _MESH_NAMES and len(call.args) >= 2:
        axis_expr = call.args[1]
    elif name in _MESH_NAMES:
        for kw in call.keywords:
            if kw.arg == "axis_names":
                axis_expr = kw.value
    elif name in _MAKE_MESH:
        axis_expr = (call.args[1] if len(call.args) >= 2 else None)
        if axis_expr is None:
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    axis_expr = kw.value
    else:
        return None
    return _axis_literal(module, axis_expr, project)


def _axis_params(fn: ast.FunctionDef, module: ModuleInfo) -> Set[str]:
    """Parameter names a function forwards as collective axis args."""
    params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
              + fn.args.posonlyargs}
    out: Set[str] = set()
    for call, _, axis_pos in _collective_calls(module, fn):
        axis = (call.args[axis_pos] if len(call.args) > axis_pos
                else next((k.value for k in call.keywords
                           if k.arg == "axis_name"), None))
        if isinstance(axis, ast.Name) and axis.id in params:
            out.add(axis.id)
    return out


def _call_literal_bindings(call: ast.Call, fn: ast.FunctionDef
                           ) -> Dict[str, ast.expr]:
    """Callee-param -> literal-string argument bindings at a call site."""
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: Dict[str, ast.expr] = {}
    for i, arg in enumerate(call.args):
        if i < len(pos):
            out[pos[i]] = arg
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = kw.value
    return out


@register
class UnknownAxisName(Rule):
    """Collective/PartitionSpec axes must exist on the bound mesh."""
    name = "unknown-axis-name"
    code = "GLT021"
    severity = Severity.ERROR
    description = ("a collective or PartitionSpec inside shard_map "
                   "names an axis the bound mesh does not define "
                   "(stale string after a mesh-axis rename); "
                   "parametric meshes are skipped")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        if "shard_map" not in module.source and \
                "xmap" not in module.source:
            return findings
        # Walk each shard_map call exactly once, with its owning scope.
        seen: Set[int] = set()
        for scope in module.scopes:
            for node in ast.walk(scope.node):
                if id(node) in seen:
                    continue
                if isinstance(node, ast.Call) \
                        and module.call_name(node) in SHARD_MAP_NAMES:
                    seen.add(id(node))
                    findings.extend(self._check_site(
                        module, scope, node, project))
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and id(node) not in seen \
                    and module.call_name(node) in SHARD_MAP_NAMES:
                findings.extend(self._check_site(
                    module, None, node, project))
        return findings

    def _check_site(self, module: ModuleInfo,
                    scope: Optional[FunctionScope], call: ast.Call,
                    project) -> List[Finding]:
        mesh_expr = next((k.value for k in call.keywords
                          if k.arg == "mesh"),
                         call.args[1] if len(call.args) > 1 else None)
        if mesh_expr is None:
            return []
        axes = _mesh_axes(module, scope, mesh_expr, project)
        if axes is None:
            return []                      # open mesh: stay quiet
        findings: List[Finding] = []

        def check_axis(node, expr, what):
            names = _axis_literal(module, expr, project)
            if names is None:
                return
            missing = sorted(names - axes)
            if missing:
                findings.append(self.finding(
                    module, node,
                    f"{what} names axis {missing} but the enclosing "
                    f"shard_map's mesh binds only "
                    f"{sorted(axes)} — every shard would wait on a "
                    f"rendezvous over an axis that does not exist "
                    f"(stale axis string after a mesh rename?)"))

        # PartitionSpec literals in the in_specs/out_specs expressions.
        for kw in call.keywords:
            if kw.arg not in ("in_specs", "out_specs"):
                continue
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Call) \
                        and module.call_name(sub) in _PSPEC_NAMES:
                    for arg in sub.args:
                        check_axis(sub, arg, "PartitionSpec")

        # Collectives in the traced body (nested defs included), plus
        # one transitive step into project functions the body calls
        # with literal axis strings.
        target = _unwrap_traced_target(call, module.imports)
        body: Optional[ast.AST] = None
        if isinstance(target, ast.Lambda):
            body = target.body
        elif isinstance(target, ast.Name):
            unit = scope.node if scope is not None else module.tree
            body = _scope_by_name(module, unit, target.id)
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id == "self"):
            body = _scope_by_name(module, module.tree, target.attr)
        if body is None:
            return findings
        for coll, name, axis_pos in _collective_calls(module, body):
            axis = (coll.args[axis_pos] if len(coll.args) > axis_pos
                    else next((k.value for k in coll.keywords
                               if k.arg == "axis_name"), None))
            check_axis(coll, axis, name.rsplit(".", 1)[-1])
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            fn_def: Optional[ast.FunctionDef] = None
            callee_mod = module
            if project is not None:
                sym = project.resolve_call(module, scope, sub)
                if isinstance(sym, FunctionSymbol) and isinstance(
                        sym.scope.node, ast.FunctionDef):
                    fn_def = sym.scope.node
                    callee_mod = sym.module
            if fn_def is None and isinstance(sub.func, ast.Name):
                got = _scope_by_name(module, module.tree, sub.func.id)
                if isinstance(got, ast.FunctionDef):
                    fn_def = got
            if fn_def is None or fn_def is body:
                continue
            fwd = _axis_params(fn_def, callee_mod)
            if not fwd:
                continue
            for param, arg in _call_literal_bindings(sub, fn_def).items():
                if param in fwd:
                    check_axis(sub, arg,
                               f"axis argument '{param}' of "
                               f"'{fn_def.name}'")
        return findings
