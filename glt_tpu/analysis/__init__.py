"""gltlint — TPU/JAX-aware static analysis for the glt_tpu data engine.

An AST pass over the whole package that catches the silent hazards a TPU
deployment hits at runtime (or never notices): host syncs inside jitted
sampling programs, PRNG key reuse that correlates neighbor draws, Python
scalars baked into traces (recompile storms), int64 id truncation under
x64-disabled JAX, unseeded host RNGs, and use-after-donation.

Usage::

    python -m glt_tpu.analysis [paths...]      # CI gate: exit 1 on errors
    python -m glt_tpu.analysis --list-rules

Programmatic::

    from glt_tpu.analysis import analyze_source, analyze_paths
    findings = analyze_source(src, "module.py")

Suppression (justify every one)::

    x = np.asarray(host_value)  # gltlint: disable=host-sync-in-jit -- host-side branch

See ``docs/analysis.md`` for each rule's TPU failure mode.

This subpackage analyzes with stdlib ``ast`` only and never imports JAX
— the lint runs in CI images with no accelerator stack (numpy, pulled in
by the parent package, is its only third-party import).

Since PR 5 the linter is *interprocedural*: the CLI parses the whole
file set into a :class:`~.symbols.Project` (symbol table -> call graph
-> per-function effect summaries), GLT001/GLT002 follow calls across
modules from any jit/shard_map entry point, and two concurrency rules
(GLT008 lock-order-inversion, GLT009 blocking-call-while-holding-lock)
gate the threaded distributed layer.  See ``docs/analysis.md``.
"""
from .cli import (
    analyze_paths,
    analyze_project,
    analyze_source,
    build_project,
    main,
)
from .report import Finding, Severity, Suppressions, format_report
from .rules import RULES, Rule, all_rules
from .symbols import Project

__all__ = [
    "Finding",
    "Project",
    "RULES",
    "Rule",
    "Severity",
    "Suppressions",
    "all_rules",
    "analyze_paths",
    "analyze_project",
    "analyze_source",
    "build_project",
    "format_report",
    "main",
]
