"""Static device-program model of Pallas kernels: GLT017/018/019.

ROADMAP item 1's blunt truth is that every Pallas kernel in ``ops/``
has only ever run in interpret mode; the first hardware run pays for a
VMEM overflow, an unbalanced DMA ring, or a misaligned tile with an
opaque Mosaic crash.  This pass reconstructs what the chip will see —
without importing JAX — by modeling every ``pl.pallas_call`` site from
the AST:

* **GLT017 vmem-budget-exceeded** — closed-form VMEM byte accounting.
  The model extracts BlockSpec block shapes, ``out_shape`` structs and
  ``pltpu.VMEM`` scratch shapes, resolves each dimension through the
  symbol table (module constants, cross-module constants such as
  ``ops/tpu_limits.py``, local assignments, loop targets, pure int
  helpers like ``_bin_width``, function defaults), and sweeps every
  unresolved symbol over the module's declared ``VMEM_MODEL_DOMAIN`` —
  which the kernel modules build from the same ``CANDIDATE_*`` tuples
  their autotuner sweeps, so every ``candidate_{gather,sample}_params``
  point is checked statically.  Pipelined (gridded) in/out blocks are
  double-buffered by Mosaic and count twice; a dimension the model
  cannot bound is itself an ERROR (the domain declaration is the fix),
  so the accounting stays total rather than silently partial.

* **GLT018 unbalanced-dma-ring** — ``make_async_copy(...).start()`` /
  ``.wait()`` symmetry per ring.  Ring-control guards (``j + nbuf <
  nd``) differ between the fill prologue and the steady state by
  construction; what must match exactly are the *data-dependent*
  predicates (those reading a kernel ref, e.g. ``binid_ref[...] ==
  bin_id``): a row-skip predicate on ``start`` that no ``wait`` shares
  leaves the unguarded wait blocking on a never-signaled semaphore,
  and the converse leaves a dangling DMA to corrupt its slot on reuse
  — the exact bug class ``sample_pallas.py`` hand-comments against.
  Guards are canonicalized by collapsing loop-index arithmetic, so the
  prologue's ``binid_ref[base + k]`` and the steady state's
  ``binid_ref[base + j + nbuf]`` compare equal.

* **GLT019 unaligned-tile-shape** — per resolved buffer point: the
  last dim must tile the 128-lane register and the sublane dim must
  honor the dtype's floor (f32 8, bf16 16, int8/fp8 32 — the rule
  ``gather_pallas`` previously encoded by convention only).  Buffers
  with unresolvable dtypes are checked at the f32 floor.

Limits come from ``ops/tpu_limits.py`` resolved through the project
symbol table (falling back to the same values when linting a lone
fixture), so the kernels and this analyzer can never disagree.
"""
from __future__ import annotations

import ast
import itertools
from typing import Dict, List, Optional, Set, Tuple

from .report import Finding, Severity
from .rules import Rule, register
from .visitor import ModuleInfo, FunctionScope, walk_own

# Canonical (post alias-resolution) dotted names.
_PALLAS_CALL = "jax.experimental.pallas.pallas_call"
_BLOCK_SPEC = ("jax.experimental.pallas.BlockSpec",)
_GRID_SPECS = ("jax.experimental.pallas.tpu.PrefetchScalarGridSpec",
               "jax.experimental.pallas.GridSpec")
_VMEM_SCRATCH = "jax.experimental.pallas.tpu.VMEM"
_DMA_SEM = "jax.experimental.pallas.tpu.SemaphoreType.DMA"
_SHAPE_STRUCT = ("jax.ShapeDtypeStruct",)
_WHEN = "jax.experimental.pallas.when"
_ASYNC_COPY = "jax.experimental.pallas.tpu.make_async_copy"
_LOOPS = ("jax.lax.fori_loop", "jax.lax.while_loop")

_DOMAIN_NAME = "VMEM_MODEL_DOMAIN"
_LIMITS_MODULE_SUFFIX = ".ops.tpu_limits"

# Fallbacks when ops/tpu_limits.py is not part of the analyzed file set
# (single-fixture runs).  Values mirror that module exactly.
_FALLBACK_LIMITS = {
    "VMEM_BYTES": 16 * 2**20,
    "LANE": 128,
    "SUBLANE_F32": 8,
}

_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}

_MAX_CANDIDATES = 64        # cap per-expression candidate sets
_MAX_POINTS = 512           # cap cross products

_NUM_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b if b else None,
    ast.Mod: lambda a, b: a % b if b else None,
    ast.Pow: lambda a, b: a ** b if abs(b) < 64 else None,
    ast.LShift: lambda a, b: a << b if 0 <= b < 64 else None,
    ast.RShift: lambda a, b: a >> b if 0 <= b < 64 else None,
}


def _dtype_name(module: ModuleInfo, expr: Optional[ast.expr]
                ) -> Optional[str]:
    """'float32' for ``jnp.float32`` / ``np.int32`` style exprs."""
    if expr is None:
        return None
    dotted = module.imports.resolve(expr)
    if dotted is None:
        return None
    leaf = dotted.rsplit(".", 1)[-1]
    return leaf if leaf in _ITEMSIZE else None


def _module_consts(module: ModuleInfo) -> Dict[str, ast.expr]:
    """Module-level ``NAME = <expr>`` assignments (last one wins)."""
    cached = getattr(module, "_km_consts", None)
    if cached is not None:
        return cached
    out: Dict[str, ast.expr] = {}
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign) and stmt.value is not None:
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value
        elif (isinstance(stmt, ast.AnnAssign) and stmt.value is not None
              and isinstance(stmt.target, ast.Name)):
            out[stmt.target.id] = stmt.value
    module._km_consts = out
    return out


def _module_functions(module: ModuleInfo) -> Dict[str, ast.FunctionDef]:
    cached = getattr(module, "_km_funcs", None)
    if cached is not None:
        return cached
    out = {stmt.name: stmt for stmt in module.tree.body
           if isinstance(stmt, ast.FunctionDef)}
    module._km_funcs = out
    return out


def _project_module(project, dotted: str
                    ) -> Tuple[Optional[ModuleInfo], Optional[str]]:
    """Split a canonical dotted path into (defining module, attr)."""
    if project is None or "." not in dotted:
        return None, None
    mod_name, attr = dotted.rsplit(".", 1)
    m = project.modules.get(mod_name)
    if m is not None:
        return m, attr
    return None, None


def const_value(module: ModuleInfo, expr: ast.expr, project=None,
                _depth: int = 0):
    """Resolve ``expr`` to a Python constant (int/str/bool/tuple) through
    literals, module constants, and cross-module constants.  Returns the
    value or None (None is never a legal constant here)."""
    if _depth > 12 or expr is None:
        return None
    if isinstance(expr, ast.Constant):
        v = expr.value
        return v if isinstance(v, (int, str, bool)) else None
    if isinstance(expr, (ast.Tuple, ast.List)):
        vals = tuple(const_value(module, el, project, _depth + 1)
                     for el in expr.elts)
        return None if any(v is None for v in vals) else vals
    if isinstance(expr, ast.UnaryOp) and isinstance(
            expr.op, (ast.USub, ast.UAdd)):
        v = const_value(module, expr.operand, project, _depth + 1)
        if isinstance(v, int):
            return -v if isinstance(expr.op, ast.USub) else v
        return None
    if isinstance(expr, ast.BinOp):
        fn = _NUM_BINOPS.get(type(expr.op))
        a = const_value(module, expr.left, project, _depth + 1)
        b = const_value(module, expr.right, project, _depth + 1)
        if fn and isinstance(a, int) and isinstance(b, int):
            try:
                return fn(a, b)
            except Exception:
                return None
        return None
    if isinstance(expr, ast.Subscript):
        base = const_value(module, expr.value, project, _depth + 1)
        idx = const_value(module, expr.slice, project, _depth + 1)
        if isinstance(base, tuple) and isinstance(idx, int) \
                and -len(base) <= idx < len(base):
            return base[idx]
        return None
    if isinstance(expr, ast.Name):
        own = _module_consts(module).get(expr.id)
        if own is not None:
            return const_value(module, own, project, _depth + 1)
        dotted = module.imports.alias_of(expr.id)
        if dotted:
            m, attr = _project_module(project, dotted)
            if m is not None and attr:
                node = _module_consts(m).get(attr)
                if node is not None:
                    return const_value(m, node, project, _depth + 1)
        return None
    if isinstance(expr, ast.Attribute):
        dotted = module.imports.resolve(expr)
        if dotted:
            m, attr = _project_module(project, dotted)
            if m is not None and attr:
                node = _module_consts(m).get(attr)
                if node is not None:
                    return const_value(m, node, project, _depth + 1)
        return None
    return None


def _limits(module: ModuleInfo, project) -> Dict[str, int]:
    """Device limits from ops/tpu_limits.py through the symbol table,
    falling back to mirrored values for lone-fixture analysis."""
    out = dict(_FALLBACK_LIMITS)
    lim_mod = None
    if project is not None:
        for name, m in project.modules.items():
            if name.endswith(_LIMITS_MODULE_SUFFIX) or name == "tpu_limits":
                lim_mod = m
                break
    if lim_mod is None and (module.name.endswith(_LIMITS_MODULE_SUFFIX)
                            or module.name == "tpu_limits"):
        lim_mod = module
    if lim_mod is not None:
        for key in out:
            node = _module_consts(lim_mod).get(key)
            val = (const_value(lim_mod, node, project)
                   if node is not None else None)
            if isinstance(val, int):
                out[key] = val
    return out


def _sublane_floor(dtype: Optional[str], f32_floor: int) -> int:
    size = _ITEMSIZE.get(dtype or "float32", 4)
    return max(f32_floor, 32 // max(size, 1))


# ---------------------------------------------------------------------------
# candidate resolution
# ---------------------------------------------------------------------------

class _SiteResolver:
    """Resolves dimension expressions at one pallas_call site to the set
    of statically-possible values, sweeping unresolved symbols over the
    module's VMEM_MODEL_DOMAIN declaration."""

    def __init__(self, module: ModuleInfo, scope: Optional[FunctionScope],
                 project):
        self.module = module
        self.scope = scope
        self.project = project
        self.simple: Dict[str, List[object]] = {}
        self.joint: List[Tuple[Tuple[str, ...], List[Tuple]]] = []
        self._cache: Dict[str, Optional[List[object]]] = {}
        self._stack: Set[str] = set()
        self._load_domain()

    # -- domain ------------------------------------------------------------
    def _load_domain(self) -> None:
        node = _module_consts(self.module).get(_DOMAIN_NAME)
        if not isinstance(node, ast.Dict):
            return
        for key, value in zip(node.keys, node.values):
            kval = const_value(self.module, key, self.project)
            vval = const_value(self.module, value, self.project)
            if vval is None:
                continue
            if isinstance(kval, str):
                self.simple[kval] = (list(vval) if isinstance(vval, tuple)
                                     else [vval])
            elif (isinstance(kval, tuple)
                  and all(isinstance(s, str) for s in kval)
                  and isinstance(vval, tuple)):
                points = [p for p in vval
                          if isinstance(p, tuple) and len(p) == len(kval)]
                if points:
                    self.joint.append((kval, points))

    def joint_group_of(self, name: str) -> Optional[int]:
        for i, (syms, _) in enumerate(self.joint):
            if name in syms:
                return i
        return None

    # -- candidates --------------------------------------------------------
    def candidates(self, expr: ast.expr, _depth: int = 0
                   ) -> Optional[List[object]]:
        """All statically-possible values of ``expr`` at this site, or
        None when the model cannot bound it."""
        if _depth > 12 or expr is None:
            return None
        v = const_value(self.module, expr, self.project)
        if v is not None:
            return [v]
        if isinstance(expr, ast.Name):
            return self._name_candidates(expr.id, _depth)
        if isinstance(expr, (ast.Tuple, ast.List)):
            per = [self.candidates(el, _depth + 1) for el in expr.elts]
            if any(p is None for p in per):
                return None
            out = [tuple(pt) for pt in itertools.product(*per)]
            return out[:_MAX_CANDIDATES]
        if isinstance(expr, ast.UnaryOp) and isinstance(
                expr.op, (ast.USub, ast.UAdd)):
            vals = self.candidates(expr.operand, _depth + 1)
            if vals is None:
                return None
            sign = -1 if isinstance(expr.op, ast.USub) else 1
            return [sign * x for x in vals if isinstance(x, int)] or None
        if isinstance(expr, ast.BinOp):
            fn = _NUM_BINOPS.get(type(expr.op))
            if fn is None:
                return None
            lv = self.candidates(expr.left, _depth + 1)
            rv = self.candidates(expr.right, _depth + 1)
            if lv is None or rv is None:
                return None
            out: List[object] = []
            for a, b in itertools.islice(
                    itertools.product(lv, rv), _MAX_POINTS):
                if isinstance(a, int) and isinstance(b, int):
                    try:
                        r = fn(a, b)
                    except Exception:
                        r = None
                    if r is not None:
                        out.append(r)
            return sorted(set(out))[:_MAX_CANDIDATES] or None
        if isinstance(expr, ast.Subscript):
            base = self.candidates(expr.value, _depth + 1)
            idx = self.candidates(expr.slice, _depth + 1)
            if base is None or idx is None:
                return None
            out = []
            for b, i in itertools.product(base, idx):
                if isinstance(b, tuple) and isinstance(i, int) \
                        and -len(b) <= i < len(b):
                    out.append(b[i])
            return sorted(set(out))[:_MAX_CANDIDATES] or None
        if isinstance(expr, ast.Call):
            return self._call_candidates(expr, _depth)
        return None

    def _call_candidates(self, call: ast.Call, _depth: int
                         ) -> Optional[List[object]]:
        if not isinstance(call.func, ast.Name) or call.keywords:
            return None
        args = [self.candidates(a, _depth + 1) for a in call.args]
        if any(a is None for a in args):
            return None
        fname = call.func.id
        if fname in ("max", "min", "len", "sum") and args:
            out = []
            fn = {"max": max, "min": min, "len": len, "sum": sum}[fname]
            for pt in itertools.islice(itertools.product(*args),
                                       _MAX_POINTS):
                try:
                    vals = (pt[0] if len(pt) == 1
                            and isinstance(pt[0], tuple) else pt)
                    out.append(fn(vals))
                except Exception:
                    pass
            return sorted(set(out))[:_MAX_CANDIDATES] or None
        # Pure int helper: a module-level def whose body is one Return
        # of an arithmetic expression over its params and constants
        # (the `_bin_width` shape).
        fdef = _module_functions(self.module).get(fname)
        if fdef is None:
            return None
        body = [s for s in fdef.body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))]
        if len(body) != 1 or not isinstance(body[0], ast.Return) \
                or body[0].value is None:
            return None
        params = [a.arg for a in fdef.args.args]
        if len(call.args) > len(params):
            return None
        env: Dict[str, List[object]] = dict(zip(params, args))
        # defaults for unbound params
        defaults = fdef.args.defaults
        for p, d in zip(params[len(params) - len(defaults):], defaults):
            if p not in env:
                dv = self.candidates(d, _depth + 1)
                if dv is None:
                    return None
                env[p] = dv
        if set(params) - set(env):
            return None
        return self._eval_env(body[0].value, env, _depth + 1)

    def _eval_env(self, expr: ast.expr, env: Dict[str, List[object]],
                  _depth: int) -> Optional[List[object]]:
        """Evaluate a helper's return expression under candidate bindings
        for its parameters (module constants still resolve normally)."""
        free = sorted({n.id for n in ast.walk(expr)
                       if isinstance(n, ast.Name) and n.id in env})
        per = [env[n] for n in free]
        out: List[object] = []
        saved = {}
        for pt in itertools.islice(itertools.product(*per), _MAX_POINTS):
            # temporarily pin the bindings in the candidate cache
            for n, v in zip(free, pt):
                saved[n] = self._cache.get(n, "__miss__")
                self._cache[n] = [v]
            vals = self.candidates(expr, _depth + 1)
            for n in free:
                if saved[n] == "__miss__":
                    self._cache.pop(n, None)
                else:
                    self._cache[n] = saved[n]
            if vals is None:
                return None
            out.extend(vals)
        uniq = []
        for v in out:
            if v not in uniq:
                uniq.append(v)
        return uniq[:_MAX_CANDIDATES] or None

    def _name_candidates(self, name: str, _depth: int
                         ) -> Optional[List[object]]:
        if name in self._cache:
            return self._cache[name]
        if name in self._stack:
            return None
        self._stack.add(name)
        try:
            out = self._resolve_name(name, _depth)
        finally:
            self._stack.discard(name)
        self._cache[name] = out
        return out

    def _resolve_name(self, name: str, _depth: int
                      ) -> Optional[List[object]]:
        # 1. local bindings in the enclosing scope chain (closures).
        scope = self.scope
        while scope is not None:
            bound = False
            vals: List[object] = []
            for node in walk_own(scope.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in node.targets):
                    bound = True
                    got = self.candidates(node.value, _depth + 1)
                    if got:
                        vals.extend(got)
                elif isinstance(node, ast.For):
                    got = self._loop_candidates(node, name, _depth)
                    if got is not None:
                        bound = True
                        vals.extend(got)
            if bound and vals:
                uniq = []
                for v in vals:
                    if v not in uniq:
                        uniq.append(v)
                return uniq[:_MAX_CANDIDATES]
            if name in scope.params:
                got = self._param_candidates(scope, name, _depth)
                if got is not None:
                    return got
                break           # a parameter shadows outer bindings
            if bound:
                break           # locally assigned but unresolvable
            scope = scope.parent
        # 2. declared model domain.
        if name in self.simple:
            return list(self.simple[name])
        g = self.joint_group_of(name)
        if g is not None:
            syms, points = self.joint[g]
            i = syms.index(name)
            return sorted({p[i] for p in points})
        return None

    def _param_candidates(self, scope: FunctionScope, name: str,
                          _depth: int) -> Optional[List[object]]:
        if name in self.simple:
            return list(self.simple[name])
        if self.joint_group_of(name) is not None:
            syms, points = self.joint[self.joint_group_of(name)]
            i = syms.index(name)
            return sorted({p[i] for p in points})
        # fall back to the declared default value.
        args = scope.node.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        for p, d in zip(pos[len(pos) - len(defaults):], defaults):
            if p.arg == name:
                return self.candidates(d, _depth + 1)
        for p, d in zip(args.kwonlyargs, args.kw_defaults):
            if p.arg == name and d is not None:
                return self.candidates(d, _depth + 1)
        return None

    def _loop_candidates(self, node: ast.For, name: str, _depth: int
                         ) -> Optional[List[object]]:
        """Values a for-target takes over a resolvable iterable
        (including the second slot of ``enumerate(...)``)."""
        target, it = node.target, node.iter
        pick_second = False
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate" and it.args:
            if isinstance(target, ast.Tuple) and len(target.elts) == 2 \
                    and isinstance(target.elts[1], ast.Name) \
                    and target.elts[1].id == name:
                it = it.args[0]
                pick_second = True
            else:
                return None
        elif not (isinstance(target, ast.Name) and target.id == name):
            return None
        if not pick_second and not (isinstance(target, ast.Name)
                                    and target.id == name):
            return None
        seqs = self.candidates(it, _depth + 1)
        if seqs is None:
            return None
        out: List[object] = []
        for s in seqs:
            if isinstance(s, tuple):
                out.extend(s)
            else:
                out.append(s)
        uniq = []
        for v in out:
            if v not in uniq:
                uniq.append(v)
        return uniq[:_MAX_CANDIDATES] or None


# ---------------------------------------------------------------------------
# pallas_call site extraction
# ---------------------------------------------------------------------------

class _Buffer:
    __slots__ = ("kind", "node", "dims", "dtype", "pipelined")

    def __init__(self, kind, node, dims, dtype, pipelined):
        self.kind = kind            # 'in block' | 'out block' | 'scratch'
        self.node = node            # anchor for findings
        self.dims = dims            # list of ast exprs
        self.dtype = dtype          # 'float32' | ... | None (assume 4B)
        self.pipelined = pipelined  # double-buffered across grid steps


class _Site:
    __slots__ = ("call", "scope", "buffers", "ring_slots")

    def __init__(self, call, scope):
        self.call = call
        self.scope = scope
        self.buffers: List[_Buffer] = []
        self.ring_slots: Optional[ast.expr] = None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _local_assign_value(module: ModuleInfo, scope: Optional[FunctionScope],
                        name: str) -> Optional[ast.expr]:
    s = scope
    while s is not None:
        for node in walk_own(s.node):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in node.targets):
                return node.value
        s = s.parent
    return None


def _as_seq(node: Optional[ast.expr]) -> List[ast.expr]:
    if node is None:
        return []
    if isinstance(node, (ast.List, ast.Tuple)):
        return list(node.elts)
    return [node]


def _extract_sites(module: ModuleInfo) -> List[_Site]:
    sites: List[_Site] = []
    covered: Set[int] = set()
    for scope in module.scopes:
        for node in walk_own(scope.node):
            if isinstance(node, ast.Call) \
                    and module.call_name(node) == _PALLAS_CALL:
                covered.add(id(node))
                sites.append(_build_site(module, scope, node))
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and id(node) not in covered \
                and module.call_name(node) == _PALLAS_CALL:
            sites.append(_build_site(module, None, node))
    return sites


def _build_site(module: ModuleInfo, scope: Optional[FunctionScope],
                call: ast.Call) -> _Site:
    site = _Site(call, scope)
    spec_call: Optional[ast.Call] = None
    gs = _kw(call, "grid_spec")
    if isinstance(gs, ast.Name):
        val = _local_assign_value(module, scope, gs.id)
        if isinstance(val, ast.Call):
            gs = val
    if isinstance(gs, ast.Call) and module.call_name(gs) in _GRID_SPECS:
        spec_call = gs

    def spec_kw(name):
        v = _kw(call, name)
        if v is None and spec_call is not None:
            v = _kw(spec_call, name)
        return v

    has_grid = spec_kw("grid") is not None

    def block_buffer(spec, kind, dtype=None):
        if not (isinstance(spec, ast.Call)
                and module.call_name(spec) in _BLOCK_SPEC):
            return
        ms = _kw(spec, "memory_space")
        ms_name = module.imports.resolve(ms) if ms is not None else None
        if ms_name is not None and (ms_name.endswith(".ANY")
                                    or ms_name.endswith(".SMEM")):
            return
        shape = spec.args[0] if spec.args else None
        if isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
            site.buffers.append(_Buffer(kind, spec, list(shape.elts),
                                        dtype, has_grid))

    structs = []
    for st in _as_seq(spec_kw("out_shape") or _kw(call, "out_shape")):
        if isinstance(st, ast.Call) \
                and module.call_name(st) in _SHAPE_STRUCT:
            structs.append(st)
    out_dtype = None
    if len(structs) == 1:
        dt = (structs[0].args[1] if len(structs[0].args) > 1
              else _kw(structs[0], "dtype"))
        out_dtype = _dtype_name(module, dt)

    out_specs = _as_seq(spec_kw("out_specs"))
    for spec in out_specs:
        block_buffer(spec, "out block", out_dtype)
    if not any(b.kind == "out block" for b in site.buffers):
        # No blocked out_specs: the whole output lives in VMEM.
        for st in structs:
            shape = st.args[0] if st.args else _kw(st, "shape")
            dt = st.args[1] if len(st.args) > 1 else _kw(st, "dtype")
            if isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
                site.buffers.append(_Buffer(
                    "out block", st, list(shape.elts),
                    _dtype_name(module, dt), False))

    for spec in _as_seq(spec_kw("in_specs")):
        block_buffer(spec, "in block")

    for sc in _as_seq(spec_kw("scratch_shapes")):
        if not isinstance(sc, ast.Call):
            continue
        name = module.call_name(sc)
        if name == _VMEM_SCRATCH:
            shape = sc.args[0] if sc.args else None
            dt = sc.args[1] if len(sc.args) > 1 else None
            if isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
                site.buffers.append(_Buffer(
                    "scratch", sc, list(shape.elts),
                    _dtype_name(module, dt), False))
        elif name == _DMA_SEM:
            shape = sc.args[0] if sc.args else None
            if isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
                site.ring_slots = shape.elts[0]
    return site


# ---------------------------------------------------------------------------
# buffer evaluation
# ---------------------------------------------------------------------------

def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KB"
    return f"{n}B"


class _EvalError(Exception):
    def __init__(self, dim_expr):
        self.dim_expr = dim_expr


def _buffer_points(buf: _Buffer, rs: _SiteResolver
                   ) -> List[Tuple[int, ...]]:
    """All candidate dim tuples for a buffer (joint-group aware).
    Raises _EvalError on an unmodelable dimension."""
    joint_syms: Dict[int, int] = {}   # dim index -> joint group
    per_dim: List[Optional[List[int]]] = []
    for i, de in enumerate(buf.dims):
        if isinstance(de, ast.Name):
            g = rs.joint_group_of(de.id)
            if g is not None and not _is_pure_const(rs, de):
                joint_syms[i] = g
                per_dim.append(None)
                continue
        vals = rs.candidates(de)
        ints = ([v for v in vals if isinstance(v, int)]
                if vals is not None else None)
        if not ints:
            raise _EvalError(de)
        per_dim.append(ints)

    groups = sorted({g for g in joint_syms.values()})
    axes: List[List] = []
    for i, de in enumerate(buf.dims):
        if i in joint_syms:
            axes.append([("joint", joint_syms[i], de.id)])
        else:
            axes.append(per_dim[i])
    out: List[Tuple[int, ...]] = []
    group_points = [rs.joint[g][1] for g in groups]
    group_syms = [rs.joint[g][0] for g in groups]
    for jp in itertools.islice(
            itertools.product(*group_points) if groups else [()],
            _MAX_POINTS):
        env: Dict[str, int] = {}
        for syms, point in zip(group_syms, jp):
            env.update({s: v for s, v in zip(syms, point)
                        if isinstance(v, int)})
        dim_axes = []
        ok = True
        for i, ax in enumerate(axes):
            if i in joint_syms:
                sym = buf.dims[i].id
                if sym not in env:
                    ok = False
                    break
                dim_axes.append([env[sym]])
            else:
                dim_axes.append(ax)
        if not ok:
            raise _EvalError(buf.dims[i])
        for pt in itertools.islice(itertools.product(*dim_axes),
                                   _MAX_POINTS):
            out.append(tuple(pt))
    uniq = []
    for p in out:
        if p not in uniq:
            uniq.append(p)
    return uniq[:_MAX_POINTS]


def _is_pure_const(rs: _SiteResolver, expr: ast.expr) -> bool:
    return const_value(rs.module, expr, rs.project) is not None


def _site_model(module: ModuleInfo, project):
    """Memoized per-module site extraction + resolver construction."""
    key = id(project)
    cached = getattr(module, "_km_model", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    model = []
    if "pallas_call" in module.source:
        for site in _extract_sites(module):
            rs = _SiteResolver(module, site.scope, project)
            model.append((site, rs))
    module._km_model = (key, model)
    return model


# ---------------------------------------------------------------------------
# GLT017 vmem-budget-exceeded
# ---------------------------------------------------------------------------

@register
class VmemBudgetExceeded(Rule):
    """Closed-form VMEM accounting over every candidate parameter point."""
    name = "vmem-budget-exceeded"
    code = "GLT017"
    severity = Severity.ERROR
    description = ("a pallas_call's tiles + ring slots + scratch exceed "
                   "the VMEM budget at some candidate parameter point "
                   "(or a buffer dim is not statically boundable)")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        model = _site_model(module, project)
        if not model:
            return findings
        budget = _limits(module, project)["VMEM_BYTES"]
        for site, rs in model:
            total = 0
            parts = []
            swept: Dict[str, int] = {}
            bad = False
            for buf in site.buffers:
                try:
                    points = _buffer_points(buf, rs)
                except _EvalError as e:
                    findings.append(self.finding(
                        module, buf.node,
                        f"VMEM model cannot bound {buf.kind} dim "
                        f"'{ast.unparse(e.dim_expr)}' of this pallas_call"
                        f" — route it through a resolvable constant or "
                        f"declare it in {_DOMAIN_NAME} so the closed-"
                        f"form accounting stays total"))
                    bad = True
                    continue
                itemsize = _ITEMSIZE.get(buf.dtype or "float32", 4)
                mult = 2 if buf.pipelined else 1
                worst, worst_pt = 0, None
                for pt in points:
                    b = mult * itemsize
                    for v in pt:
                        b *= max(v, 0)
                    if b > worst:
                        worst, worst_pt = b, pt
                total += worst
                if worst_pt is not None:
                    shape = "x".join(str(v) for v in worst_pt)
                    pre = "2x " if mult == 2 else ""
                    parts.append(f"{buf.kind} {pre}[{shape}] "
                                 f"{buf.dtype or 'f32(assumed)'} = "
                                 f"{_fmt_bytes(worst)}")
                    for de, v in zip(buf.dims, worst_pt):
                        if isinstance(de, ast.Name) \
                                and not _is_pure_const(rs, de):
                            swept.setdefault(de.id, v)
            if bad or total <= budget:
                continue
            at = ", ".join(f"{k}={v}" for k, v in sorted(swept.items()))
            findings.append(self.finding(
                module, site.call,
                f"VMEM model: {' + '.join(parts)} = {_fmt_bytes(total)} "
                f"exceeds the {_fmt_bytes(budget)} budget"
                + (f" at candidate point {at}" if at else "")
                + " — shrink the tile/ring point or drop it from the "
                  "sweep table"))
        return findings


# ---------------------------------------------------------------------------
# GLT018 unbalanced-dma-ring
# ---------------------------------------------------------------------------

def _flatten_conjuncts(expr: ast.expr) -> List[ast.expr]:
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.BitAnd):
        return (_flatten_conjuncts(expr.left)
                + _flatten_conjuncts(expr.right))
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.And):
        out = []
        for v in expr.values:
            out.extend(_flatten_conjuncts(v))
        return out
    return [expr]


def _canon(expr: ast.expr, loop_vars: Set[str]) -> str:
    """Canonical guard string: loop-index arithmetic collapses to '@' so
    the fill prologue and steady state compare equal."""
    if isinstance(expr, ast.Name):
        return "@" if expr.id in loop_vars else expr.id
    if isinstance(expr, ast.Constant):
        return repr(expr.value)
    if isinstance(expr, ast.BinOp):
        left = _canon(expr.left, loop_vars)
        right = _canon(expr.right, loop_vars)
        if "@" in (left, right) and type(expr.op) in _NUM_BINOPS:
            return "@"
        return f"({left} {type(expr.op).__name__} {right})"
    if isinstance(expr, ast.UnaryOp):
        inner = _canon(expr.operand, loop_vars)
        return inner if inner == "@" else \
            f"({type(expr.op).__name__} {inner})"
    if isinstance(expr, ast.Compare):
        parts = [_canon(expr.left, loop_vars)]
        for op, cmp in zip(expr.ops, expr.comparators):
            parts.append(type(op).__name__)
            parts.append(_canon(cmp, loop_vars))
        return " ".join(parts)
    if isinstance(expr, ast.Subscript):
        return (f"{_canon(expr.value, loop_vars)}"
                f"[{_canon(expr.slice, loop_vars)}]")
    if isinstance(expr, ast.Attribute):
        return f"{_canon(expr.value, loop_vars)}.{expr.attr}"
    if isinstance(expr, ast.Call):
        args = ", ".join(_canon(a, loop_vars) for a in expr.args)
        return f"{_canon(expr.func, loop_vars)}({args})"
    try:
        return ast.unparse(expr)
    except Exception:  # pragma: no cover - exotic nodes
        return type(expr).__name__


class _RingEvent:
    __slots__ = ("kind", "node", "helper", "data_guards", "guard_src")

    def __init__(self, kind, node, helper, data_guards, guard_src):
        self.kind = kind
        self.node = node
        self.helper = helper
        self.data_guards = data_guards   # set of canonical strings
        self.guard_src = guard_src       # {canon: source text}


def _loop_vars(unit: ast.AST, module: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    body_defs: Set[str] = set()
    for node in ast.walk(unit):
        if isinstance(node, ast.For):
            t = node.target
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                out |= {e.id for e in t.elts if isinstance(e, ast.Name)}
        elif isinstance(node, ast.Call):
            name = module.call_name(node)
            if name in _LOOPS:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        body_defs.add(arg.id)
    for node in ast.walk(unit):
        if isinstance(node, ast.FunctionDef) and node.name in body_defs \
                and node.args.args:
            out.add(node.args.args[0].arg)
    return out


def _dma_helpers(unit: ast.AST, module: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(unit):
        if isinstance(node, ast.FunctionDef):
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Return) \
                        and isinstance(stmt.value, ast.Call) \
                        and module.call_name(stmt.value) == _ASYNC_COPY:
                    out.add(node.name)
    return out


def _event_guards(node: ast.AST, unit: ast.AST, module: ModuleInfo,
                  loop_vars: Set[str]):
    """Data-dependent guard conjuncts between an event and its unit."""
    data: Set[str] = set()
    src: Dict[str, str] = {}
    cur = module.parents.get(node)
    while cur is not None and cur is not unit:
        preds: List[ast.expr] = []
        if isinstance(cur, ast.If):
            preds.append(cur.test)
        elif isinstance(cur, ast.FunctionDef):
            for dec in cur.decorator_list:
                if isinstance(dec, ast.Call) \
                        and module.call_name(dec) == _WHEN and dec.args:
                    preds.append(dec.args[0])
        for pred in preds:
            for conj in _flatten_conjuncts(pred):
                if any(isinstance(n, ast.Subscript)
                       for n in ast.walk(conj)):
                    c = _canon(conj, loop_vars)
                    data.add(c)
                    try:
                        src.setdefault(c, ast.unparse(conj))
                    except Exception:  # pragma: no cover
                        src.setdefault(c, c)
        cur = module.parents.get(cur)
    return data, src


def _ring_units(module: ModuleInfo):
    """(unit scope, events) for every top-level function owning a ring."""
    for scope in module.scopes:
        if scope.parent is not None:
            continue
        unit = scope.node
        helpers = _dma_helpers(unit, module)
        loop_vars = _loop_vars(unit, module)
        events: List[_RingEvent] = []
        for node in ast.walk(unit):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("start", "wait")):
                continue
            base = node.func.value
            helper = None
            if isinstance(base, ast.Call):
                if module.call_name(base) == _ASYNC_COPY:
                    helper = "<inline>"
                elif isinstance(base.func, ast.Name) \
                        and base.func.id in helpers:
                    helper = base.func.id
            if helper is None:
                continue
            data, src = _event_guards(node, unit, module, loop_vars)
            events.append(_RingEvent(node.func.attr, node, helper,
                                     data, src))
        if events:
            yield scope, events


@register
class UnbalancedDmaRing(Rule):
    """Async-copy start/wait pairs must agree on row-skip predicates."""
    name = "unbalanced-dma-ring"
    code = "GLT018"
    severity = Severity.ERROR
    description = ("a make_async_copy start without a matching wait "
                   "(or a data-dependent predicate guarding one side "
                   "only): skipped rows leave dangling DMAs or waits "
                   "on never-signaled semaphores")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        if "make_async_copy" not in module.source:
            return findings
        for scope, events in _ring_units(module):
            by_helper: Dict[str, List[_RingEvent]] = {}
            for ev in events:
                by_helper.setdefault(ev.helper, []).append(ev)
            for helper, evs in sorted(by_helper.items()):
                starts = [e for e in evs if e.kind == "start"]
                waits = [e for e in evs if e.kind == "wait"]
                ring = (f"DMA ring '{helper}'" if helper != "<inline>"
                        else "inline make_async_copy")
                if starts and not waits:
                    findings.append(self.finding(
                        module, starts[0].node,
                        f"{ring} in '{scope.name}' is started but never "
                        f"awaited — the in-flight DMA dangles and "
                        f"corrupts its slot on reuse"))
                    continue
                if waits and not starts:
                    findings.append(self.finding(
                        module, waits[0].node,
                        f"{ring} in '{scope.name}' is awaited but never "
                        f"started — the wait blocks forever on a "
                        f"never-signaled semaphore"))
                    continue
                data_s = set().union(*(e.data_guards for e in starts)) \
                    if starts else set()
                data_w = set().union(*(e.data_guards for e in waits)) \
                    if waits else set()
                srcs: Dict[str, str] = {}
                for e in evs:
                    srcs.update(e.guard_src)
                for c in sorted(data_s - data_w):
                    anchor = next(e.node for e in starts
                                  if c in e.data_guards)
                    findings.append(self.finding(
                        module, anchor,
                        f"{ring} in '{scope.name}': data-dependent "
                        f"predicate '{srcs.get(c, c)}' guards start but "
                        f"no wait shares it — a row skipped at start "
                        f"leaves its unconditional wait blocking on a "
                        f"never-signaled semaphore; guard start and "
                        f"wait with the same row predicate"))
                for c in sorted(data_w - data_s):
                    anchor = next(e.node for e in waits
                                  if c in e.data_guards)
                    findings.append(self.finding(
                        module, anchor,
                        f"{ring} in '{scope.name}': data-dependent "
                        f"predicate '{srcs.get(c, c)}' guards wait but "
                        f"no start shares it — rows skipped at wait "
                        f"leave their started DMA dangling on the ring "
                        f"slot; guard start and wait with the same row "
                        f"predicate"))
        return findings


# ---------------------------------------------------------------------------
# GLT019 unaligned-tile-shape
# ---------------------------------------------------------------------------

@register
class UnalignedTileShape(Rule):
    """VMEM blocks must tile the (sublane, 128-lane) register."""
    name = "unaligned-tile-shape"
    code = "GLT019"
    severity = Severity.ERROR
    description = ("a VMEM block/scratch shape whose last dim is not a "
                   "multiple of the 128-lane register, or whose sublane "
                   "dim violates the dtype's floor (f32 8 / bf16 16 / "
                   "int8 32)")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        findings: List[Finding] = []
        model = _site_model(module, project)
        if not model:
            return findings
        lims = _limits(module, project)
        lane = lims["LANE"]
        for site, rs in model:
            for buf in site.buffers:
                try:
                    points = _buffer_points(buf, rs)
                except _EvalError:
                    continue          # GLT017 already reports it
                floor = _sublane_floor(buf.dtype, lims["SUBLANE_F32"])
                bad_lane = sorted({pt[-1] for pt in points
                                   if pt[-1] % lane != 0})
                bad_sub = sorted({pt[-2] for pt in points
                                  if len(pt) >= 2 and pt[-2] % floor})
                dt = buf.dtype or "f32(assumed)"
                if bad_lane:
                    findings.append(self.finding(
                        module, buf.node,
                        f"{buf.kind} last dim {bad_lane} is not a "
                        f"multiple of the {lane}-lane register — Mosaic "
                        f"pads every row to {lane} lanes (wasted VMEM "
                        f"and misaligned DMAs); pad the trailing dim or "
                        f"restructure the block"))
                if bad_sub:
                    findings.append(self.finding(
                        module, buf.node,
                        f"{buf.kind} sublane dim {bad_sub} violates the "
                        f"{floor}-sublane floor for {dt} — the compiler "
                        f"pads each tile up to ({floor}, {lane}); round "
                        f"the dim up to a multiple of {floor}"))
        return findings
