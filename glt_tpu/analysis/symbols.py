"""Project symbol table: every module, function, class, and lock in one
namespace, with cross-module call resolution.

This is the layer that turns gltlint from a per-file linter into a
project analysis: :class:`Project` parses the whole file set, assigns
each function a stable id (``module.Class.method``), resolves import
aliases across modules (``from ..channel.base import bounded_get as bg``
and re-exports through ``__init__`` both land on the one definition),
and answers *"which function does this call site invoke?"* — the
question the call graph, the effect engine, and the transitive rules are
built on.

Resolution strategy for ``x.m(...)`` attribute calls, most precise
first:

1. a fully-dotted alias chain (``mod.fn``, ``pkg.mod.Class.m``);
2. ``self.m`` / ``cls.m`` -> the enclosing class (and its bases);
3. a receiver whose class is known — a local assigned from a project
   class constructor, or a ``self.attr`` recorded as
   ``self.attr = SomeClass(...)`` in the class body;
4. unique-method-name fallback: if exactly one class in the project
   defines ``m`` (and ``m`` is not on the generic-name blocklist), bind
   to it.

Unresolvable calls contribute no effects — the analyses stay
calibrated-quiet rather than guess.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Union

from .visitor import (
    JIT_NAMES,
    SHARD_MAP_NAMES,
    FunctionScope,
    ModuleInfo,
    _static_arg_names,
    _unwrap_traced_target,
    dotted_expr,
)

# Constructors whose result is a mutual-exclusion object; assignments from
# these define the project's lock universe (GLT008/GLT009).
LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
}

# Method names too generic for the unique-name fallback: binding `.get()`
# or `.close()` to whichever single class happens to define one would
# invent effects out of coincidence.
AMBIGUOUS_METHOD_NAMES = frozenset({
    "get", "put", "join", "wait", "send", "recv", "close", "stop",
    "start", "run", "read", "write", "flush", "acquire", "release",
    "items", "keys", "values", "append", "pop", "add", "clear", "update",
    "copy", "encode", "decode", "set", "is_set", "is_alive", "poll",
    "sample", "next", "sendall", "accept", "connect", "get_nowait",
    "put_nowait", "empty", "shutdown", "reset", "tolist", "item",
    # jax.random.split / str.split / np.split: binding a project class's
    # .split to these call sites invented host-sync effects (PR 9).
    "split", "submit",
    # pl.load / pl.store inside Pallas kernels: binding a project
    # class's .load (DistDataset.load) to the kernel's masked-memory-op
    # call sites invented a host-sync chain out of coincidence (PR 10).
    "load", "store",
})

_RESOLVE_DEPTH = 8   # alias-chain / inheritance walk bound


@dataclass(eq=False)
class FunctionSymbol:
    """One addressable function definition."""
    fid: str                       # "glt_tpu.channel.base.bounded_get"
    module: ModuleInfo
    scope: FunctionScope
    class_id: Optional[str] = None  # owning class cid for methods

    @property
    def short(self) -> str:
        return self.fid.rsplit(".", 2)[-1] if self.class_id is None \
            else ".".join(self.fid.rsplit(".", 2)[-2:])


@dataclass(eq=False)
class ClassSymbol:
    """One class definition, with the facts the analyses need: bases,
    methods, constructor-assigned attribute types, and lock attributes."""
    cid: str
    name: str
    module: ModuleInfo
    node: ast.ClassDef
    base_refs: List[str] = field(default_factory=list)
    methods: Dict[str, FunctionSymbol] = field(default_factory=dict)
    lock_attrs: Set[str] = field(default_factory=set)
    attr_type_refs: Dict[str, str] = field(default_factory=dict)


Symbol = Union[FunctionSymbol, ClassSymbol]


class Project:
    """The whole analyzed file set as one namespace."""

    def __init__(self, modules: Iterable[ModuleInfo]) -> None:
        mods = list(modules)
        self.modules: Dict[str, ModuleInfo] = {m.name: m for m in mods}
        self.by_path: Dict[str, ModuleInfo] = {m.path: m for m in mods}
        self.functions: Dict[str, FunctionSymbol] = {}   # module-level fns
        self.classes: Dict[str, ClassSymbol] = {}
        self.all_functions: Dict[str, FunctionSymbol] = {}  # incl. nested
        self._fid_by_scope: Dict[FunctionScope, str] = {}
        self._scope_children: Dict[FunctionScope,
                                   Dict[str, FunctionScope]] = {}
        self._module_locks: Dict[str, Set[str]] = {}
        self._method_index: Dict[str, List[FunctionSymbol]] = {}
        for name in sorted(self.modules):
            self._index_module(self.modules[name])
        self._mark_cross_module_jit()
        self._effects = None

    # -- construction ------------------------------------------------------
    def _index_module(self, m: ModuleInfo) -> None:
        for scope in m.scopes:                 # DFS order: parents first
            if isinstance(scope.node, ast.Lambda):
                continue
            if scope.parent is None:
                qual = (f"{scope.class_name}.{scope.name}"
                        if scope.class_name else scope.name)
            else:
                parent_fid = self._fid_by_scope.get(scope.parent)
                if parent_fid is None:
                    continue                   # nested under a lambda
                qual = (parent_fid[len(m.name) + 1:]
                        + f".<locals>.{scope.name}")
                self._scope_children.setdefault(
                    scope.parent, {})[scope.name] = scope
            fid = f"{m.name}.{qual}"
            self._fid_by_scope[scope] = fid
            sym = FunctionSymbol(
                fid, m, scope,
                class_id=(f"{m.name}.{scope.class_name}"
                          if scope.class_name and scope.parent is None
                          else None))
            self.all_functions[fid] = sym
            if scope.parent is None and scope.class_name is None:
                self.functions[fid] = sym
        # classes (top level only; nested classes are out of scope)
        for node in ast.iter_child_nodes(m.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cid = f"{m.name}.{node.name}"
            cls = ClassSymbol(cid, node.name, m, node)
            for b in node.bases:
                ref = m.imports.resolve(b)
                if ref:
                    cls.base_refs.append(ref)
            for scope in m.scopes:
                if (scope.parent is None and scope.class_name == node.name
                        and not isinstance(scope.node, ast.Lambda)):
                    sym = self.all_functions.get(
                        f"{cid}.{scope.name}")
                    if sym is not None:
                        cls.methods[scope.name] = sym
                        self._method_index.setdefault(
                            scope.name, []).append(sym)
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)):
                    continue
                ref = m.imports.resolve(sub.value.func)
                if ref is None:
                    continue
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        if ref in LOCK_FACTORIES:
                            cls.lock_attrs.add(t.attr)
                        else:
                            cls.attr_type_refs.setdefault(t.attr, ref)
            self.classes[cid] = cls
        # module-level locks
        for stmt in ast.iter_child_nodes(m.tree):
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and m.imports.resolve(stmt.value.func)
                    in LOCK_FACTORIES):
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self._module_locks.setdefault(
                        m.name, set()).add(t.id)

    def _mark_cross_module_jit(self) -> None:
        """``jax.jit(fn)`` where ``fn`` is imported from another project
        module: the target's home module cannot see the wrap, so mark its
        scope a jit root here and re-run that module's intra-module
        transitive marking."""
        remark: Set[ModuleInfo] = set()
        for name in sorted(self.modules):
            m = self.modules[name]
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                wrapper = m.call_name(node)
                if (wrapper not in JIT_NAMES
                        and wrapper not in SHARD_MAP_NAMES):
                    continue
                target = _unwrap_traced_target(node, m.imports)
                if target is None or not isinstance(
                        target, (ast.Name, ast.Attribute)):
                    continue
                dotted = m.imports.resolve(target)
                if not dotted:
                    continue
                sym = self.resolve_dotted(dotted)
                if (isinstance(sym, FunctionSymbol)
                        and sym.module is not m
                        and not sym.scope.jit_root):
                    sym.scope.jit_root = True
                    sym.scope.jit_reason = (
                        f"wrapped by {wrapper} at "
                        f"{m.path}:{node.lineno}")
                    if wrapper in JIT_NAMES:
                        sym.scope.static_args |= _static_arg_names(
                            node, sym.scope.node)
                    remark.add(sym.module)
        for m in remark:
            m._mark_called_from_jit()

    # -- lazily-built analyses ---------------------------------------------
    @property
    def effects(self):
        """The per-function effect summaries (built on first use)."""
        if self._effects is None:
            from .effects import EffectEngine
            self._effects = EffectEngine(self)
        return self._effects

    # -- queries -----------------------------------------------------------
    def fid_of(self, scope: FunctionScope) -> Optional[str]:
        return self._fid_by_scope.get(scope)

    def resolve_dotted(self, dotted: str,
                       depth: int = 0) -> Optional[Symbol]:
        """A project symbol for a canonical dotted path, following
        re-export alias chains (bounded)."""
        if not dotted or depth > _RESOLVE_DEPTH:
            return None
        if dotted in self.functions:
            return self.functions[dotted]
        if dotted in self.classes:
            return self.classes[dotted]
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod_name = ".".join(parts[:cut])
            m = self.modules.get(mod_name)
            if m is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                target = m.imports.alias_of(rest[0])
                if target and target != dotted:
                    return self.resolve_dotted(target, depth + 1)
                return None
            if len(rest) == 2:
                cls = self.classes.get(f"{mod_name}.{rest[0]}")
                if cls is not None:
                    return self.class_method(cls, rest[1])
                target = m.imports.alias_of(rest[0])
                if target and f"{target}.{rest[1]}" != dotted:
                    return self.resolve_dotted(f"{target}.{rest[1]}",
                                               depth + 1)
            return None
        return None

    def class_method(self, cls: ClassSymbol, name: str,
                     depth: int = 0) -> Optional[FunctionSymbol]:
        """Method lookup with (bounded) base-class traversal."""
        if name in cls.methods:
            return cls.methods[name]
        if depth >= _RESOLVE_DEPTH:
            return None
        for ref in cls.base_refs:
            base = self._class_from_ref(cls.module, ref)
            if base is not None:
                got = self.class_method(base, name, depth + 1)
                if got is not None:
                    return got
        return None

    def _class_from_ref(self, module: ModuleInfo,
                        ref: str) -> Optional[ClassSymbol]:
        sym = self.resolve_dotted(ref)
        if sym is None and "." not in ref:
            sym = self.resolve_dotted(f"{module.name}.{ref}")
        return sym if isinstance(sym, ClassSymbol) else None

    def class_attr_type(self, cls: ClassSymbol, attr: str,
                        depth: int = 0) -> Optional[ClassSymbol]:
        """The class of ``self.<attr>`` when a constructor assignment
        recorded it (``self.conn = RemoteServerConnection(...)``)."""
        ref = cls.attr_type_refs.get(attr)
        if ref is not None:
            return self._class_from_ref(cls.module, ref)
        if depth >= _RESOLVE_DEPTH:
            return None
        for bref in cls.base_refs:
            base = self._class_from_ref(cls.module, bref)
            if base is not None:
                got = self.class_attr_type(base, attr, depth + 1)
                if got is not None:
                    return got
        return None

    def own_class(self, module: ModuleInfo,
                  scope: Optional[FunctionScope]) -> Optional[ClassSymbol]:
        if scope is None or not scope.class_name:
            return None
        return self.classes.get(f"{module.name}.{scope.class_name}")

    def resolve_call(self, module: ModuleInfo,
                     scope: Optional[FunctionScope], call: ast.Call,
                     type_env: Optional[Dict[str, ClassSymbol]] = None
                     ) -> Optional[Symbol]:
        """The project symbol a call site invokes, or None.

        Returns a :class:`FunctionSymbol` for plain calls and a
        :class:`ClassSymbol` for constructor calls (effects use its
        ``__init__``).
        """
        func = call.func
        if isinstance(func, ast.Name):
            nm = func.id
            cur = scope
            while cur is not None:           # nested defs shadow outward
                child = self._scope_children.get(cur, {}).get(nm)
                if child is not None:
                    return self.all_functions.get(
                        self._fid_by_scope.get(child, ""))
                cur = cur.parent
            sym = (self.functions.get(f"{module.name}.{nm}")
                   or self.classes.get(f"{module.name}.{nm}"))
            if sym is not None:
                return sym
            target = module.imports.alias_of(nm)
            if target:
                return self.resolve_dotted(target)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        dotted = module.imports.resolve(func)
        if dotted:
            sym = self.resolve_dotted(dotted)
            if sym is not None:
                return sym
        base = func.value
        own = self.own_class(module, scope)
        if (isinstance(base, ast.Name) and base.id in ("self", "cls")
                and own is not None):
            got = self.class_method(own, meth)
            if got is not None:
                return got
        if type_env:
            recv = dotted_expr(base)
            cls = type_env.get(recv) if recv else None
            if cls is not None:
                got = self.class_method(cls, meth)
                if got is not None:
                    return got
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and own is not None):
            t = self.class_attr_type(own, base.attr)
            if t is not None:
                got = self.class_method(t, meth)
                if got is not None:
                    return got
        if (not meth.startswith("__")
                and meth not in AMBIGUOUS_METHOD_NAMES):
            cands = self._method_index.get(meth, [])
            if len(cands) == 1:
                return cands[0]
        return None

    # -- locks -------------------------------------------------------------
    def lock_id(self, module: ModuleInfo, scope: Optional[FunctionScope],
                expr: ast.expr,
                type_env: Optional[Dict[str, ClassSymbol]] = None
                ) -> Optional[str]:
        """Canonical id for a lock expression at a use site
        (``with self._lock:`` / ``_LOCK.acquire()``), or None when the
        expression is not a known lock object."""
        d = dotted_expr(expr)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            if parts[0] in self._module_locks.get(module.name, set()):
                return f"{module.name}.{parts[0]}"
            target = module.imports.alias_of(parts[0])
            if target and "." in target:
                mod, var = target.rsplit(".", 1)
                if var in self._module_locks.get(mod, set()):
                    return target
            return None
        if len(parts) == 2:
            if parts[0] in ("self", "cls"):
                own = self.own_class(module, scope)
                if own is not None and self._has_lock_attr(own, parts[1]):
                    return f"{own.cid}.{parts[1]}"
                return None
            if type_env:
                cls = type_env.get(parts[0])
                if cls is not None and self._has_lock_attr(cls, parts[1]):
                    return f"{cls.cid}.{parts[1]}"
            # module-qualified: native._LOCK
            target = module.imports.alias_of(parts[0])
            if target and parts[1] in self._module_locks.get(target, set()):
                return f"{target}.{parts[1]}"
        if len(parts) == 3 and parts[0] == "self":
            # self.attr._lock with a typed attr
            own = self.own_class(module, scope)
            if own is not None:
                t = self.class_attr_type(own, parts[1])
                if t is not None and self._has_lock_attr(t, parts[2]):
                    return f"{t.cid}.{parts[2]}"
        return None

    def _has_lock_attr(self, cls: ClassSymbol, attr: str,
                       depth: int = 0) -> bool:
        if attr in cls.lock_attrs:
            return True
        if depth >= _RESOLVE_DEPTH:
            return False
        return any(
            self._has_lock_attr(base, attr, depth + 1)
            for ref in cls.base_refs
            for base in [self._class_from_ref(cls.module, ref)]
            if base is not None)
