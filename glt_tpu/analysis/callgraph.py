"""Project call graph: who calls whom, cycle-tolerant, bounded-depth.

Nodes are function ids (``module.Class.method`` / ``module.fn`` /
``module.outer.<locals>.inner``) assigned by :class:`~.symbols.Project`;
edges come from the call-resolution pass (one edge per resolvable call
site).  The graph is deliberately tolerant of the two things naive
bottom-up analyses choke on:

* **cycles** (mutual recursion, retry loops calling back into the
  protocol layer): Tarjan SCC condensation yields a callees-first order
  in which every strongly-connected component is processed as one unit —
  the effect engine iterates each SCC to a (bounded) fixpoint instead of
  recursing forever;
* **depth**: :meth:`CallGraph.reachable` takes a ``max_depth`` cutoff so
  queries (and transitive-effect chains built on them) stay bounded even
  on adversarial inputs.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple


@dataclass(frozen=True)
class CallEdge:
    caller: str
    callee: str
    line: int


class CallGraph:
    def __init__(self, nodes: Iterable[str],
                 edges: Iterable[CallEdge]) -> None:
        self.nodes: List[str] = sorted(set(nodes))
        self.edges: List[CallEdge] = list(edges)
        self._out: Dict[str, List[CallEdge]] = {n: [] for n in self.nodes}
        for e in self.edges:
            self._out.setdefault(e.caller, []).append(e)
            if e.callee not in self._out:
                self._out[e.callee] = []
        if len(self._out) != len(self.nodes):
            self.nodes = sorted(self._out)

    def callees(self, fid: str) -> List[CallEdge]:
        return self._out.get(fid, [])

    # -- SCC condensation --------------------------------------------------
    def sccs(self) -> List[List[str]]:
        """Strongly connected components, callees-first (Tarjan order: a
        component is emitted only after everything it can reach).  The
        effect engine walks this order so callee summaries exist before
        their callers are summarized — and a recursive component is
        handled as one fixpoint unit, never an infinite descent."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        for root in self.nodes:
            if root in index:
                continue
            # iterative Tarjan: (node, iterator position) work stack
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                node, pi = work.pop()
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                succs = self._out.get(node, [])
                for i in range(pi, len(succs)):
                    succ = succs[i].callee
                    if succ not in index:
                        work.append((node, i + 1))
                        work.append((succ, 0))
                        recurse = True
                        break
                    if succ in on_stack:
                        low[node] = min(low[node], index[succ])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    out.append(sorted(comp))
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        return out

    # -- bounded reachability ----------------------------------------------
    def reachable(self, fid: str,
                  max_depth: Optional[int] = None) -> Dict[str, int]:
        """BFS call-depths from ``fid`` (itself at depth 0); traversal
        stops at ``max_depth`` edges — the engine's bounded-depth cutoff."""
        depths: Dict[str, int] = {fid: 0}
        frontier = [fid]
        d = 0
        while frontier and (max_depth is None or d < max_depth):
            d += 1
            nxt: List[str] = []
            for cur in frontier:
                for e in self._out.get(cur, []):
                    if e.callee not in depths:
                        depths[e.callee] = d
                        nxt.append(e.callee)
            frontier = nxt
        return depths
