"""Thread-entry discovery + shared-field race detection (GLT027).

The distributed tier is deliberately multi-threaded: the server spawns
an accept loop, a lease reaper, and one handler thread per connection;
producers spawn forwarders; the serving front runs a dispatcher; the
fleet controller and SLO monitor run poll loops; the supervisor spawns
heartbeat and watch threads.  GLT008/GLT009 check lock *ordering* —
this pass checks lock *coverage*: an instance field written from one
thread entry and read or written from another must have a common lock
on its access paths, unless it matches a sanctioned lock-free idiom.

**Thread entries** are ``threading.Thread(target=...)`` spawns whose
target is a bound method (``target=self._run``) or a nested function of
a method (``target=loop``).  Each entry's *domain* is the set of
same-class scopes reachable from it through ``self.method()`` calls;
every other method body belongs to the implicit main/caller domain.  A
scope reachable from a spawn entry is attributed to that entry only —
public drivers like ``tick()`` are either called externally *or* from
the spawned loop, never both concurrently in the house designs.

For every ``self.<attr>`` access the pass records read/write, whether
the write is a read-modify-write (``+=``, container mutation through
``.append``/``.add``/``[k] = v``), and the locks held at the access
(``with self._lock:`` blocks and explicit ``acquire``/``release``,
matched through :meth:`~.symbols.Project.lock_id`).

A field accessed from two or more domains and written outside
``__init__`` is flagged **unless**:

* every write shares a common lock (unlocked reads of a lock-guarded
  field are the accepted stale-read idiom);
* all writes are plain whole-value assignments from a single domain
  (atomic publish-via-replace, e.g. a shed fraction published by the
  monitor thread);
* all writes are lock-free read-modify-writes from a single domain
  *and* no access path takes any lock (single-writer counters such as
  heartbeat ``sent``/``failures``) — if other accesses of the same
  field do take a lock, the unlocked write missed the field's locking
  discipline and is flagged.

Fields holding thread-safe primitives (queues, events, thread handles)
and fields declared as locks are exempt; cross-class callback threading
(e.g. a supervisor thread invoking another object's callback) is out of
scope — same-class state is where the calibrated true positives live.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .report import Finding
from .rules import Rule, register
from .symbols import ClassSymbol, Project
from .visitor import FunctionScope, ModuleInfo, walk_own

# Mutating container methods: a load of ``self.attr`` used as their
# receiver is a write to the container's state.
MUTATOR_METHODS = {
    "add", "append", "appendleft", "extend", "insert", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
}

# Constructor refs whose instances are internally synchronized (or only
# ever driven from the owning thread): accesses through them are not
# shared-state hazards.
THREADSAFE_TYPE_REFS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "collections.deque",
    "threading.Event", "threading.local", "threading.Thread",
    "threading.Timer", "threading.Barrier",
    "multiprocessing.Queue", "multiprocessing.Event",
    "multiprocessing.Process",
    "concurrent.futures.ThreadPoolExecutor",
}

_THREAD_FACTORY = "threading.Thread"
_MAIN = "<caller>"


@dataclass(frozen=True)
class FieldAccess:
    attr: str
    line: int
    scope_name: str
    is_write: bool
    is_rmw: bool                    # +=, container mutation, del
    held: FrozenSet[str]
    node: ast.AST


@dataclass
class ThreadEntry:
    label: str                      # entry scope name, e.g. "_run"
    scope: FunctionScope
    spawn_line: int


class _ClassThreadModel:
    """Per-class view: spawn entries, scope domains, field accesses."""

    def __init__(self, project: Project, cls: ClassSymbol) -> None:
        self.project = project
        self.cls = cls
        self.module = cls.module
        self.entries: List[ThreadEntry] = []
        self.scopes: List[FunctionScope] = self._class_scopes()
        self._scope_set = set(self.scopes)

    def _class_scopes(self) -> List[FunctionScope]:
        """Methods of the class plus their nested functions."""
        out: List[FunctionScope] = []
        methods = {m.scope for m in self.cls.methods.values()}
        for scope in self.module.scopes:
            cur: Optional[FunctionScope] = scope
            while cur is not None:
                if cur in methods:
                    out.append(scope)
                    break
                cur = cur.parent
        return out

    # -- call edges + reachability ---------------------------------------
    def _edges(self, scope: FunctionScope) -> List[FunctionScope]:
        out: List[FunctionScope] = []
        for node in walk_own(scope.node):
            if not isinstance(node, ast.Call):
                continue
            target = self._resolve_target(scope, node.func)
            if target is not None:
                out.append(target)
        return out

    def _resolve_target(self, scope: FunctionScope,
                        func: ast.expr) -> Optional[FunctionScope]:
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"):
            sym = self.project.class_method(self.cls, func.attr)
            if sym is not None and sym.scope in self._scope_set:
                return sym.scope
        elif isinstance(func, ast.Name):
            cur: Optional[FunctionScope] = scope
            while cur is not None:
                child = self.project._scope_children.get(
                    cur, {}).get(func.id)
                if child is not None and child in self._scope_set:
                    return child
                cur = cur.parent
        return None

    def domains(self) -> Dict[FunctionScope, Set[str]]:
        """Scope -> domain labels.  Spawn-reachable scopes belong to
        their entries; everything else is the caller domain."""
        reached: Dict[FunctionScope, Set[str]] = {}
        for entry in self.entries:
            frontier = [entry.scope]
            seen: Set[FunctionScope] = set()
            while frontier:
                cur = frontier.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                reached.setdefault(cur, set()).add(entry.label)
                frontier.extend(self._edges(cur))
        out: Dict[FunctionScope, Set[str]] = {}
        for scope in self.scopes:
            if scope.name in ("__init__", "__del__"):
                continue
            out[scope] = reached.get(scope, {_MAIN})
        return out

    # -- field accesses ---------------------------------------------------
    def accesses(self, scope: FunctionScope) -> List[FieldAccess]:
        collector = _AccessWalk(self.project, self.module, scope,
                                self.cls)
        body = getattr(scope.node, "body", None)
        if isinstance(body, list):
            collector.walk(body, ())
        return collector.out


class _AccessWalk:
    """Linear statement walk with lock-hold tracking (the effects.py
    discipline), recording every ``self.<attr>`` read/write."""

    def __init__(self, project: Project, module: ModuleInfo,
                 scope: FunctionScope, cls: ClassSymbol) -> None:
        self.project = project
        self.module = module
        self.scope = scope
        self.cls = cls
        self.out: List[FieldAccess] = []

    def walk(self, body: Sequence[ast.stmt],
             held: Tuple[str, ...]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                  # separate scope
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    self._record_stmt(item.context_expr, held)
                    lid = self.project.lock_id(
                        self.module, self.scope, item.context_expr, {})
                    if lid is not None:
                        inner = inner + (lid,)
                self.walk(stmt.body, inner)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._record_stmt(stmt.iter, held)
                self._record_target(stmt.target, held)
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.While):
                self._record_stmt(stmt.test, held)
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.If):
                self._record_stmt(stmt.test, held)
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.Try):
                self.walk(stmt.body, held)
                for h in stmt.handlers:
                    self.walk(h.body, held)
                self.walk(stmt.orelse, held)
                self.walk(stmt.finalbody, held)
                continue
            adj = self._acquire_release(stmt)
            if adj is not None:
                lid, is_acquire = adj
                if is_acquire:
                    held = held + (lid,)
                elif lid in held:
                    held = tuple(x for x in held if x != lid)
                continue
            self._record_stmt(stmt, held)

    def _acquire_release(self, stmt: ast.stmt
                         ) -> Optional[Tuple[str, bool]]:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in ("acquire", "release")):
            return None
        lid = self.project.lock_id(self.module, self.scope,
                                   stmt.value.func.value, {})
        if lid is None:
            return None
        return lid, stmt.value.func.attr == "acquire"

    # -- per-statement access extraction ----------------------------------
    def _record_stmt(self, node: ast.AST,
                     held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value_reads = (self._self_attrs(value)
                           if value is not None else set())
            for t in targets:
                self._record_target(t, held, value_reads=value_reads)
            if value is not None:
                self._record_loads(value, held)
            return
        if isinstance(node, ast.AugAssign):
            attr = self._self_attr_of(node.target)
            if attr is not None:
                self._emit(attr, node.lineno, node.target, held,
                           is_write=True, is_rmw=True)
            elif isinstance(node.target, ast.Subscript):
                base = self._self_attr_of(node.target.value)
                if base is not None:
                    self._emit(base, node.lineno, node.target, held,
                               is_write=True, is_rmw=True)
                self._record_loads(node.target.slice, held)
            self._record_loads(node.value, held)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                attr = self._self_attr_of(t)
                if attr is not None:
                    self._emit(attr, node.lineno, t, held,
                               is_write=True, is_rmw=True)
                elif isinstance(t, ast.Subscript):
                    base = self._self_attr_of(t.value)
                    if base is not None:
                        self._emit(base, node.lineno, t, held,
                                   is_write=True, is_rmw=True)
            return
        self._record_loads(node, held)

    def _record_target(self, target: ast.expr, held: Tuple[str, ...],
                       value_reads: Set[str] = frozenset()) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._record_target(el, held, value_reads=value_reads)
            return
        attr = self._self_attr_of(target)
        if attr is not None:
            # assigning a value computed from the same field is a
            # read-modify-write even when spelled as a plain Assign
            self._emit(attr, target.lineno, target, held,
                       is_write=True, is_rmw=attr in value_reads)
            return
        if isinstance(target, ast.Subscript):
            base = self._self_attr_of(target.value)
            if base is not None:
                self._emit(base, target.lineno, target, held,
                           is_write=True, is_rmw=True)
            self._record_loads(target.slice, held)

    def _record_loads(self, node: ast.AST,
                      held: Tuple[str, ...]) -> None:
        for sub in list(walk_own(node)) + [node]:
            attr = self._self_attr_of(sub)
            if attr is None:
                continue
            if self._is_mutator_receiver(sub):
                self._emit(attr, sub.lineno, sub, held,
                           is_write=True, is_rmw=True)
            else:
                self._emit(attr, sub.lineno, sub, held,
                           is_write=False, is_rmw=False)

    def _self_attrs(self, node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for sub in list(walk_own(node)) + [node]:
            attr = self._self_attr_of(sub)
            if attr is not None:
                out.add(attr)
        return out

    def _self_attr_of(self, node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _is_mutator_receiver(self, node: ast.AST) -> bool:
        """``self.attr`` as the receiver of ``.add()``/``.append()``/…"""
        parent = self.module.parents.get(node)
        if not (isinstance(parent, ast.Attribute)
                and parent.value is node
                and parent.attr in MUTATOR_METHODS):
            return False
        call = self.module.parents.get(parent)
        return isinstance(call, ast.Call) and call.func is parent

    def _emit(self, attr: str, line: int, node: ast.AST,
              held: Tuple[str, ...], is_write: bool,
              is_rmw: bool) -> None:
        if attr in self.cls.lock_attrs:
            return
        ref = self.cls.attr_type_refs.get(attr)
        if ref in THREADSAFE_TYPE_REFS:
            return
        self.out.append(FieldAccess(
            attr=attr, line=line, scope_name=self.scope.name,
            is_write=is_write, is_rmw=is_rmw,
            held=frozenset(held), node=node))


def _own_class(project: Project, module: ModuleInfo,
               scope: Optional[FunctionScope]) -> Optional[ClassSymbol]:
    cur = scope
    while cur is not None:
        if cur.class_name:
            return project.classes.get(
                f"{module.name}.{cur.class_name}")
        cur = cur.parent
    return None


def _thread_target_scope(project: Project, module: ModuleInfo,
                         spawn_scope: Optional[FunctionScope],
                         cls: ClassSymbol,
                         target: ast.expr) -> Optional[FunctionScope]:
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        sym = project.class_method(cls, target.attr)
        return sym.scope if sym is not None else None
    if isinstance(target, ast.Name):
        cur = spawn_scope
        while cur is not None:
            child = project._scope_children.get(cur, {}).get(target.id)
            if child is not None:
                return child
            cur = cur.parent
    return None


def build_thread_models(project: Project
                        ) -> Dict[str, _ClassThreadModel]:
    """Discover every ``Thread(target=...)`` spawn, grouped by owning
    class (memoized on the project)."""
    cached = getattr(project, "_thread_models", None)
    if cached is not None:
        return cached
    models: Dict[str, _ClassThreadModel] = {}
    for name in sorted(project.modules):
        module = project.modules[name]
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if module.call_name(node) != _THREAD_FACTORY:
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and node.args:
                target = node.args[0]
            if target is None:
                continue
            spawn_scope = _enclosing_scope(module, node)
            cls = _own_class(project, module, spawn_scope)
            if cls is None:
                continue
            model = models.get(cls.cid)
            if model is None:
                model = models[cls.cid] = _ClassThreadModel(
                    project, cls)
            entry_scope = _thread_target_scope(
                project, module, spawn_scope, cls, target)
            if entry_scope is None or entry_scope not in \
                    model._scope_set:
                continue
            model.entries.append(ThreadEntry(
                label=entry_scope.name, scope=entry_scope,
                spawn_line=node.lineno))
    project._thread_models = models
    return models


def _enclosing_scope(module: ModuleInfo,
                     node: ast.AST) -> Optional[FunctionScope]:
    cur = module.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return module.scope_of(cur)
        cur = module.parents.get(cur)
    return None


@register
class UnguardedSharedField(Rule):
    name = "unguarded-shared-field"
    code = "GLT027"
    description = ("an instance field shared across thread entries "
                   "without a common lock or a sanctioned lock-free "
                   "idiom")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        if project is None:
            return []
        out: List[Finding] = []
        for cid in sorted(build_thread_models(project)):
            model = build_thread_models(project)[cid]
            if model.module is not module or not model.entries:
                continue
            out.extend(self._check_class(model))
        return out

    def _check_class(self, model: _ClassThreadModel) -> List[Finding]:
        domains = model.domains()
        by_attr: Dict[str, List[Tuple[FieldAccess, Set[str]]]] = {}
        for scope, doms in domains.items():
            for acc in model.accesses(scope):
                by_attr.setdefault(acc.attr, []).append((acc, doms))
        out: List[Finding] = []
        for attr in sorted(by_attr):
            finding = self._check_field(model, attr, by_attr[attr])
            if finding is not None:
                out.append(finding)
        return out

    def _check_field(self, model: _ClassThreadModel, attr: str,
                     accesses: List[Tuple[FieldAccess, Set[str]]]
                     ) -> Optional[Finding]:
        writes = [(a, d) for a, d in accesses if a.is_write]
        if not writes:
            return None
        touched: Set[str] = set()
        for _a, doms in accesses:
            touched |= doms
        if len(touched) < 2:
            return None                  # single-threaded field
        common = frozenset.intersection(*[a.held for a, _d in writes])
        if common:
            return None                  # every write shares a lock
        w0 = writes[0][0]
        cname = model.cls.name
        if any(a.held for a, _d in writes):
            unlocked = next(a for a, _d in writes if not a.held)
            return self.finding(
                model.module, unlocked.node,
                f"field '{attr}' of {cname} is written without a lock "
                f"in '{unlocked.scope_name}' but other writes hold "
                f"one — inconsistent locking on a field shared across "
                f"thread entries")
        write_domains: Set[str] = set()
        for _a, doms in writes:
            write_domains |= doms
        if len(write_domains) == 1:
            if all(not a.is_rmw for a, _d in writes):
                return None              # atomic publish-via-replace
            if not any(a.held for a, _d in accesses):
                return None              # single-writer counter idiom
            locked = next(a for a, _d in accesses if a.held)
            return self.finding(
                model.module, w0.node,
                f"field '{attr}' of {cname} is updated in place "
                f"without a lock in '{w0.scope_name}' (thread entry "
                f"domain {sorted(write_domains)[0]!r}) while "
                f"'{locked.scope_name}' accesses it under "
                f"'{sorted(locked.held)[0]}' — the write misses the "
                f"field's locking discipline")
        return self.finding(
            model.module, w0.node,
            f"field '{attr}' of {cname} is written from multiple "
            f"thread domains ({', '.join(sorted(write_domains))}) "
            f"with no common lock")
