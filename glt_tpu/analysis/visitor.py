"""Shared AST machinery: import resolution, scopes, jit-context discovery.

Every rule needs the same three questions answered about a module:

1. *What does this name really mean?*  ``jnp.asarray`` vs
   ``jax.numpy.asarray`` vs ``from jax.numpy import asarray`` are one
   callee.  :class:`ImportMap` canonicalises call targets to full dotted
   paths ("jax.numpy.asarray", "numpy.random.default_rng", ...).

2. *Which code is traced?*  ``@jax.jit`` / ``@partial(jax.jit, ...)``
   decorators, ``jax.jit(fn)`` / ``jax.jit(jax.shard_map(fn, ...))``
   wrapping expressions (including ``jax.jit(self._impl)`` on methods),
   and module-level helpers called from traced bodies are all jit
   contexts; host-side rules must not fire there and trace-side rules
   only fire there.

3. *What is the statement order inside a function?*  Key-reuse and
   donation analyses walk statements linearly, forking state at ``if``
   branches (a use in the else-branch is not "after" a use in the
   then-branch).

This module answers 1 and 2 (:class:`ModuleInfo`); rules implement 3 on
top with :func:`iter_statements` / :func:`names_loaded` helpers.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# Canonical dotted names (post alias-resolution) for the JAX tracing
# entry points.  ``pjit``/``shard_map`` trace exactly like ``jit``.
JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
SHARD_MAP_NAMES = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.maps.xmap",
}
PARTIAL_NAMES = {"functools.partial", "partial"}
TRACE_WRAPPERS = JIT_NAMES | SHARD_MAP_NAMES | {
    "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.lax.scan", "jax.lax.map",
}


class ImportMap:
    """Alias table mapping local names to canonical dotted module paths.

    When ``package`` is given (the importing module's package), relative
    imports — ``from ..channel.base import bounded_get`` — are resolved
    against it to absolute dotted paths, so cross-module symbol lookup
    (analysis/symbols.py) sees one canonical spelling.
    """

    def __init__(self) -> None:
        self._alias: Dict[str, str] = {}

    def collect(self, tree_or_nodes, package: str = "") -> "ImportMap":
        """Collect aliases from a whole tree, or from a pre-gathered
        iterable of Import/ImportFrom nodes (ModuleInfo passes the list
        from its single traversal so the tree is walked once, not per
        consumer)."""
        nodes = (ast.walk(tree_or_nodes)
                 if isinstance(tree_or_nodes, ast.AST) else tree_or_nodes)
        for node in nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    self._alias[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = package.split(".") if package else []
                    cut = len(pkg) - (node.level - 1)
                    if cut < 0:
                        continue          # escapes the analyzed root
                    prefix = ".".join(pkg[:cut])
                    base = (f"{prefix}.{node.module}"
                            if node.module and prefix
                            else (prefix or node.module or ""))
                if not base:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self._alias[a.asname or a.name] = f"{base}.{a.name}"
        return self

    def alias_of(self, name: str) -> Optional[str]:
        """The canonical dotted target this local name was imported as."""
        return self._alias.get(name)

    def resolve(self, node: ast.expr) -> Optional[str]:
        """Canonical dotted path for a Name/Attribute chain, else None.

        Unaliased bare names resolve to themselves so builtins (``int``,
        ``float``) and locals still produce a comparable string.
        """
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self._alias.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))


def names_loaded(node: ast.AST) -> Set[str]:
    """All Name identifiers read anywhere inside ``node``."""
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def param_names(fn: FunctionNode) -> List[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def assign_targets(node: ast.stmt) -> List[str]:
    """Plain-Name targets (including tuple unpacking) of an assignment."""
    out: List[str] = []
    targets: Sequence[ast.expr] = ()
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = (node.target,)
    for t in targets:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                if isinstance(el, ast.Name):
                    out.append(el.id)
                elif isinstance(el, ast.Starred) and isinstance(
                        el.value, ast.Name):
                    out.append(el.value.id)
    return out


def walk_own(node: ast.AST) -> Iterator[ast.AST]:
    """Walk an AST without descending into nested function/class bodies
    (those are separate scopes with their own analysis passes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if not isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(cur))


def dotted_expr(node: ast.expr) -> Optional[str]:
    """'self.x.y' style dotted string for Name/Attribute chains (no alias
    resolution — used for tracking local/attribute variables)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    return ".".join(reversed(parts))


STATIC_ATTRS = ("shape", "ndim", "dtype", "size")


def traced_names(node: ast.AST) -> Set[str]:
    """Names + dotted attribute strings read inside ``node``, except those
    reached only through a static attribute (``x.shape[0]`` is a Python
    int even on a tracer, so it is not a traced-value read)."""
    out: Set[str] = set()
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.Attribute) and cur.attr in STATIC_ATTRS:
            continue                       # x.shape / x.ndim: static
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Load):
            out.add(cur.id)
        if isinstance(cur, ast.Attribute):
            d = dotted_expr(cur)
            if d is not None:
                out.add(d)
        stack.extend(ast.iter_child_nodes(cur))
    return out


def module_name_for_path(path: str) -> str:
    """Dotted module name for a file, walking up through ``__init__.py``
    packages (``glt_tpu/channel/base.py`` -> ``glt_tpu.channel.base``); a
    file outside any package resolves to its bare stem."""
    path = os.path.abspath(path)
    base = os.path.splitext(os.path.basename(path))[0]
    parts = [] if base == "__init__" else [base]
    d = os.path.dirname(path)
    while d and os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return ".".join(parts) or base


def iter_statements(body: Sequence[ast.stmt]) -> Iterator[ast.stmt]:
    """Flatten statements in source order, descending into compound
    statements (but NOT into nested function/class definitions)."""
    for stmt in body:
        yield stmt
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub and not isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef)):
                yield from iter_statements(sub)
        for handler in getattr(stmt, "handlers", ()) or ():
            yield from iter_statements(handler.body)


@dataclass(eq=False)     # identity hash: scopes key analysis caches
class FunctionScope:
    node: FunctionNode
    name: str
    parent: Optional["FunctionScope"]     # enclosing function, if nested
    class_name: Optional[str]             # enclosing class, if a method
    jit_root: bool = False                # directly jitted/shard_mapped
    jit_reason: str = ""                  # how it became a jit context
    static_args: Set[str] = field(default_factory=set)
    donate_argnums: Set[int] = field(default_factory=set)
    # set when jit-ness is only transitive (called from a jit body):
    # (caller scope, call node) — rules use it to bind caller taint to
    # params instead of assuming every param is traced
    transitive_call: Optional[Tuple["FunctionScope", ast.Call]] = None

    @property
    def params(self) -> List[str]:
        return param_names(self.node)


def _unwrap_traced_target(call: ast.Call, imports: ImportMap
                          ) -> Optional[ast.expr]:
    """Peel ``jax.jit(jax.shard_map(partial(fn, ...), ...))`` down to the
    innermost traced callable expression (fn)."""
    if not call.args:
        return None
    target = call.args[0]
    while isinstance(target, ast.Call):
        inner = imports.resolve(target.func)
        if inner in TRACE_WRAPPERS or inner in PARTIAL_NAMES:
            if not target.args:
                return None
            target = target.args[0]
        else:
            break
    return target


def _static_arg_names(call: ast.Call, fn: Optional[FunctionNode]
                      ) -> Set[str]:
    """Names covered by static_argnums/static_argnames in a jit call."""
    static: Set[str] = set()
    pos = param_names(fn) if fn is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in _iter_const(kw.value):
                if isinstance(el, str):
                    static.add(el)
        elif kw.arg == "static_argnums":
            for el in _iter_const(kw.value):
                if isinstance(el, int) and 0 <= el < len(pos):
                    static.add(pos[el])
    return static


def _donated_argnums(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            out |= {el for el in _iter_const(kw.value)
                    if isinstance(el, int)}
    return out


def _iter_const(node: ast.expr) -> Iterator[object]:
    if isinstance(node, ast.Constant):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for el in node.elts:
            yield from _iter_const(el)


class ModuleInfo:
    """Parsed module + resolved imports + jit-context classification.

    ``module_name`` (the dotted import path, e.g.
    ``glt_tpu.distributed.dist_server``) keys the module in a project-wide
    analysis (analysis/symbols.py) and anchors relative-import resolution;
    when omitted it defaults to the file stem and relative imports stay
    unresolved (single-module analysis, fixtures).
    """

    def __init__(self, path: str, source: str,
                 module_name: Optional[str] = None):
        self.path = path
        self.source = source
        self.name = module_name or os.path.splitext(
            os.path.basename(path))[0]
        if os.path.basename(path) == "__init__.py":
            self.package = self.name
        else:
            self.package = (self.name.rsplit(".", 1)[0]
                            if "." in self.name else "")
        self.tree = ast.parse(source, filename=path)
        # One traversal feeds every downstream consumer: the parent map,
        # the import table, and the call-site list _mark_jit_roots scans
        # (full ast.walk per consumer dominated analysis setup time).
        self.parents: Dict[ast.AST, ast.AST] = {}
        import_nodes: List[ast.stmt] = []
        self._calls: List[ast.Call] = []
        stack: List[ast.AST] = [self.tree]
        while stack:
            parent = stack.pop()
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
                stack.append(child)
                if isinstance(child, ast.Call):
                    self._calls.append(child)
                elif isinstance(child, (ast.Import, ast.ImportFrom)):
                    import_nodes.append(child)
        self.imports = ImportMap().collect(import_nodes,
                                           package=self.package)
        self.scopes: List[FunctionScope] = []
        self._scope_by_node: Dict[ast.AST, FunctionScope] = {}
        self._collect_scopes(self.tree, None, None)
        self._mark_jit_roots()
        self._mark_called_from_jit()

    # -- scope collection --------------------------------------------------
    def _collect_scopes(self, node: ast.AST, parent: Optional[FunctionScope],
                        class_name: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = FunctionScope(child, child.name, parent, class_name)
                self.scopes.append(scope)
                self._scope_by_node[child] = scope
                self._collect_scopes(child, scope, None)
            elif isinstance(child, ast.Lambda):
                scope = FunctionScope(child, "<lambda>", parent, class_name)
                self.scopes.append(scope)
                self._scope_by_node[child] = scope
                self._collect_scopes(child, scope, None)
            elif isinstance(child, ast.ClassDef):
                self._collect_scopes(child, parent, child.name)
            else:
                self._collect_scopes(child, parent, class_name)

    # -- jit classification ------------------------------------------------
    def _resolve(self, node: ast.expr) -> Optional[str]:
        return self.imports.resolve(node)

    def _mark_decorated(self, scope: FunctionScope) -> None:
        fn = scope.node
        if isinstance(fn, ast.Lambda):
            return
        for dec in fn.decorator_list:
            name = self._resolve(dec if not isinstance(dec, ast.Call)
                                 else dec.func)
            if name in JIT_NAMES | SHARD_MAP_NAMES:
                scope.jit_root = True
                scope.jit_reason = f"decorated with {name}"
                if isinstance(dec, ast.Call):
                    scope.static_args |= _static_arg_names(dec, fn)
                    scope.donate_argnums |= _donated_argnums(dec)
            elif (isinstance(dec, ast.Call) and name in PARTIAL_NAMES
                  and dec.args):
                inner = self._resolve(dec.args[0])
                if inner in JIT_NAMES | SHARD_MAP_NAMES:
                    scope.jit_root = True
                    scope.jit_reason = f"decorated with partial({inner})"
                    scope.static_args |= _static_arg_names(dec, fn)
                    scope.donate_argnums |= _donated_argnums(dec)

    def _mark_jit_roots(self) -> None:
        for scope in self.scopes:
            self._mark_decorated(scope)
        # jax.jit(expr) / jax.jit(jax.shard_map(expr, ...)) call sites.
        by_name: Dict[str, List[FunctionScope]] = {}
        for scope in self.scopes:
            by_name.setdefault(scope.name, []).append(scope)
        for node in self._calls:
            name = self._resolve(node.func)
            if name not in JIT_NAMES and name not in SHARD_MAP_NAMES:
                continue
            target = _unwrap_traced_target(node, self.imports)
            if target is None:
                continue
            marked: List[FunctionScope] = []
            if isinstance(target, ast.Lambda) and target in self._scope_by_node:
                marked = [self._scope_by_node[target]]
            elif isinstance(target, ast.Name):
                marked = by_name.get(target.id, [])
            elif (isinstance(target, ast.Attribute)
                  and isinstance(target.value, ast.Name)
                  and target.value.id == "self"):
                marked = by_name.get(target.attr, [])
            for scope in marked:
                scope.jit_root = True
                scope.jit_reason = scope.jit_reason or f"wrapped by {name}"
                if name in JIT_NAMES:
                    scope.static_args |= _static_arg_names(node, scope.node)
                    scope.donate_argnums |= _donated_argnums(node)

    def _mark_called_from_jit(self) -> None:
        """One transitive step: module functions called by name from a jit
        context are themselves traced (the `ops/` helper-library pattern:
        pure functions invoked only from inside jitted programs)."""
        by_name: Dict[str, List[FunctionScope]] = {}
        for scope in self.scopes:
            by_name.setdefault(scope.name, []).append(scope)
        for _ in range(4):  # small fixpoint; call chains here are shallow
            changed = False
            jit_scopes = [s for s in self.scopes if self.in_jit_context(s)]
            for scope in jit_scopes:
                for node in ast.walk(scope.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = None
                    if isinstance(node.func, ast.Name):
                        callee = node.func.id
                    elif (isinstance(node.func, ast.Attribute)
                          and isinstance(node.func.value, ast.Name)
                          and node.func.value.id == "self"):
                        callee = node.func.attr
                    if not callee:
                        continue
                    for cand in by_name.get(callee, []):
                        if not cand.jit_root and cand.parent is None:
                            cand.jit_root = True
                            cand.jit_reason = (
                                f"called from jit context "
                                f"'{scope.name}' (line {node.lineno})")
                            cand.transitive_call = (scope, node)
                            changed = True
            if not changed:
                break

    # -- queries -----------------------------------------------------------
    def scope_of(self, fn: FunctionNode) -> Optional[FunctionScope]:
        return self._scope_by_node.get(fn)

    def in_jit_context(self, scope: FunctionScope) -> bool:
        """True if the scope's body is traced: it is a jit root, or it is
        nested (def-in-def) inside one."""
        cur: Optional[FunctionScope] = scope
        while cur is not None:
            if cur.jit_root:
                return True
            cur = cur.parent
        return False

    def jit_scopes(self) -> List[FunctionScope]:
        return [s for s in self.scopes if self.in_jit_context(s)]

    def call_name(self, call: ast.Call) -> Optional[str]:
        return self._resolve(call.func)
