"""gltlint command line: ``python -m glt_tpu.analysis [paths]``.

Exit codes: 0 = clean (or warnings only), 1 = at least one ERROR finding,
2 = usage/parse problems (a file that cannot be parsed is reported as an
error finding, not a crash — CI must not go green on a syntax error).
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Iterable, List, Optional, Sequence

from .report import (
    Finding,
    Severity,
    Suppressions,
    apply_suppressions,
    format_report,
)
from .rules import RULES, Rule, all_rules
from .visitor import ModuleInfo


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence[Rule]] = None,
                   suppress: bool = True) -> List[Finding]:
    """Run the given rules (default: all) over one module's source."""
    rules = list(rules) if rules is not None else all_rules()
    try:
        module = ModuleInfo(path, source)
    except SyntaxError as exc:
        return [Finding(path=path, line=exc.lineno or 1,
                        col=(exc.offset or 1), rule="parse-error",
                        code="GLT000", severity=Severity.ERROR,
                        message=f"cannot parse: {exc.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(module))
    if suppress:
        findings = apply_suppressions(findings,
                                      Suppressions.from_source(source))
    return findings


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as exc:
            findings.append(Finding(
                path=path, line=1, col=1, rule="io-error", code="GLT000",
                severity=Severity.ERROR, message=str(exc)))
            continue
        findings.extend(analyze_source(source, path, rules))
    return findings


def _select_rules(select: Optional[str], ignore: Optional[str]
                  ) -> List[Rule]:
    by_key = {}
    for cls in RULES.values():
        rule = cls()
        by_key[rule.name] = rule
        by_key[rule.code.lower()] = rule
    def lookup(spec: str) -> List[Rule]:
        out = []
        for key in spec.split(","):
            key = key.strip().lower()
            if not key:
                continue
            if key not in by_key:
                raise SystemExit(f"gltlint: unknown rule {key!r} "
                                 f"(see --list-rules)")
            out.append(by_key[key])
        return out
    rules = lookup(select) if select else all_rules()
    if ignore:
        dropped = {r.name for r in lookup(ignore)}
        rules = [r for r in rules if r.name not in dropped]
    return rules


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m glt_tpu.analysis",
        description="gltlint: TPU/JAX-aware static analysis for glt_tpu")
    parser.add_argument("paths", nargs="*", default=["glt_tpu"],
                        help="files or directories to analyze "
                             "(default: glt_tpu)")
    parser.add_argument("--select", metavar="RULES",
                        help="comma-separated rule names/codes to run")
    parser.add_argument("--ignore", metavar="RULES",
                        help="comma-separated rule names/codes to skip")
    parser.add_argument("--strict", action="store_true",
                        help="treat warnings as errors for the exit code")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.code}  {rule.name:32s} {rule.severity!s:8s} "
                  f"{rule.description}")
        return 0

    rules = _select_rules(args.select, args.ignore)
    findings = analyze_paths(args.paths, rules)
    print(format_report(findings))
    gate = (findings if args.strict else
            [f for f in findings if f.severity is Severity.ERROR])
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
