"""gltlint command line: ``python -m glt_tpu.analysis [paths]``.

The CLI parses the whole file set into one :class:`~.symbols.Project`
(symbol table -> call graph -> effect summaries) and runs every rule per
module with the project attached, so the interprocedural rules
(GLT001/GLT002 transitive, GLT008/GLT009) see across files.

Exit codes: 0 = clean (or warnings only), 1 = at least one gating ERROR
finding, 2 = usage/parse problems (a file that cannot be parsed is
reported as an error finding, not a crash — CI must not go green on a
syntax error).

Output modes (``--format``): ``text`` (default), ``json``, ``github``
(workflow-command annotations that render inline on PRs).  A committed
``--baseline`` file gates only on findings not already recorded
(``--write-baseline`` records the current set); ``--profile`` prints
per-pass timings to stderr — the CI job asserts the whole run stays
under its time budget.
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .report import (
    Finding,
    Severity,
    Suppressions,
    apply_suppressions,
    format_github,
    format_json,
    format_report,
    load_baseline,
    split_by_baseline,
    write_baseline,
)
from .rules import RULES, Rule, all_rules
from .symbols import Project
from .visitor import ModuleInfo, module_name_for_path

_FORMATTERS = {"text": format_report, "json": format_json,
               "github": format_github}


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def build_project(paths: Iterable[str]
                  ) -> Tuple[Project, List[Finding]]:
    """Parse every file into one project; unparseable/unreadable files
    become findings (never crashes the gate)."""
    findings: List[Finding] = []
    modules: List[ModuleInfo] = []
    seen_names: Dict[str, int] = {}
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as exc:
            findings.append(Finding(
                path=path, line=1, col=1, rule="io-error", code="GLT000",
                severity=Severity.ERROR, message=str(exc)))
            continue
        name = module_name_for_path(path)
        # de-collide duplicate stems from unrelated directories
        if name in seen_names:
            seen_names[name] += 1
            name = f"{name}#{seen_names[name]}"
        else:
            seen_names[name] = 0
        try:
            modules.append(ModuleInfo(path, source, module_name=name))
        except SyntaxError as exc:
            findings.append(Finding(
                path=path, line=exc.lineno or 1, col=(exc.offset or 1),
                rule="parse-error", code="GLT000",
                severity=Severity.ERROR,
                message=f"cannot parse: {exc.msg}"))
    return Project(modules), findings


def analyze_project(project: Project,
                    rules: Optional[Sequence[Rule]] = None,
                    suppress: bool = True,
                    rule_timings: Optional[Dict[str, float]] = None,
                    only_paths: Optional[Iterable[str]] = None
                    ) -> List[Finding]:
    """Run the given rules (default: all) over every project module.

    When ``rule_timings`` is given, each rule's cumulative wall time
    across all modules is accumulated into it (keyed by rule name) —
    the ``--profile`` per-pass table and the CI perf guard read this.

    ``only_paths`` restricts the *rule passes* to those module paths
    (the incremental ``--changed``/``--since`` mode): the whole file
    set is still parsed into the project, so cross-file resolution and
    effect summaries stay sound, but per-module rule work — the
    dominant cost as the tree grows — runs only on the changed slice.
    """
    rules = list(rules) if rules is not None else all_rules()
    selected = (None if only_paths is None
                else {os.path.abspath(p) for p in only_paths})
    findings: List[Finding] = []
    for path in sorted(project.by_path):
        if selected is not None \
                and os.path.abspath(path) not in selected:
            continue
        module = project.by_path[path]
        module_findings: List[Finding] = []
        for rule in rules:
            if rule_timings is None:
                module_findings.extend(rule.check(module, project))
                continue
            t0 = time.perf_counter()
            module_findings.extend(rule.check(module, project))
            rule_timings[rule.name] = (rule_timings.get(rule.name, 0.0)
                                       + time.perf_counter() - t0)
        if suppress and module_findings:   # tokenizing clean files is
            module_findings = apply_suppressions(   # pure overhead
                module_findings, Suppressions.from_source(module.source))
        findings.extend(module_findings)
    return findings


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence[Rule]] = None,
                   suppress: bool = True) -> List[Finding]:
    """Run the given rules (default: all) over one module's source.

    The module is wrapped in a single-module project, so the
    interprocedural rules work within the file (cross-file effects need
    :func:`analyze_paths` / :func:`analyze_project`).
    """
    rules = list(rules) if rules is not None else all_rules()
    try:
        module = ModuleInfo(path, source)
    except SyntaxError as exc:
        return [Finding(path=path, line=exc.lineno or 1,
                        col=(exc.offset or 1), rule="parse-error",
                        code="GLT000", severity=Severity.ERROR,
                        message=f"cannot parse: {exc.msg}")]
    project = Project([module])
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(module, project))
    if suppress:
        findings = apply_suppressions(findings,
                                      Suppressions.from_source(source))
    return findings


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    project, findings = build_project(paths)
    return findings + analyze_project(project, rules)


def _git_changed_files(since: str) -> Optional[List[str]]:
    """Repo paths changed since ``since`` (tracked diffs + untracked
    files), or None when git is unavailable — the caller falls back to
    a full run rather than silently linting nothing."""
    import subprocess

    def run(*cmd: str) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                list(cmd), capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        return [ln for ln in proc.stdout.splitlines() if ln]

    diffed = run("git", "diff", "--name-only", since, "--")
    if diffed is None:
        return None
    untracked = run("git", "ls-files", "--others",
                    "--exclude-standard") or []
    return sorted(set(diffed) | set(untracked))


def _select_rules(select: Optional[str], ignore: Optional[str]
                  ) -> List[Rule]:
    by_key = {}
    for cls in RULES.values():
        rule = cls()
        by_key[rule.name] = rule
        by_key[rule.code.lower()] = rule
    def lookup(spec: str) -> List[Rule]:
        out = []
        for key in spec.split(","):
            key = key.strip().lower()
            if not key:
                continue
            if key not in by_key:
                raise SystemExit(f"gltlint: unknown rule {key!r} "
                                 f"(see --list-rules)")
            out.append(by_key[key])
        return out
    rules = lookup(select) if select else all_rules()
    if ignore:
        dropped = {r.name for r in lookup(ignore)}
        rules = [r for r in rules if r.name not in dropped]
    return rules


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m glt_tpu.analysis",
        description="gltlint: TPU/JAX-aware static analysis for glt_tpu")
    parser.add_argument("paths", nargs="*", default=["glt_tpu"],
                        help="files or directories to analyze "
                             "(default: glt_tpu)")
    parser.add_argument("--select", metavar="RULES",
                        help="comma-separated rule names/codes to run")
    parser.add_argument("--rule", metavar="RULE",
                        help="run exactly one rule (name or code) and "
                             "skip the call-graph/effect build — the "
                             "fast inner loop while fixing one finding "
                             "class")
    parser.add_argument("--ignore", metavar="RULES",
                        help="comma-separated rule names/codes to skip")
    parser.add_argument("--strict", action="store_true",
                        help="treat warnings as errors for the exit code")
    parser.add_argument("--format",
                        choices=sorted(_FORMATTERS) + ["optable"],
                        default="text", dest="fmt",
                        help="report format (default: text; 'github' "
                             "emits PR-inline workflow annotations; "
                             "'optable' dumps the extracted wire-op "
                             "table as the docs/distributed.md matrix "
                             "instead of findings)")
    parser.add_argument("--changed", action="store_true",
                        help="incremental mode: run rule passes only "
                             "on files changed vs HEAD (plus untracked "
                             "files); the whole tree is still parsed "
                             "so cross-file resolution stays sound")
    parser.add_argument("--since", metavar="REV",
                        help="like --changed, diffed against REV "
                             "instead of HEAD")
    parser.add_argument("--baseline", metavar="FILE",
                        help="gate only on findings not recorded in this "
                             "baseline file")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="record the current findings as the "
                             "baseline and exit 0")
    parser.add_argument("--profile", action="store_true",
                        help="print per-pass timings to stderr")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.code}  {rule.name:32s} {rule.severity!s:8s} "
                  f"{rule.description}")
        return 0

    if args.rule and args.select:
        parser.error("--rule and --select are mutually exclusive")
    if args.rule:
        if "," in args.rule:
            parser.error("--rule takes exactly one rule "
                         "(use --select for a list)")
        rules = _select_rules(args.rule, args.ignore)
    else:
        rules = _select_rules(args.select, args.ignore)
    timings: List[Tuple[str, float]] = []
    t0 = time.perf_counter()
    project, findings = build_project(args.paths)
    timings.append(("parse+symbols", time.perf_counter() - t0))

    if args.fmt == "optable":
        from .protocol import extract_op_table, format_op_table
        print(format_op_table(extract_op_table(project)))
        for f in findings:               # parse failures must not hide
            print(f.format(), file=sys.stderr)
        return 2 if findings else 0

    only_paths: Optional[List[str]] = None
    if args.changed or args.since:
        only_paths = _git_changed_files(args.since or "HEAD")
        if only_paths is None:
            print("gltlint: --changed/--since needs git; running the "
                  "full file set", file=sys.stderr)
        elif args.profile:
            print(f"gltlint --profile: incremental slice: "
                  f"{len(only_paths)} changed file(s)", file=sys.stderr)

    if not args.rule:
        # Single-rule mode skips the forced build: a rule that needs
        # effects still triggers it lazily, but GLT017-021 style passes
        # stay under a second for the fix-one-finding inner loop.
        t0 = time.perf_counter()
        project.effects        # force callgraph + effect summaries
        timings.append(("callgraph+effects", time.perf_counter() - t0))
    t0 = time.perf_counter()
    rule_timings: Dict[str, float] = {}
    findings = findings + analyze_project(
        project, rules,
        rule_timings=rule_timings if args.profile else None,
        only_paths=only_paths)
    timings.append(("rules", time.perf_counter() - t0))

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"gltlint: wrote {len(findings)} finding(s) to baseline "
              f"{args.write_baseline}")
        return 0

    baselined = 0
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            print(f"gltlint: cannot read baseline: {exc}",
                  file=sys.stderr)
            return 2
        findings, baselined = split_by_baseline(findings, baseline)

    print(_FORMATTERS[args.fmt](findings))
    if baselined and args.fmt == "text":
        print(f"gltlint: {baselined} baselined finding(s) hidden "
              f"({args.baseline})")
    if args.profile:
        total = sum(dt for _, dt in timings)
        for name, dt in timings:
            print(f"gltlint --profile: {name:18s} {dt * 1e3:8.1f} ms",
                  file=sys.stderr)
        for name, dt in sorted(rule_timings.items(),
                               key=lambda kv: -kv[1]):
            print(f"gltlint --profile:   pass {name:26s} "
                  f"{dt * 1e3:8.1f} ms", file=sys.stderr)
        print(f"gltlint --profile: {'total':18s} {total * 1e3:8.1f} ms",
              file=sys.stderr)
    gate = (findings if args.strict else
            [f for f in findings if f.severity is Severity.ERROR])
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
