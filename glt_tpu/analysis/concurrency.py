"""Concurrency rules for the threaded distributed layer: GLT008/GLT009.

Both rules read the project-wide effect summaries (analysis/effects.py),
so a hazard hidden one (or N) calls deep is as visible as a direct one —
the shape of bug the dynamic ``bounded_get`` fix closed at runtime, now
gated statically before it ships.

* **GLT008 lock-order-inversion** — the engine records every ordered
  pair "lock A held while lock B is acquired", whether the inner
  acquisition is textually nested (``with a: with b:``) or buried in a
  callee's summary.  Two call paths acquiring the same two locks in
  opposite orders can deadlock the moment both run concurrently (server
  request thread vs. reaper vs. client prefetcher); the rule reports each
  inverted unordered pair once, citing both paths.

* **GLT009 blocking-call-while-holding-lock** — a may-block effect
  (socket recv/accept/connect/sendall, ``time.sleep``, subprocess waits,
  zero-arg ``.get()``/``.join()``/``.wait()``, timeout-polling get
  loops) reachable while a ``threading`` lock is held.  Every other
  thread that touches the lock then inherits the wait: a wedged peer
  turns into a wedged *server*.  Scopes running the GLT007
  timeout-and-recheck pattern are exempt for the poll class
  (``bounded_get``'s waits are bounded by its liveness probe), and
  ``cond.wait()`` on the held Condition itself is the sanctioned monitor
  pattern.  One finding per (function, lock): the first blocking site is
  reported, further sites under the same lock are implied.
"""
from __future__ import annotations

import ast
from typing import List

from .report import Finding, Severity
from .rules import Rule, register
from .symbols import FunctionSymbol
from .visitor import ModuleInfo


@register
class LockOrderInversion(Rule):
    """Two locks acquired in inconsistent orders across any two paths."""
    name = "lock-order-inversion"
    code = "GLT008"
    severity = Severity.ERROR
    description = ("two locks acquired in opposite orders on two call "
                   "paths (deadlock the moment both run concurrently)")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        if project is None:
            return []
        pairs = project.effects.pairs
        findings: List[Finding] = []
        seen = set()
        for (a, b) in sorted(pairs):
            if a == b or frozenset((a, b)) in seen:
                continue
            other = pairs.get((b, a))
            if other is None:
                continue
            seen.add(frozenset((a, b)))
            site = pairs[(a, b)]
            rep, alt = ((site, other)
                        if (site.path, site.line) <= (other.path,
                                                      other.line)
                        else (other, site))
            # one report per inversion, in the module holding the
            # representative site (the rule runs once per module)
            if rep.path != module.path:
                continue
            findings.append(Finding(
                path=rep.path, line=rep.line, col=1, rule=self.name,
                code=self.code, severity=self.severity,
                message=(f"lock order inversion between '{a}' and "
                         f"'{b}': {rep.detail} ({rep.path}:{rep.line}) "
                         f"but on another path {alt.detail} "
                         f"({alt.path}:{alt.line}); two threads taking "
                         f"these paths concurrently deadlock — pick one "
                         f"global acquisition order")))
        return findings


@register
class BlockingUnderLock(Rule):
    """A may-block effect reachable while holding a threading lock."""
    name = "blocking-call-while-holding-lock"
    code = "GLT009"
    severity = Severity.ERROR
    description = ("a blocking call (socket recv/send, sleep, zero-arg "
                   "get/join/wait, subprocess) reachable while a "
                   "threading.Lock/Condition is held")

    def check(self, module: ModuleInfo, project=None) -> List[Finding]:
        if project is None:
            return []
        eng = project.effects
        findings: List[Finding] = []
        for scope in module.scopes:
            if isinstance(scope.node, ast.Lambda):
                continue
            fid = project.fid_of(scope)
            facts = eng.facts.get(fid) if fid else None
            if facts is None:
                continue
            events = []      # (line, innermost lock, detail, held)
            for site, held in facts.blocks:
                if held:
                    events.append((site.line, held[-1],
                                   f"{site.detail}", held))
            for cf in facts.calls:
                if not cf.held:
                    continue
                csum = eng.summary_for(cf.callee)
                if not csum.blocking:
                    continue
                short = (cf.callee.short
                         if isinstance(cf.callee, FunctionSymbol)
                         else cf.callee.name)
                b = csum.blocking[0]
                events.append((cf.line, cf.held[-1],
                               f"{short}() -> {b.detail}", cf.held))
            events.sort(key=lambda e: (e[0], e[1]))
            reported = set()
            for line, lock, detail, held in events:
                if lock in reported:
                    continue
                reported.add(lock)
                held_s = ", ".join(f"'{h}'" for h in held)
                findings.append(Finding(
                    path=module.path, line=line, col=1, rule=self.name,
                    code=self.code, severity=self.severity,
                    message=(f"blocking call {detail} while holding "
                             f"{held_s}: every thread contending on the "
                             f"lock inherits the wait (wedged peer -> "
                             f"wedged service); move the blocking call "
                             f"outside the critical section, bound it, "
                             f"or suppress with a justified escape "
                             f"hatch")))
        return findings
