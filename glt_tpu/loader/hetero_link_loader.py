"""Heterogeneous link-prediction loader.

Rebuild of the reference's hetero ``LinkNeighborLoader`` path
(loader/link_loader.py hetero branch): seed edges of one edge type drive
``HeteroNeighborSampler.sample_from_edges`` with binary/triplet negatives;
metadata carries the local ``edge_label_index`` / triplet indices.
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

import numpy as np

from ..data.dataset import Dataset
from ..sampler.base import EdgeSamplerInput, NegativeSampling
from ..sampler.hetero_neighbor_sampler import HeteroNeighborSampler
from ..typing import EdgeType
from .hetero_neighbor_loader import HeteroNeighborLoader
from .transform import HeteroBatch, to_hetero_batch


class HeteroLinkNeighborLoader(HeteroNeighborLoader):
    def __init__(
        self,
        data: Dataset,
        num_neighbors,
        edge_label_index,           # (EdgeType, [2, E] ids)
        edge_label: Optional[np.ndarray] = None,
        neg_sampling: Optional[NegativeSampling] = None,
        batch_size: int = 512,
        shuffle: bool = False,
        drop_last: bool = False,
        frontier_cap: Optional[int] = None,
        prefetch: int = 2,
        seed: int = 0,
    ):
        edge_type, eli = edge_label_index
        eli = np.asarray(eli)
        sampler = HeteroNeighborSampler(
            data.graph, num_neighbors, edge_type[0],
            batch_size=batch_size, frontier_cap=frontier_cap, seed=seed)
        super().__init__(data, num_neighbors,
                         (edge_type[0], np.arange(eli.shape[1])),
                         batch_size=batch_size, shuffle=shuffle,
                         drop_last=drop_last, prefetch=prefetch, seed=seed,
                         sampler=sampler)
        self.edge_type: EdgeType = edge_type
        self.edge_label_index = eli
        self.edge_label = (None if edge_label is None
                           else np.asarray(edge_label))
        self.neg_sampling = neg_sampling

    def __iter__(self) -> Iterator[HeteroBatch]:
        pending = deque()
        batches = self._epoch_seed_batches()  # batches of edge positions
        while True:
            while len(pending) < self.prefetch:
                pos = next(batches, None)
                if pos is None:
                    break
                inp = EdgeSamplerInput(
                    row=self.edge_label_index[0, pos],
                    col=self.edge_label_index[1, pos],
                    label=None if self.edge_label is None
                    else self.edge_label[pos],
                    input_type=self.edge_type,
                    neg_sampling=self.neg_sampling)
                pending.append(
                    (self.sampler.sample_from_edges(inp), pos.shape[0]))
            if not pending:
                return
            out, npos = pending.popleft()
            yield self._collate_fn(out, npos)
