"""SubGraphLoader — induced-subgraph batches (cf. loader/subgraph_loader.py).

Drives ``NeighborSampler.subgraph``: hop expansion to collect a node set,
then exact induced-subgraph extraction, with ``mapping`` metadata locating
the seeds inside the batch (subgraph_loader.py:89-98).
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, Optional, Sequence

import numpy as np

from ..data.dataset import Dataset
from ..sampler.base import NodeSamplerInput
from ..sampler.neighbor_sampler import NeighborSampler
from .node_loader import NodeLoader
from .transform import Batch


class SubGraphLoader(NodeLoader):
    def __init__(
        self,
        data: Dataset,
        num_neighbors: Sequence[int],
        input_nodes: np.ndarray,
        batch_size: int = 64,
        max_degree: int = 64,
        shuffle: bool = False,
        drop_last: bool = False,
        prefetch: int = 2,
        seed: int = 0,
        sampler: Optional[NeighborSampler] = None,
    ):
        if sampler is None:
            sampler = NeighborSampler(
                data.get_graph(), num_neighbors, batch_size=batch_size,
                seed=seed)
        super().__init__(data, sampler, input_nodes, batch_size=batch_size,
                         shuffle=shuffle, drop_last=drop_last,
                         prefetch=prefetch, seed=seed)
        self.max_degree = int(max_degree)

    def __iter__(self) -> Iterator[Batch]:
        pending = deque()
        batches = self._epoch_seed_batches()
        while True:
            while len(pending) < self.prefetch:
                seeds = next(batches, None)
                if seeds is None:
                    break
                pending.append(
                    (self.sampler.subgraph(NodeSamplerInput(seeds),
                                           max_degree=self.max_degree),
                     seeds.shape[0]))
            if not pending:
                return
            out, nseeds = pending.popleft()
            yield self._collate_fn(out, nseeds)
