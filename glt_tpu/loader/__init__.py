from .link_loader import LinkLoader, LinkNeighborLoader
from .node_loader import NeighborLoader, NodeLoader
from .subgraph_loader import SubGraphLoader
from .transform import Batch, HeteroBatch, to_batch, to_hetero_batch

__all__ = [
    "Batch",
    "HeteroBatch",
    "LinkLoader",
    "LinkNeighborLoader",
    "NeighborLoader",
    "NodeLoader",
    "SubGraphLoader",
    "to_batch",
    "to_hetero_batch",
]
