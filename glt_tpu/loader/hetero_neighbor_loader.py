"""HeteroNeighborLoader — heterogeneous neighbor-sampling loader.

Rebuild of the reference's hetero loader path (loader/neighbor_loader.py
hetero branch + loader/transform.py:54-104 ``to_hetero_data``): per-type
feature/label joins over a :class:`HeteroSamplerOutput`.
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ..data.dataset import Dataset
from ..sampler.base import NodeSamplerInput
from ..sampler.hetero_neighbor_sampler import HeteroNeighborSampler
from ..typing import NodeType, PADDING_ID
from .transform import HeteroBatch, to_hetero_batch


class HeteroNeighborLoader:
    def __init__(
        self,
        data: Dataset,
        num_neighbors,
        input_nodes,
        batch_size: int = 512,
        shuffle: bool = False,
        drop_last: bool = False,
        frontier_cap: Optional[int] = None,
        prefetch: int = 2,
        seed: int = 0,
        sampler: Optional[HeteroNeighborSampler] = None,
        last_hop_dedup: bool = True,
    ):
        if isinstance(input_nodes, tuple):
            input_type, seeds = input_nodes
        else:
            raise ValueError(
                "input_nodes must be (node_type, ids) for hetero loading")
        self.data = data
        self.input_type: NodeType = input_type
        self.input_nodes = np.asarray(seeds).astype(np.int64)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.prefetch = max(1, int(prefetch))
        self._rng = np.random.default_rng(seed)
        self._labels_dev = {}
        if sampler is None:
            sampler = HeteroNeighborSampler(
                data.graph, num_neighbors, input_type,
                batch_size=batch_size, frontier_cap=frontier_cap,
                seed=seed, last_hop_dedup=last_hop_dedup)
        self.sampler = sampler

    def __len__(self) -> int:
        n = self.input_nodes.shape[0]
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_seed_batches(self):
        ids = self.input_nodes
        if self.shuffle:
            ids = ids[self._rng.permutation(ids.shape[0])]
        n = ids.shape[0]
        end = (n // self.batch_size) * self.batch_size if self.drop_last else n
        for lo in range(0, end, self.batch_size):
            yield ids[lo: lo + self.batch_size]

    def __iter__(self) -> Iterator[HeteroBatch]:
        pending = deque()
        batches = self._epoch_seed_batches()
        while True:
            while len(pending) < self.prefetch:
                seeds = next(batches, None)
                if seeds is None:
                    break
                pending.append(
                    (self.sampler.sample_from_nodes(
                        NodeSamplerInput(seeds, self.input_type)),
                     seeds.shape[0]))
            if not pending:
                return
            out, nseeds = pending.popleft()
            yield self._collate_fn(out, nseeds)

    def _collate_fn(self, out, num_seeds: int) -> HeteroBatch:
        x = {}
        for t, node in out.node.items():
            feat = self.data.get_node_feature(t)
            if feat is not None:
                x[t] = feat.gather(node)
        y = None
        labels = self.data.node_labels
        if isinstance(labels, dict):
            y = {}
            for t, lab in labels.items():
                if t not in out.node:
                    continue
                if t not in self._labels_dev:
                    self._labels_dev[t] = jnp.asarray(np.asarray(lab))
                node = out.node[t]
                safe = jnp.clip(node, 0, self._labels_dev[t].shape[0] - 1)
                y[t] = jnp.where(node >= 0,
                                 jnp.take(self._labels_dev[t], safe, axis=0),
                                 PADDING_ID)
        return to_hetero_batch(out, x=x, y=y, batch_size=num_seeds)
