"""Sampled-batch container + assembly — the ``to_data`` analog.

Rebuild of the reference's ``loader/transform.py:25-104`` (``to_data`` /
``to_hetero_data``): there, sampler output + gathered features become a PyG
``Data``/``HeteroData``.  Here the product is :class:`Batch` — a registered
pytree with static shapes, ready to feed a jitted flax model: padded COO
``edge_index``, -1 sentinels, and explicit masks instead of ragged tensors.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..sampler.base import HeteroSamplerOutput, SamplerOutput
from ..typing import EdgeType, NodeType


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Batch:
    """One sampled ego-subgraph batch (the PyG ``Data`` analog).

    * ``x``: ``[max_nodes, d]`` features for ``node`` (zeros on padding).
    * ``y``: ``[max_nodes]`` labels (PADDING on padding rows).
    * ``edge_index``: ``[2, max_edges]`` local COO, direction dst<-src
      (row 0 = message source), -1 padded.
    * ``edge_id``: ``[max_edges]`` global edge ids.
    * ``node``: ``[max_nodes]`` global node ids; seeds occupy the first
      ``batch_size`` slots (loader contract, node_loader.py:85).
    * ``batch``: ``[batch_size]`` seed ids; ``batch_size`` is static.
    """
    x: Optional[jnp.ndarray]
    y: Optional[jnp.ndarray]
    edge_index: jnp.ndarray
    edge_id: Optional[jnp.ndarray]
    node: jnp.ndarray
    node_mask: jnp.ndarray
    edge_mask: jnp.ndarray
    batch: Optional[jnp.ndarray]
    batch_size: int = 0
    edge_attr: Optional[jnp.ndarray] = None
    metadata: Optional[Dict[str, Any]] = None

    @property
    def num_nodes(self) -> int:
        return int(self.node.shape[0])

    def tree_flatten(self):
        children = (self.x, self.y, self.edge_index, self.edge_id, self.node,
                    self.node_mask, self.edge_mask, self.batch,
                    self.edge_attr, self.metadata)
        return children, (self.batch_size,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (x, y, edge_index, edge_id, node, node_mask, edge_mask, batch,
         edge_attr, metadata) = children
        return cls(x, y, edge_index, edge_id, node, node_mask, edge_mask,
                   batch, aux[0], edge_attr, metadata)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class HeteroBatch:
    """Heterogeneous batch (the PyG ``HeteroData`` analog): per-type dicts."""
    x: Dict[NodeType, jnp.ndarray]
    y: Optional[Dict[NodeType, jnp.ndarray]]
    edge_index: Dict[EdgeType, jnp.ndarray]
    edge_id: Dict[EdgeType, jnp.ndarray]
    node: Dict[NodeType, jnp.ndarray]
    node_mask: Dict[NodeType, jnp.ndarray]
    edge_mask: Dict[EdgeType, jnp.ndarray]
    batch: Optional[Dict[NodeType, jnp.ndarray]]
    batch_size: int = 0
    input_type: Optional[NodeType] = None
    metadata: Optional[Dict[str, Any]] = None

    def tree_flatten(self):
        children = (self.x, self.y, self.edge_index, self.edge_id, self.node,
                    self.node_mask, self.edge_mask, self.batch, self.metadata)
        return children, (self.batch_size, self.input_type)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (x, y, edge_index, edge_id, node, node_mask, edge_mask, batch,
         metadata) = children
        return cls(x, y, edge_index, edge_id, node, node_mask, edge_mask,
                   batch, aux[0], aux[1], metadata)


def to_batch(
    out: SamplerOutput,
    x: Optional[jnp.ndarray] = None,
    y: Optional[jnp.ndarray] = None,
    batch_size: int = 0,
    edge_attr: Optional[jnp.ndarray] = None,
) -> Batch:
    """Assemble a :class:`Batch` from sampler output + gathered tensors.

    Edge direction: ``SamplerOutput.row`` is already the neighbor
    (message-source) side — the transpose happened in the sampler
    (neighbor_sampler.py:159-165) — so ``edge_index[0] = row``.
    """
    return Batch(
        x=x,
        y=y,
        edge_index=jnp.stack([out.row, out.col]),
        edge_id=out.edge,
        node=out.node,
        node_mask=out.node_mask,
        edge_mask=out.edge_mask,
        batch=out.batch,
        batch_size=batch_size,
        edge_attr=edge_attr,
        metadata=out.metadata,
    )


def as_pyg_v1_adjs(batch: Batch, batch_size: int, fanouts,
                   frontier_cap=None):
    """Layered PyG-v1-style output (cf. neighbor_sampler.py:383-407).

    Returns ``(batch_size, n_id, adjs)`` where ``adjs`` is one
    ``(edge_index, e_id, size)`` triple per hop, outermost hop first (the
    reversed order PyG v1 models consume).  Per-hop edges are contiguous
    segments of the batch's padded COO because the sampler concatenates
    hops in order.
    """
    from ..sampler.neighbor_sampler import hop_widths

    widths = hop_widths(batch_size, list(fanouts), frontier_cap)
    adjs = []
    lo = 0
    for w, f in zip(widths, fanouts):
        hi = lo + w * f
        adjs.append((batch.edge_index[:, lo:hi], batch.edge_id[lo:hi],
                     (batch.node.shape[0], batch.node.shape[0])))
        lo = hi
    return batch_size, batch.node, list(reversed(adjs))


def to_hetero_batch(
    out: HeteroSamplerOutput,
    x: Optional[Dict[NodeType, jnp.ndarray]] = None,
    y: Optional[Dict[NodeType, jnp.ndarray]] = None,
    batch_size: int = 0,
) -> HeteroBatch:
    edge_index = {et: jnp.stack([out.row[et], out.col[et]])
                  for et in out.row}
    return HeteroBatch(
        x=x or {}, y=y, edge_index=edge_index, edge_id=out.edge,
        node=out.node, node_mask=out.node_mask, edge_mask=out.edge_mask,
        batch=out.batch, batch_size=batch_size, input_type=out.input_type,
        metadata=out.metadata,
    )
