"""LinkLoader / LinkNeighborLoader — seed-edge loaders for link prediction.

Rebuild of ``loader/link_loader.py`` + ``loader/link_neighbor_loader.py``:
seed edges drive ``sample_from_edges`` with optional binary/triplet negative
sampling; the batch carries ``edge_label_index`` / ``edge_label`` (binary)
or ``src_index`` / ``dst_pos_index`` / ``dst_neg_index`` (triplet) metadata,
with the reference's label-increment semantics (link_loader.py:111-216).
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, Optional, Sequence

import numpy as np

from ..data.dataset import Dataset
from ..sampler.base import EdgeSamplerInput, NegativeSampling
from ..sampler.neighbor_sampler import NeighborSampler
from .node_loader import NodeLoader
from .transform import Batch, to_batch


class LinkLoader(NodeLoader):
    """Iterate seed-edge batches through ``sample_from_edges``.

    Args:
      edge_label_index: ``[2, num_edges]`` seed edges (global ids).
      edge_label: optional labels per seed edge.
      neg_sampling: :class:`NegativeSampling` spec or None.
    """

    def __init__(
        self,
        data: Dataset,
        link_sampler,
        edge_label_index: np.ndarray,
        edge_label: Optional[np.ndarray] = None,
        neg_sampling: Optional[NegativeSampling] = None,
        batch_size: int = 512,
        shuffle: bool = False,
        drop_last: bool = False,
        prefetch: int = 2,
        seed: int = 0,
    ):
        eli = np.asarray(edge_label_index)
        super().__init__(data, link_sampler, np.arange(eli.shape[1]),
                         batch_size=batch_size, shuffle=shuffle,
                         drop_last=drop_last, prefetch=prefetch, seed=seed)
        self.edge_label_index = eli
        self.edge_label = (None if edge_label is None
                           else np.asarray(edge_label))
        self.neg_sampling = neg_sampling

    def __iter__(self) -> Iterator[Batch]:
        pending = deque()
        batches = self._epoch_seed_batches()  # batches of edge positions
        while True:
            while len(pending) < self.prefetch:
                pos = next(batches, None)
                if pos is None:
                    break
                inp = EdgeSamplerInput(
                    row=self.edge_label_index[0, pos],
                    col=self.edge_label_index[1, pos],
                    label=None if self.edge_label is None
                    else self.edge_label[pos],
                    neg_sampling=self.neg_sampling)
                pending.append(
                    (self.sampler.sample_from_edges(inp), pos.shape[0]))
            if not pending:
                return
            out, npos = pending.popleft()
            yield self._collate_fn(out, npos)


class LinkNeighborLoader(LinkLoader):
    """Link loader with neighbor sampling (cf. link_neighbor_loader.py:27)."""

    def __init__(
        self,
        data: Dataset,
        num_neighbors: Sequence[int],
        edge_label_index: np.ndarray,
        edge_label: Optional[np.ndarray] = None,
        neg_sampling: Optional[NegativeSampling] = None,
        batch_size: int = 512,
        shuffle: bool = False,
        drop_last: bool = False,
        frontier_cap: Optional[int] = None,
        prefetch: int = 2,
        seed: int = 0,
    ):
        sampler = NeighborSampler(
            data.get_graph(), num_neighbors, batch_size=batch_size,
            frontier_cap=frontier_cap, seed=seed)
        super().__init__(data, sampler, edge_label_index,
                         edge_label=edge_label, neg_sampling=neg_sampling,
                         batch_size=batch_size, shuffle=shuffle,
                         drop_last=drop_last, prefetch=prefetch, seed=seed)
        self.num_neighbors = list(num_neighbors)
