"""NodeLoader / NeighborLoader — seed iteration + batch assembly.

Rebuild of ``loader/node_loader.py`` + ``loader/neighbor_loader.py``: the
reference wraps a torch ``DataLoader`` over seed ids and joins features +
labels in ``_collate_fn`` (node_loader.py:54-113).  Here the host loop is a
plain numpy batcher; sampling is one fused XLA program per batch and feature
gather is either in-graph (HBM-resident features) or a host stage (tiered).

Pipelining replaces the reference's producer processes: jax dispatch is
async, so the loader dispatches batch ``i+1``'s sampling before the caller
has consumed batch ``i`` (``prefetch`` depth), hiding sample latency behind
train-step compute the way GLT's shm-channel producers did.
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import Dataset
from ..obs import metrics as _metrics
from ..obs.trace import span as _span
from ..sampler.base import NodeSamplerInput
from ..sampler.neighbor_sampler import NeighborSampler
from ..typing import PADDING_ID
from .transform import Batch, to_batch

# Host-boundary instrumentation (docs/observability.md): the dispatch
# span measures async enqueue cost only — device completion is observed
# by the consumer's own sync, never forced here (a per-batch fence would
# serialize the prefetch pipeline this loader exists to keep full).
_M_BATCHES = _metrics.counter(
    "glt.loader.batches", "batches delivered by Node/NeighborLoader")
_M_OVERFLOW = _metrics.counter(
    "glt.loader.overflow_batches",
    "occupancy-capped batches re-sampled at full capacity")
_M_SAMPLE_MS = _metrics.histogram(
    "glt.loader.sample_dispatch_ms", "sampler dispatch wall per batch")
_M_COLLATE_MS = _metrics.histogram(
    "glt.loader.collate_ms", "feature/label collate dispatch per batch")


class NodeLoader:
    """Iterate seed-node batches through a sampler into :class:`Batch` es.

    Args:
      data: the :class:`~glt_tpu.data.dataset.Dataset`.
      node_sampler: any sampler exposing ``sample_from_nodes``.
      input_nodes: ``[num_seeds]`` global seed ids (host).
      batch_size: static batch width; the trailing partial batch is padded
        (never dropped) unless ``drop_last``.
      shuffle: reshuffle seeds each epoch.
      prefetch: how many sampled batches to keep in flight.
    """

    def __init__(
        self,
        data: Dataset,
        node_sampler,
        input_nodes: np.ndarray,
        batch_size: int = 512,
        shuffle: bool = False,
        drop_last: bool = False,
        prefetch: int = 2,
        seed: int = 0,
        overflow_fallback: bool = True,
    ):
        self.data = data
        self.sampler = node_sampler
        self.input_nodes = np.asarray(input_nodes).astype(np.int64)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.prefetch = max(1, int(prefetch))
        self._rng = np.random.default_rng(seed)
        self._labels_dev = None
        self._epoch = 0
        # Occupancy-capped samplers flag rare batches whose unique-node
        # count exceeds the static buffer; strict mode (default) re-runs
        # those through the exact full-capacity program.  Costs one
        # device->host scalar fetch per batch — free once the batch is
        # consumed anyway; set False to defer (flag rides in
        # batch.metadata, overflow edges are already masked).
        self.overflow_fallback = bool(overflow_fallback)
        self.overflow_batches = 0
        self._autotune_row_gather()
        self._autotune_sample()

    def _autotune_row_gather(self) -> None:
        """Warmup sweep of the row-gather kernel (XLA vs the tiled-DMA
        Pallas (tile_rows, ring_depth) grid) for this loader's gather
        shape, memoized per (row width, batch, dtype) —
        ``gather_rows(force='auto')`` then serves every ``_collate_fn``
        with the measured winner.  The probe is built at THIS sampler's
        ``node_capacity``, so an occupancy-capped loader sweeps its own
        (smaller) shape instead of inheriting a full-cap winner whose
        tile/padding choice may lose there (the BENCH_r05
        ``gather_ms_capped`` inversion).  No-op off TPU and for
        tiered/absent features (their gathers are host-side stages)."""
        feat = self.data.get_node_feature() if self.data is not None else None
        cap = getattr(self.sampler, "node_capacity", None)
        if (feat is None or cap is None
                or getattr(feat, "hot_count", 0) != getattr(feat, "size", -1)):
            return
        from ..ops.gather_pallas import autotune_gather_rows

        # Spread probe ids across the table: a constant index would hit
        # one cached row and flatter whichever path wins on latency.
        probe = jnp.arange(int(cap), dtype=jnp.int32) % max(feat.size, 1)
        autotune_gather_rows(feat.hot_rows, probe)

    def _autotune_sample(self) -> None:
        """Warmup sweep of the neighbor-sampling kernel (XLA vs the
        degree-binned Pallas (tile_rows, ring_depth, bin_edges) grid),
        one sweep per hop at that hop's **exact** frontier (width,
        fanout) — ``sample_neighbors(force='auto')`` inside the
        sampler's jitted programs then serves each hop with its measured
        winner.  Same exact-shape discipline as ``_autotune_row_gather``
        (a capped hop width is its own key, never the full-cap
        winner's).  No-op off TPU — ``autotune_sample`` pins 'xla'
        there, so CPU runs resolve the seam honestly — and for samplers
        without the hop-width protocol."""
        sampler = self.sampler
        graph = getattr(sampler, "graph", None)
        widths = getattr(sampler, "_widths", None)
        fanouts = getattr(sampler, "num_neighbors", None)
        if graph is None or widths is None or not fanouts:
            return
        if jax.default_backend() != "tpu":
            return
        from ..ops.sample_pallas import autotune_sample

        nn = max(int(graph.num_nodes), 1)
        for w, f in zip(widths, fanouts):
            # Probe seeds spread across the graph so per-bin occupancy
            # reflects the real degree distribution, not one hot row.
            probe = jnp.arange(int(w), dtype=jnp.int32) % nn
            autotune_sample(graph.indptr, graph.indices, probe, int(f),
                            edge_ids=graph.gather_edge_ids,
                            with_edge=getattr(sampler, "with_edge", True))

    def __len__(self) -> int:
        n = self.input_nodes.shape[0]
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    # -- state-capture protocol (glt_tpu.ckpt) -----------------------------
    def state_dict(self) -> dict:
        """Epoch cursor + shuffle-rng state, for durable checkpoints.

        Restoring this into a freshly constructed loader (same seeds,
        same config) makes its NEXT epoch's shuffle order identical to
        what the captured loader would have drawn — the loader half of
        the bit-identical-resume contract.  Covers every subclass
        (Neighbor/Link/LinkNeighbor ride the same ``_rng``/``_epoch``).
        """
        from ..ckpt.state import capture_rng

        return {
            "epoch": int(self._epoch),
            "rng": capture_rng(self._rng),
            "overflow_batches": int(self.overflow_batches),
        }

    def load_state_dict(self, state: dict) -> None:
        from ..ckpt.state import load_rng

        load_rng(self._rng, state["rng"])
        self._epoch = int(state["epoch"])
        self.overflow_batches = int(state.get("overflow_batches", 0))

    def _epoch_seed_batches(self) -> Iterator[np.ndarray]:
        ids = self.input_nodes
        if self.shuffle:
            ids = ids[self._rng.permutation(ids.shape[0])]
        n = ids.shape[0]
        end = (n // self.batch_size) * self.batch_size if self.drop_last else n
        for lo in range(0, end, self.batch_size):
            yield ids[lo: lo + self.batch_size]

    def __iter__(self) -> Iterator[Batch]:
        self._epoch += 1
        pending = deque()
        batches = self._epoch_seed_batches()
        feat = self.data.get_node_feature() if self.data is not None else None
        stage = getattr(feat, "stage_ahead", None)
        try:
            while True:
                while len(pending) < self.prefetch:
                    seeds = next(batches, None)
                    if seeds is None:
                        break
                    if stage is not None:
                        # Disk-tier hint (glt_tpu.store): seeds are
                        # host-side at dispatch, so this costs no device
                        # sync; the DRAM stager pulls their rows off
                        # disk while the batch sits in the prefetch
                        # queue.  No-op for DRAM-resident features.
                        stage(np.asarray(seeds))
                    with _span("loader.sample_dispatch"), \
                            _M_SAMPLE_MS.time():
                        out = self.sampler.sample_from_nodes(
                            NodeSamplerInput(seeds))
                    # Deferred-flag pattern (cf. run_scanned_epoch):
                    # start the flag's D2H copy at dispatch so the
                    # strict check at pop time resolves a transfer that
                    # overlapped the prefetch window instead of paying a
                    # blocking round trip per batch.
                    self._prime_overflow_flag(out)
                    pending.append((out, seeds.shape[0]))
                if not pending:
                    return
                out, nseeds = pending.popleft()
                out = self._maybe_refetch_overflow(out)
                with _span("loader.collate"), _M_COLLATE_MS.time():
                    batch = self._collate_fn(out, nseeds)
                _M_BATCHES.inc()
                yield batch
        finally:
            pending.clear()

    def _overflow_checked(self) -> bool:
        """Whether the strict overflow fallback is active for this loader."""
        return (self.overflow_fallback
                and bool(getattr(self.sampler, "capped", False)))

    def _prime_overflow_flag(self, out) -> None:
        """Async-fetch the overflow scalar of a freshly primed batch.

        ``copy_to_host_async`` enqueues the device->host copy behind the
        sample program; by the time the batch reaches the head of the
        prefetch queue the scalar has usually landed, so the pop-time
        check costs ~nothing when overflow never occurs (the blocking
        per-batch ``device_get`` round trip was ADVICE r5's finding).
        """
        if not self._overflow_checked() or not out.metadata:
            return
        flag = out.metadata.get("overflow")
        copy_async = getattr(flag, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:  # pragma: no cover - backend w/o async copy
                pass

    def _maybe_refetch_overflow(self, out):
        """Strict overflow fallback: re-sample a flagged batch through the
        sampler's full-capacity twin.

        Only the SEEDS are verbatim (``out.batch``): the full-capacity
        sibling draws with its own fresh RNG counter, so the refetched
        batch is a NEW neighbor draw at full capacity — not the uncapped
        replay of the flagged draw.  Fine for training (any exact draw
        is as good as another); don't expect deterministic reproduction
        of the flagged batch during eval/debugging.
        """
        if not self._overflow_checked() or not out.metadata:
            return out
        import jax

        if not bool(np.asarray(jax.device_get(out.metadata["overflow"]))):
            return out
        self.overflow_batches += 1
        _M_OVERFLOW.inc()
        return self.sampler.full_capacity_sibling().sample_from_nodes(
            NodeSamplerInput(out.batch))

    # -- collate (cf. node_loader.py:85 ``_collate_fn``) -------------------
    def _collate_fn(self, out, num_seeds: int) -> Batch:
        x = None
        feat = self.data.get_node_feature()
        if feat is not None:
            x = feat.gather(out.node)
        y = None
        labels = self.data.get_node_label()
        if labels is not None:
            if self._labels_dev is None:
                self._labels_dev = jnp.asarray(np.asarray(labels))
            safe = jnp.clip(out.node, 0, self._labels_dev.shape[0] - 1)
            y = jnp.where(out.node >= 0, jnp.take(self._labels_dev, safe,
                                                  axis=0), PADDING_ID)
        return to_batch(out, x=x, y=y, batch_size=num_seeds)


class NeighborLoader(NodeLoader):
    """Neighbor-sampling loader (cf. loader/neighbor_loader.py:27-105).

    Builds its own :class:`NeighborSampler` from ``num_neighbors`` when one
    isn't supplied.
    """

    def __init__(
        self,
        data: Dataset,
        num_neighbors: Sequence[int],
        input_nodes: np.ndarray,
        batch_size: int = 512,
        shuffle: bool = False,
        drop_last: bool = False,
        frontier_cap: Optional[int] = None,
        with_edge: bool = True,
        prefetch: int = 2,
        seed: int = 0,
        sampler: Optional[NeighborSampler] = None,
        as_pyg_v1: bool = False,
        last_hop_dedup: bool = True,
        node_capacity: Optional[int] = None,
        overflow_fallback: bool = True,
        sample_force: str = "auto",
    ):
        if sampler is None:
            sampler = NeighborSampler(
                data.get_graph(), num_neighbors, batch_size=batch_size,
                frontier_cap=frontier_cap, with_edge=with_edge, seed=seed,
                last_hop_dedup=last_hop_dedup, node_capacity=node_capacity,
                sample_force=sample_force)
        super().__init__(data, sampler, input_nodes, batch_size=batch_size,
                         shuffle=shuffle, drop_last=drop_last,
                         prefetch=prefetch, seed=seed,
                         overflow_fallback=overflow_fallback)
        self.num_neighbors = list(num_neighbors)
        self.frontier_cap = frontier_cap
        self.as_pyg_v1 = as_pyg_v1

    def __iter__(self):
        if not self.as_pyg_v1:
            yield from super().__iter__()
            return
        # Layered (batch_size, n_id, adjs) protocol
        # (cf. neighbor_loader.py as_pyg_v1 path).
        from .transform import as_pyg_v1_adjs

        for batch in super().__iter__():
            # widths derive from the loader's static batch width, not the
            # (possibly smaller) trailing batch's seed count
            yield as_pyg_v1_adjs(batch, self.batch_size,
                                 self.num_neighbors, self.frontier_cap)
