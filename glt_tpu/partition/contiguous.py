"""Bridge: arbitrary partition books -> contiguous arithmetic sharding.

The reference routes every id through a dense partition book at runtime
(dist_graph.py:88).  The TPU design keeps runtime routing **arithmetic**
(``owner = id // nodes_per_shard``, :mod:`glt_tpu.parallel.sharding`) by
relabeling ids offline so each partition owns one contiguous, equal-width
id range: partition ``p``'s nodes become ``[p * c, p * c + |p|)`` where
``c = max partition size`` (tail slots unused).  The relabeling maps are
returned for translating seeds/labels/features, after which
``shard_graph``/``shard_feature`` produce mesh-ready blocks whose shard ``s``
is exactly partition ``s``.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np

from ..data.topology import CSRTopo


class ContiguousRelabel(NamedTuple):
    old2new: np.ndarray       # [N_old] -> new id
    new2old: np.ndarray       # [num_parts * c] -> old id (-1 for unused)
    nodes_per_shard: int
    num_parts: int


def contiguous_relabel(node_pb: np.ndarray,
                       hotness: Optional[np.ndarray] = None,
                       num_parts: Optional[int] = None
                       ) -> ContiguousRelabel:
    """Build the relabeling for a dense node partition book.

    ``hotness`` (optional, ``[N]``) orders each partition's nodes
    hottest-first within its contiguous range, so a per-shard HBM prefix
    (:class:`~glt_tpu.parallel.dist_feature.TieredShardedFeature`) covers
    the most-accessed rows.  This is the static-shape translation of the
    reference's ``cat_feature_cache`` (partition/base.py:606-647): with
    fixed-shape all-to-all exchanges, replicating remote-hot rows locally
    cannot reduce collective bytes, so hotness instead decides which rows
    live in HBM vs host DRAM.
    """
    node_pb = np.asarray(node_pb)
    n = node_pb.shape[0]
    if num_parts is None:
        # Derived from the book when not given; pass it explicitly when
        # trailing partitions may be empty.
        num_parts = int(node_pb.max()) + 1
    counts = np.bincount(node_pb, minlength=num_parts)
    c = int(counts.max())

    old2new = np.empty(n, np.int64)
    new2old = np.full(num_parts * c, -1, np.int64)
    for p in range(num_parts):
        own = np.where(node_pb == p)[0]
        if hotness is not None:
            own = own[np.argsort(-np.asarray(hotness)[own],
                                 kind="stable")]
        old2new[own] = p * c + np.arange(own.shape[0])
        new2old[p * c: p * c + own.shape[0]] = own
    return ContiguousRelabel(old2new, new2old, c, num_parts)


def relabel_topology(topo: CSRTopo, rel: ContiguousRelabel) -> CSRTopo:
    """Relabel a topology's node ids; edge ids are preserved."""
    src, dst = topo.to_coo()
    new_n = rel.num_parts * rel.nodes_per_shard
    return CSRTopo(
        np.stack([rel.old2new[src], rel.old2new[dst]]),
        edge_ids=topo.edge_ids, num_nodes=new_n)


def relabel_rows(rows: np.ndarray, rel: ContiguousRelabel,
                 fill=0) -> np.ndarray:
    """Reorder a per-old-node row array into new-id order (padded)."""
    rows = np.asarray(rows)
    out_shape = (rel.num_parts * rel.nodes_per_shard,) + rows.shape[1:]
    out = np.full(out_shape, fill, rows.dtype)
    valid = rel.new2old >= 0
    out[valid] = rows[rel.new2old[valid]]
    return out
