"""Hotness-aware frequency partitioner.

Rebuild of ``partition/frequency_partitioner.py``: each training rank
supplies a per-node access-probability vector (from
``NeighborSampler.sample_prob`` over its seed set); node chunks are greedily
assigned to the partition where they are hottest relative to the others
(``_get_chunk_probs_sum`` / ``_partition_node``, frequency_partitioner.py:
96-170), under a balance cap; each partition then hot-caches the most
frequently accessed *remote* nodes under a cache budget (``_cache_node``,
:171+).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .base import PartitionerBase


def residency_scores(probs: Sequence[np.ndarray],
                     normalize: bool = True) -> np.ndarray:
    """Collapse per-partition access-probability vectors into one global
    ``[num_nodes]`` float64 hotness score — the prefetch oracle for the
    disk tier's DRAM stager (:meth:`glt_tpu.store.stager.DramStager.warm`).

    The same ``sample_prob`` statistics that drive hotness-aware
    partitioning rank which rows deserve DRAM residency: a node's score
    is its access probability summed over every rank that touches it.
    With ``normalize`` the result is scaled to a max of 1.0 so budgets
    and thresholds compare across graphs.
    """
    if not probs:
        raise ValueError("residency_scores: need at least one "
                         "probability vector")
    score = np.zeros_like(np.asarray(probs[0], np.float64))
    for p in probs:
        p = np.asarray(p, np.float64)
        if p.shape != score.shape:
            raise ValueError(
                f"residency_scores: shape mismatch {p.shape} vs "
                f"{score.shape}")
        score += p
    if normalize:
        peak = score.max()
        if peak > 0:
            score /= peak
    return score


class FrequencyPartitioner(PartitionerBase):
    """Args beyond :class:`PartitionerBase`:

    probs: per-partition ``[num_nodes]`` access-probability vectors (one
      per training rank, ``len(probs) == num_parts``).
    cache_ratio: fraction of nodes each partition may hot-cache.
    balance_cap: max fraction above perfect balance a partition may own.
    """

    def __init__(self, *args, probs: Sequence[np.ndarray],
                 cache_ratio: float = 0.0, balance_cap: float = 1.05,
                 **kwargs):
        super().__init__(*args, **kwargs)
        assert len(probs) == self.num_parts, \
            "need one probability vector per partition"
        self.probs = [np.asarray(p, np.float64) for p in probs]
        self.cache_ratio = float(cache_ratio)
        self.balance_cap = float(balance_cap)

    def _partition_node(self) -> np.ndarray:
        n, k = self.num_nodes, self.num_parts
        cap = int(np.ceil(n / k * self.balance_cap))
        node_pb = np.full(n, -1, np.int32)
        counts = np.zeros(k, np.int64)

        for lo in range(0, n, self.chunk_size):
            hi = min(lo + self.chunk_size, n)
            # score[p] = own hotness * k - everyone's hotness
            # (frequency_partitioner.py:96-120)
            chunk_probs = np.stack([p[lo:hi].sum() for p in self.probs])
            score = chunk_probs * k - chunk_probs.sum()
            order = np.argsort(-score)
            for p in order:
                if counts[p] + (hi - lo) <= cap:
                    node_pb[lo:hi] = p
                    counts[p] += hi - lo
                    break
            else:  # all at cap: least-loaded
                p = int(np.argmin(counts))
                node_pb[lo:hi] = p
                counts[p] += hi - lo
        return node_pb

    def _cache_node(self, node_pb: np.ndarray) -> List[np.ndarray]:
        budget = int(self.num_nodes * self.cache_ratio)
        out = []
        for p in range(self.num_parts):
            if budget == 0:
                out.append(np.empty(0, np.int64))
                continue
            prob = self.probs[p].copy()
            prob[node_pb == p] = -1.0  # only remote nodes are worth caching
            hot = np.argsort(-prob)[:budget]
            out.append(hot[prob[hot] > 0].astype(np.int64))
        return out
