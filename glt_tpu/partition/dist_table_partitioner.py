"""Distributed partitioning fed directly from table readers.

Rebuild of the reference's ``DistTableRandomPartitioner``
(``distributed/dist_table_dataset.py:38-147``): there, each rank reads its
slice of an ODPS edge/node table and pushes rows to owner ranks over RPC.
Here each rank drains its table slice through the same reader protocol as
:class:`~glt_tpu.data.table_dataset.TableDataset` (``common_io``-compatible
``read``/``close``; any factory works) and spills rows per owner through
the filesystem — the :class:`DistRandomPartitioner` flow, which replaces
the reference's RPC ``DistPartitionManager`` with stateless hash ownership
plus shared-filesystem merge.

Usage (one call per rank, then one ``finalize``)::

    p = DistTableRandomPartitioner(out_dir, num_parts=4,
                                   num_nodes=n, num_edges=e)
    p.partition_rank_tables(rank, edge_table="odps://.../edges_slice_r",
                            node_table="odps://.../nodes_slice_r",
                            edge_id_offset=rank_edge_offset,
                            reader_factory=my_reader)
    ...
    p.finalize()

Record formats match ``TableDataset.from_tables`` exactly: edge tables
yield ``(src_id, dst_id)``; node tables yield ``(id, "f1:f2:...:fd")``.
Global edge ids are ``edge_id_offset + position`` within the rank's slice
(the reference likewise derives ids from per-rank offsets).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..data.table_dataset import (
    drain_table,
    parse_feature_field,
    resolve_reader_factory,
)
from .dist_random_partitioner import DistRandomPartitioner


class DistTableRandomPartitioner(DistRandomPartitioner):
    """Per-rank, table-fed distributed random partitioner."""

    def partition_rank_tables(
        self,
        rank: int,
        edge_table,
        node_table=None,
        reader_factory=None,
        edge_id_offset: int = 0,
        reader_batch_size: int = 1024,
    ) -> int:
        """Drain this rank's table slices and spill per-owner rows.

        Returns the number of edges read (so callers can chain
        ``edge_id_offset`` across ranks when slice sizes aren't known
        upfront).  Labels are not partitioned — like the reference, label
        lookup stays a whole-array load at ``DistDataset.load`` time.
        """
        factory, oor = resolve_reader_factory(reader_factory)
        edge_recs = drain_table(edge_table, factory, oor, reader_batch_size)
        edge_index = np.stack([
            np.array([r[0] for r in edge_recs], dtype=np.int64),
            np.array([r[1] for r in edge_recs], dtype=np.int64)])
        edge_ids = edge_id_offset + np.arange(len(edge_recs), dtype=np.int64)

        node_ids: Optional[np.ndarray] = None
        node_feat: Optional[np.ndarray] = None
        if node_table is not None:
            node_recs = drain_table(node_table, factory, oor,
                                    reader_batch_size)
            # An empty slice must not spill: np.asarray([]) is 1-D and
            # would break finalize's (k, d) feature concatenation.
            if node_recs:
                node_ids = np.array([r[0] for r in node_recs],
                                    dtype=np.int64)
                node_feat = np.asarray(
                    [parse_feature_field(r[1]) for r in node_recs],
                    np.float32)

        self.partition_rank_chunk(rank, edge_index, edge_ids,
                                  node_ids=node_ids, node_feat=node_feat)
        return len(edge_recs)
