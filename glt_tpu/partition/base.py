"""Offline graph partitioning: orchestration + on-disk layout.

Rebuild of the reference's ``partition/base.py``: ``PartitionerBase``
orchestrates node -> node-feature -> graph -> edge-feature partitioning and
writes a per-partition directory tree (base.py:120-456; layout documented at
:337-412).  Differences for the TPU build: artifacts are ``.npy`` (numpy)
instead of ``torch.save``; the layout is otherwise the same in spirit:

    <root>/
      META.json                  {num_parts, num_nodes, num_edges, ...}
      node_pb.npy                dense node -> partition book
      edge_pb.npy                dense edge -> partition book
      node_feat_pb.npy           feature ownership (differs from node_pb
                                 when hot rows are cached, base.py:606-647)
      part{i}/graph/{rows,cols,eids}.npy
      part{i}/node_feat/{feats,ids}.npy [+ cache_feats, cache_ids]
      part{i}/edge_feat/{feats,ids}.npy
"""
from __future__ import annotations

import json
import os
from abc import ABC, abstractmethod
from typing import List, Optional, Tuple

import numpy as np

from ..typing import FeaturePartitionData, GraphPartitionData


class PartitionerBase(ABC):
    """Orchestrates a full partition pass (cf. partition/base.py:120).

    Args:
      output_dir: root directory for the on-disk layout.
      num_parts: number of partitions.
      num_nodes / num_edges: global counts.
      edge_index: ``[2, E]`` COO (row=src, col=dst).
      edge_ids: ``[E]`` global edge ids (default positions).
      node_feat / edge_feat: optional feature matrices.
      edge_assign_strategy: 'by_src' or 'by_dst' (base.py:218-290).
      chunk_size: nodes per assignment chunk.
    """

    def __init__(
        self,
        output_dir: str,
        num_parts: int,
        num_nodes: int,
        edge_index: np.ndarray,
        edge_ids: Optional[np.ndarray] = None,
        node_feat: Optional[np.ndarray] = None,
        edge_feat: Optional[np.ndarray] = None,
        edge_assign_strategy: str = "by_src",
        chunk_size: int = 10000,
    ):
        self.output_dir = output_dir
        self.num_parts = int(num_parts)
        self.num_nodes = int(num_nodes)
        self.edge_index = np.asarray(edge_index)
        self.num_edges = int(self.edge_index.shape[1])
        self.edge_ids = (np.arange(self.num_edges, dtype=np.int64)
                         if edge_ids is None else np.asarray(edge_ids))
        self.node_feat = None if node_feat is None else np.asarray(node_feat)
        self.edge_feat = None if edge_feat is None else np.asarray(edge_feat)
        assert edge_assign_strategy in ("by_src", "by_dst")
        self.edge_assign_strategy = edge_assign_strategy
        self.chunk_size = int(chunk_size)

    # -- node assignment (subclass strategy) -------------------------------
    @abstractmethod
    def _partition_node(self) -> np.ndarray:
        """Return the dense node partition book ``[num_nodes] -> part``."""
        raise NotImplementedError

    def _cache_node(self, node_pb: np.ndarray) -> List[np.ndarray]:
        """Per-partition ids of *remote* nodes to hot-cache (default none)."""
        return [np.empty(0, np.int64) for _ in range(self.num_parts)]

    # -- orchestration (cf. base.py:120-456) ------------------------------
    def partition(self) -> None:
        node_pb = self._partition_node().astype(np.int32)

        # Edges follow their src (or dst) endpoint's partition.
        anchor = (self.edge_index[0] if self.edge_assign_strategy == "by_src"
                  else self.edge_index[1])
        edge_pb = node_pb[anchor].astype(np.int32)

        cache_ids = self._cache_node(node_pb)
        # Feature partition book starts as node_pb; cached rows stay owned
        # by their partition but are *also* resolvable locally at loaders
        # via cat_feature_cache (base.py:606-647).
        node_feat_pb = node_pb.copy()

        os.makedirs(self.output_dir, exist_ok=True)
        np.save(os.path.join(self.output_dir, "node_pb.npy"), node_pb)
        np.save(os.path.join(self.output_dir, "edge_pb.npy"), edge_pb)
        np.save(os.path.join(self.output_dir, "node_feat_pb.npy"),
                node_feat_pb)
        # META.json is the partition set's read gate (loaders open it
        # first): publish atomically so a loader racing the partitioner
        # sees either no partition set or a complete one (GLT011).
        meta_path = os.path.join(self.output_dir, "META.json")
        meta_tmp = f"{meta_path}.tmp-{os.getpid()}"
        with open(meta_tmp, "w") as fh:
            json.dump({
                "num_parts": self.num_parts,
                "num_nodes": self.num_nodes,
                "num_edges": self.num_edges,
                "edge_assign_strategy": self.edge_assign_strategy,
                "with_node_feat": self.node_feat is not None,
                "with_edge_feat": self.edge_feat is not None,
            }, fh)
        os.replace(meta_tmp, meta_path)

        for p in range(self.num_parts):
            pdir = os.path.join(self.output_dir, f"part{p}")
            gdir = os.path.join(pdir, "graph")
            os.makedirs(gdir, exist_ok=True)
            emask = edge_pb == p
            np.save(os.path.join(gdir, "rows.npy"), self.edge_index[0][emask])
            np.save(os.path.join(gdir, "cols.npy"), self.edge_index[1][emask])
            np.save(os.path.join(gdir, "eids.npy"), self.edge_ids[emask])

            if self.node_feat is not None:
                fdir = os.path.join(pdir, "node_feat")
                os.makedirs(fdir, exist_ok=True)
                own = np.where(node_pb == p)[0]
                np.save(os.path.join(fdir, "ids.npy"), own)
                np.save(os.path.join(fdir, "feats.npy"), self.node_feat[own])
                np.save(os.path.join(fdir, "cache_ids.npy"), cache_ids[p])
                np.save(os.path.join(fdir, "cache_feats.npy"),
                        self.node_feat[cache_ids[p].astype(np.int64)])

            if self.edge_feat is not None:
                fdir = os.path.join(pdir, "edge_feat")
                os.makedirs(fdir, exist_ok=True)
                np.save(os.path.join(fdir, "ids.npy"), self.edge_ids[emask])
                np.save(os.path.join(fdir, "feats.npy"),
                        self.edge_feat[emask])


def load_partition(root: str, part_idx: int):
    """Load one partition (cf. base.py:502-603).

    Returns ``(graph, node_feat, edge_feat, node_pb, edge_pb, meta)`` where
    ``graph`` is a :class:`GraphPartitionData` and features are
    :class:`FeaturePartitionData` or None.
    """
    with open(os.path.join(root, "META.json")) as fh:
        meta = json.load(fh)
    node_pb = np.load(os.path.join(root, "node_pb.npy"))
    edge_pb = np.load(os.path.join(root, "edge_pb.npy"))
    pdir = os.path.join(root, f"part{part_idx}")

    gdir = os.path.join(pdir, "graph")
    graph = GraphPartitionData(
        edge_index=np.stack([np.load(os.path.join(gdir, "rows.npy")),
                             np.load(os.path.join(gdir, "cols.npy"))]),
        eids=np.load(os.path.join(gdir, "eids.npy")))

    node_feat = None
    fdir = os.path.join(pdir, "node_feat")
    if meta["with_node_feat"] and os.path.isdir(fdir):
        node_feat = FeaturePartitionData(
            feats=np.load(os.path.join(fdir, "feats.npy")),
            ids=np.load(os.path.join(fdir, "ids.npy")),
            cache_feats=np.load(os.path.join(fdir, "cache_feats.npy")),
            cache_ids=np.load(os.path.join(fdir, "cache_ids.npy")))

    edge_feat = None
    fdir = os.path.join(pdir, "edge_feat")
    if meta["with_edge_feat"] and os.path.isdir(fdir):
        edge_feat = FeaturePartitionData(
            feats=np.load(os.path.join(fdir, "feats.npy")),
            ids=np.load(os.path.join(fdir, "ids.npy")))

    return graph, node_feat, edge_feat, node_pb, edge_pb, meta


def cat_feature_cache(part_feat: FeaturePartitionData,
                      num_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
    """Merge hot-cache rows in front of owned rows (cf. base.py:606-647).

    Returns ``(feats, id2index)``: cache rows first (so a hotness-ordered
    ``split_ratio`` prefix covers them), then owned rows; ``id2index`` maps
    global id -> local row (-1 when not locally resolvable), replacing the
    reference's rewritten feature partition book.
    """
    if part_feat.cache_ids is None or part_feat.cache_ids.size == 0:
        feats = part_feat.feats
        ids = part_feat.ids
    else:
        feats = np.concatenate([part_feat.cache_feats, part_feat.feats])
        ids = np.concatenate([part_feat.cache_ids, part_feat.ids])
    id2index = np.full(num_nodes, -1, np.int64)
    # later (owned) rows win over cache duplicates
    id2index[ids] = np.arange(ids.shape[0])
    return feats, id2index
