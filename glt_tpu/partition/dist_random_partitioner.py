"""Distributed random partitioning: per-rank chunks, no global view.

Rebuild of ``distributed/dist_random_partitioner.py:60-538``: the reference
has every rank partition its own slice of nodes/edges/features and RPC-push
rows to their owner's ``DistPartitionManager``.  The TPU-host redesign
removes the RPC mesh: ownership is a **seeded stateless hash** every rank
computes identically (no partition-book exchange needed), and rows move
through the filesystem — each rank writes per-partition spill files for its
chunk, and ``finalize`` concatenates them into the standard on-disk layout
of :mod:`glt_tpu.partition.base`.  Ranks can be processes on one host or
jobs on a shared filesystem; nothing needs to fit in one memory.
"""
from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

_MIX = np.uint64(0x9E3779B97F4A7C15)


def hash_partition(ids: np.ndarray, num_parts: int, seed: int) -> np.ndarray:
    """Stateless balanced-ish owner assignment (splitmix-style mixer)."""
    with np.errstate(over="ignore"):  # uint64 wraparound is the point
        x = ids.astype(np.uint64) + np.uint64(seed) * _MIX
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
        return (x % np.uint64(num_parts)).astype(np.int32)


class DistRandomPartitioner:
    """Args:
      output_dir: shared output root.
      num_parts: number of partitions.
      num_nodes / num_edges: global counts.
      seed: hash seed — must match across ranks.
    """

    def __init__(self, output_dir: str, num_parts: int, num_nodes: int,
                 num_edges: int, seed: int = 0,
                 edge_assign_strategy: str = "by_src"):
        self.output_dir = output_dir
        self.num_parts = int(num_parts)
        self.num_nodes = int(num_nodes)
        self.num_edges = int(num_edges)
        self.seed = int(seed)
        assert edge_assign_strategy in ("by_src", "by_dst")
        self.edge_assign_strategy = edge_assign_strategy

    def _spill_dir(self, rank: int) -> str:
        d = os.path.join(self.output_dir, f"_spill_rank{rank}")
        os.makedirs(d, exist_ok=True)
        return d

    # -- per-rank work (cf. DistRandomPartitioner.partition, :129-538) -----
    def partition_rank_chunk(
        self,
        rank: int,
        edge_index: np.ndarray,          # [2, e_chunk] global ids
        edge_ids: np.ndarray,            # [e_chunk]
        node_ids: Optional[np.ndarray] = None,   # ids of feat rows held here
        node_feat: Optional[np.ndarray] = None,  # [n_chunk, d]
    ) -> None:
        d = self._spill_dir(rank)
        anchor = (edge_index[0] if self.edge_assign_strategy == "by_src"
                  else edge_index[1])
        e_owner = hash_partition(np.asarray(anchor), self.num_parts,
                                 self.seed)
        for p in range(self.num_parts):
            m = e_owner == p
            np.savez(os.path.join(d, f"edges_p{p}.npz"),
                     rows=edge_index[0][m], cols=edge_index[1][m],
                     eids=np.asarray(edge_ids)[m])
        if node_feat is not None:
            n_owner = hash_partition(np.asarray(node_ids), self.num_parts,
                                     self.seed)
            for p in range(self.num_parts):
                m = n_owner == p
                np.savez(os.path.join(d, f"nodes_p{p}.npz"),
                         ids=np.asarray(node_ids)[m],
                         feats=np.asarray(node_feat)[m])

    # -- merge (the reference's owner-side accumulate, :129-260) -----------
    def finalize(self, with_node_feat: bool = True) -> None:
        node_pb = hash_partition(np.arange(self.num_nodes), self.num_parts,
                                 self.seed)
        os.makedirs(self.output_dir, exist_ok=True)
        np.save(os.path.join(self.output_dir, "node_pb.npy"), node_pb)

        ranks = sorted(
            int(d[len("_spill_rank"):]) for d in os.listdir(self.output_dir)
            if d.startswith("_spill_rank"))
        # -1 marks "no rank spilled this edge" so coverage gaps fail loudly
        # instead of silently landing every missing edge in partition 0.
        edge_pb = np.full(self.num_edges, -1, np.int32)
        for p in range(self.num_parts):
            rows, cols, eids, ids, feats = [], [], [], [], []
            for r in ranks:
                d = self._spill_dir(r)
                ef = os.path.join(d, f"edges_p{p}.npz")
                if os.path.exists(ef):
                    z = np.load(ef)
                    rows.append(z["rows"])
                    cols.append(z["cols"])
                    eids.append(z["eids"])
                nf = os.path.join(d, f"nodes_p{p}.npz")
                if with_node_feat and os.path.exists(nf):
                    z = np.load(nf)
                    ids.append(z["ids"])
                    feats.append(z["feats"])
            pdir = os.path.join(self.output_dir, f"part{p}", "graph")
            os.makedirs(pdir, exist_ok=True)
            cat = lambda xs: (np.concatenate(xs) if xs
                              else np.empty(0, np.int64))
            all_eids = cat(eids)
            np.save(os.path.join(pdir, "rows.npy"), cat(rows))
            np.save(os.path.join(pdir, "cols.npy"), cat(cols))
            np.save(os.path.join(pdir, "eids.npy"), all_eids)
            edge_pb[all_eids.astype(np.int64)] = p
            if with_node_feat and ids:
                fdir = os.path.join(self.output_dir, f"part{p}", "node_feat")
                os.makedirs(fdir, exist_ok=True)
                np.save(os.path.join(fdir, "ids.npy"), np.concatenate(ids))
                np.save(os.path.join(fdir, "feats.npy"),
                        np.concatenate(feats))
                np.save(os.path.join(fdir, "cache_ids.npy"),
                        np.empty(0, np.int64))
                np.save(os.path.join(fdir, "cache_feats.npy"),
                        np.empty((0,) + feats[0].shape[1:],
                                 feats[0].dtype))
        unassigned = int(np.count_nonzero(edge_pb < 0))
        if unassigned:
            raise RuntimeError(
                f"{unassigned} of {self.num_edges} edge ids were not "
                f"covered by any rank's spill files; every rank must call "
                f"partition_rank_chunk before finalize")
        np.save(os.path.join(self.output_dir, "edge_pb.npy"), edge_pb)
        np.save(os.path.join(self.output_dir, "node_feat_pb.npy"), node_pb)
        # Atomic META publish, matching partition/base.py (GLT011): the
        # META write is the "partition set complete" commit point.
        meta_path = os.path.join(self.output_dir, "META.json")
        meta_tmp = f"{meta_path}.tmp-{os.getpid()}"
        with open(meta_tmp, "w") as fh:
            json.dump({
                "num_parts": self.num_parts,
                "num_nodes": self.num_nodes,
                "num_edges": self.num_edges,
                "edge_assign_strategy": self.edge_assign_strategy,
                "with_node_feat": with_node_feat,
                "with_edge_feat": False,
            }, fh)
        os.replace(meta_tmp, meta_path)
        # clean spill dirs
        for r in ranks:
            d = self._spill_dir(r)
            for f in os.listdir(d):
                os.remove(os.path.join(d, f))
            os.rmdir(d)
