"""Random node partitioner (cf. partition/random_partitioner.py:28-85)."""
from __future__ import annotations

import numpy as np

from .base import PartitionerBase


class RandomPartitioner(PartitionerBase):
    """Uniform random balanced assignment: shuffled ids round-robin."""

    def __init__(self, *args, seed: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.seed = seed

    def _partition_node(self) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(self.num_nodes)
        node_pb = np.empty(self.num_nodes, np.int32)
        node_pb[perm] = np.arange(self.num_nodes) % self.num_parts
        return node_pb
