from .base import PartitionerBase, cat_feature_cache, load_partition
from .contiguous import (
    ContiguousRelabel,
    contiguous_relabel,
    relabel_rows,
    relabel_topology,
)
from .frequency_partitioner import FrequencyPartitioner
from .random_partitioner import RandomPartitioner

__all__ = [
    "ContiguousRelabel",
    "FrequencyPartitioner",
    "PartitionerBase",
    "RandomPartitioner",
    "cat_feature_cache",
    "contiguous_relabel",
    "load_partition",
    "relabel_rows",
    "relabel_topology",
]
