from .base import PartitionerBase, cat_feature_cache, load_partition
from .contiguous import (
    ContiguousRelabel,
    contiguous_relabel,
    relabel_rows,
    relabel_topology,
)
from .dist_random_partitioner import DistRandomPartitioner, hash_partition
from .dist_table_partitioner import DistTableRandomPartitioner
from .frequency_partitioner import FrequencyPartitioner, residency_scores
from .random_partitioner import RandomPartitioner

__all__ = [
    "ContiguousRelabel",
    "DistRandomPartitioner",
    "DistTableRandomPartitioner",
    "FrequencyPartitioner",
    "PartitionerBase",
    "RandomPartitioner",
    "cat_feature_cache",
    "contiguous_relabel",
    "hash_partition",
    "load_partition",
    "relabel_rows",
    "relabel_topology",
    "residency_scores",
]
