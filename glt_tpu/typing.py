"""Core type aliases and small typed containers.

TPU-native rebuild of the reference's ``graphlearn_torch/python/typing.py``
(node/edge type aliases, reverse-edge convention, partition-book types).
Arrays are JAX/numpy instead of torch tensors.
"""
from __future__ import annotations

import enum
from typing import Dict, List, NamedTuple, Optional, Tuple, Union

import numpy as np

# A node type is a plain string; an edge type is a (src_type, relation,
# dst_type) triple — same convention as the reference (typing.py).
NodeType = str
EdgeType = Tuple[str, str, str]

# Dense id -> partition-number map (int8/int32 vector of length num_nodes or
# num_edges). Mirrors ``PartitionBook = torch.Tensor`` in the reference.
PartitionBook = np.ndarray

# Per-hop fanout specification: [15, 10, 5] or {edge_type: [15, 10]}.
NumNeighbors = Union[List[int], Dict[EdgeType, List[int]]]

# Sentinel id used to pad static-shape id arrays on device.  All kernels and
# ops in this library treat negative ids as "absent".
PADDING_ID = -1

_REVERSE_PREFIX = "rev_"


def as_str(type_: Union[NodeType, EdgeType]) -> str:
    """Canonical string form of a node or edge type."""
    if isinstance(type_, NodeType):
        return type_
    if isinstance(type_, (tuple, list)) and len(type_) == 3:
        return "__".join(type_)
    raise ValueError(f"invalid graph type: {type_!r}")


def edge_type_from_str(s: str) -> EdgeType:
    parts = tuple(s.split("__"))
    if len(parts) != 3:
        raise ValueError(f"not an edge-type string: {s!r}")
    return parts  # type: ignore[return-value]


def reverse_edge_type(etype: EdgeType) -> EdgeType:
    """Reverse an edge type using the reference's ``rev_`` prefix convention."""
    src, rel, dst = etype
    if src != dst:
        if rel.startswith(_REVERSE_PREFIX):
            rel = rel[len(_REVERSE_PREFIX):]
        else:
            rel = _REVERSE_PREFIX + rel
    return (dst, rel, src)


class GraphPartitionData(NamedTuple):
    """One partition's topology: COO edge index + global edge ids."""
    edge_index: np.ndarray  # [2, E] global node ids (row=src, col=dst)
    eids: np.ndarray        # [E] global edge ids
    weights: Optional[np.ndarray] = None


class FeaturePartitionData(NamedTuple):
    """One partition's features: rows + the global ids they belong to."""
    feats: np.ndarray            # [n, d]
    ids: np.ndarray              # [n] global ids
    cache_feats: Optional[np.ndarray] = None
    cache_ids: Optional[np.ndarray] = None


class SamplingType(enum.Enum):
    NODE = 0
    LINK = 1
    SUBGRAPH = 2
    RANDOM_WALK = 3
