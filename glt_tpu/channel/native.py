"""ctypes bindings + build for the native shm queue (csrc/shm_queue.cc)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libglt_shm.so")
_LOCK = threading.Lock()
_LIB = None


# Installed-package location (built by setup.py's BuildWithNative).
_PKG_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "libglt_shm.so")


def ensure_built() -> str:
    src = os.path.join(_CSRC, "shm_queue.cc")
    if not os.path.exists(src):
        # Installed package: csrc isn't shipped; use the wheel-built lib.
        if os.path.exists(_PKG_SO):
            return _PKG_SO
        raise RuntimeError("libglt_shm.so not found; reinstall glt-tpu or "
                           "run from a source checkout")
    if (not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(src)):
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        # Build to a private temp path and publish with an atomic rename:
        # concurrent builders (threads that both saw a stale .so, or two
        # processes sharing the checkout) each publish a complete library
        # instead of interleaving writes into one corrupt file — which is
        # also what lets lib() run this seconds-long g++ wait OUTSIDE its
        # lock (gltlint GLT009).
        tmp = f"{_SO}.{os.getpid()}.tmp"
        try:
            subprocess.run(
                ["g++", "-O2", "-fPIC", "-shared", "-pthread",
                 "-std=c++17", src, "-o", tmp, "-lrt"],
                check=True, capture_output=True)
            os.replace(tmp, _SO)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return _SO


def lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:      # fast path: no lock once loaded (GIL-safe)
        return _LIB
    # The blocking part (a possible g++ build) runs before the lock is
    # taken; ensure_built() is safe to race because it publishes
    # atomically.  The lock only serializes the cheap CDLL load +
    # prototype setup so _LIB is initialized exactly once.
    so_path = ensure_built()
    with _LOCK:
        if _LIB is None:
            L = ctypes.CDLL(so_path)
            L.glt_shmq_create.restype = ctypes.c_void_p
            L.glt_shmq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            L.glt_shmq_attach.restype = ctypes.c_void_p
            L.glt_shmq_attach.argtypes = [ctypes.c_char_p]
            L.glt_shmq_enqueue.restype = ctypes.c_int
            L.glt_shmq_enqueue.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_uint64]
            L.glt_shmq_next_size.restype = ctypes.c_uint64
            L.glt_shmq_next_size.argtypes = [ctypes.c_void_p]
            L.glt_shmq_dequeue.restype = ctypes.c_int64
            L.glt_shmq_dequeue.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_uint64]
            L.glt_shmq_msg_count.restype = ctypes.c_uint64
            L.glt_shmq_msg_count.argtypes = [ctypes.c_void_p]
            L.glt_shmq_dequeue_alloc.restype = ctypes.c_int
            L.glt_shmq_dequeue_alloc.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
            L.glt_shmq_buf_free.restype = None
            L.glt_shmq_buf_free.argtypes = [
                ctypes.POINTER(ctypes.c_uint8)]
            L.glt_shmq_close.restype = None
            L.glt_shmq_close.argtypes = [ctypes.c_void_p]
            L.glt_shmq_unlink.restype = ctypes.c_int
            L.glt_shmq_unlink.argtypes = [ctypes.c_char_p]
            _LIB = L
    return _LIB
