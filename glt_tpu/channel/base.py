"""Channel interface (cf. graphlearn_torch/python/channel/base.py).

A ``SampleMessage`` is a flat ``Dict[str, np.ndarray]``; channels move them
between the sampling producer and the trainer.
"""
from __future__ import annotations

import queue
import threading
from abc import ABC, abstractmethod
from typing import Callable, Dict, Optional

import numpy as np

SampleMessage = Dict[str, np.ndarray]


class QueueSourceDied(RuntimeError):
    """The producer feeding a queue died with the consumer still waiting.

    Raised by :func:`bounded_get` when its liveness probe turns false and a
    final drain finds the queue empty — the bounded replacement for the
    block-forever ``q.get()`` hang (gltlint GLT007).
    """


def bounded_get(q: "queue.Queue",
                alive: Optional[Callable[[], bool]] = None,
                poll: float = 0.5,
                on_wait: Optional[Callable[[], None]] = None):
    """Get from a queue with bounded waits and a liveness recheck.

    The dual of :func:`bounded_put`: instead of blocking forever on an
    empty queue, wake every ``poll`` seconds, call ``on_wait`` (lease
    renewal, heartbeat), and recheck ``alive()``.  When the source is no
    longer alive the queue is drained one last time (a source's final put
    races its death) before :class:`QueueSourceDied` is raised — the
    consumer gets an error, never a hang.
    """
    while True:
        try:
            return q.get(timeout=poll)
        except queue.Empty:
            pass
        if on_wait is not None:
            on_wait()
        if alive is not None and not alive():
            try:
                return q.get_nowait()
            except queue.Empty:
                raise QueueSourceDied(
                    "queue source died (or stopped) with the consumer "
                    "still waiting") from None


def bounded_put(q: "queue.Queue", item, stop: threading.Event,
                timeout: float = 0.5) -> bool:
    """Put into a bounded queue, giving up when ``stop`` is set.

    Shared by both ends of the server-client protocol (the server's
    producer buffer and the client's prefetch queue) so a producer whose
    consumer vanished exits instead of wedging on a full queue.  Returns
    False iff stopped before the item was enqueued.
    """
    while not stop.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


class ChannelBase(ABC):
    @abstractmethod
    def send(self, msg: SampleMessage) -> None:
        raise NotImplementedError

    @abstractmethod
    def recv(self) -> SampleMessage:
        raise NotImplementedError

    def empty(self) -> bool:
        return False
