"""Channel interface (cf. graphlearn_torch/python/channel/base.py).

A ``SampleMessage`` is a flat ``Dict[str, np.ndarray]``; channels move them
between the sampling producer and the trainer.
"""
from __future__ import annotations

import queue
import threading
from abc import ABC, abstractmethod
from typing import Dict

import numpy as np

SampleMessage = Dict[str, np.ndarray]


def bounded_put(q: "queue.Queue", item, stop: threading.Event,
                timeout: float = 0.5) -> bool:
    """Put into a bounded queue, giving up when ``stop`` is set.

    Shared by both ends of the server-client protocol (the server's
    producer buffer and the client's prefetch queue) so a producer whose
    consumer vanished exits instead of wedging on a full queue.  Returns
    False iff stopped before the item was enqueued.
    """
    while not stop.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


class ChannelBase(ABC):
    @abstractmethod
    def send(self, msg: SampleMessage) -> None:
        raise NotImplementedError

    @abstractmethod
    def recv(self) -> SampleMessage:
        raise NotImplementedError

    def empty(self) -> bool:
        return False
