"""Channel interface (cf. graphlearn_torch/python/channel/base.py).

A ``SampleMessage`` is a flat ``Dict[str, np.ndarray]``; channels move them
between the sampling producer and the trainer.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict

import numpy as np

SampleMessage = Dict[str, np.ndarray]


class ChannelBase(ABC):
    @abstractmethod
    def send(self, msg: SampleMessage) -> None:
        raise NotImplementedError

    @abstractmethod
    def recv(self) -> SampleMessage:
        raise NotImplementedError

    def empty(self) -> bool:
        return False
