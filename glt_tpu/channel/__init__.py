from .base import ChannelBase, SampleMessage
from .serialization import deserialize, serialize, serialized_size
from .shm_channel import ShmChannel

__all__ = [
    "ChannelBase",
    "SampleMessage",
    "ShmChannel",
    "deserialize",
    "serialize",
    "serialized_size",
]
