from .base import (
    ChannelBase,
    QueueSourceDied,
    SampleMessage,
    bounded_get,
    bounded_put,
)
from .serialization import deserialize, serialize, serialized_size
from .shm_channel import ShmChannel

__all__ = [
    "ChannelBase",
    "QueueSourceDied",
    "SampleMessage",
    "bounded_get",
    "bounded_put",
    "ShmChannel",
    "deserialize",
    "serialize",
    "serialized_size",
]
