"""Shared-memory channel over the native C++ ring queue.

Rebuild of ``channel/shm_channel.py`` + the native ``SampleQueue``
(include/sample_queue.h, py_export.cc:125-140): capacity-bounded
cross-process transport of serialized ``SampleMessage`` dicts, picklable by
queue name so ``multiprocessing`` workers re-attach on the other side —
the role the reference's shmid pickling plays.
"""
from __future__ import annotations

import ctypes
import os
import uuid
from typing import Optional

from .base import ChannelBase, SampleMessage
from .native import lib
from .serialization import deserialize, serialize


class ShmChannel(ChannelBase):
    """Args:
      capacity_bytes: ring size (cf. MpDistSamplingWorkerOptions'
        64MB/worker default, dist_options.py:202-254).
      name: optional explicit shm name (attach when it already exists).
    """

    def __init__(self, capacity_bytes: int = 64 * 1024 * 1024,
                 name: Optional[str] = None, _attach: bool = False):
        self._lib = lib()
        self.capacity = int(capacity_bytes)
        self.name = name or f"/glt_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._owner = not _attach
        if _attach:
            self._q = self._lib.glt_shmq_attach(self.name.encode())
        else:
            self._q = self._lib.glt_shmq_create(self.name.encode(),
                                                self.capacity)
        if not self._q:
            raise OSError(f"failed to open shm queue {self.name}")

    def send(self, msg: SampleMessage) -> None:
        data = serialize(msg)
        rc = self._lib.glt_shmq_enqueue(self._q, data, len(data))
        if rc != 0:
            raise ValueError(
                f"message of {len(data)} bytes exceeds channel capacity "
                f"{self.capacity}")

    def recv(self, timeout: Optional[float] = None
             ) -> Optional[SampleMessage]:
        """Dequeue one message; block up to ``timeout`` seconds.

        ``timeout=None`` blocks forever; on timeout returns ``None``.
        Size-peek + payload-copy happen in one native critical section
        (``glt_shmq_dequeue_alloc``), so multiple consumers on one queue
        are actually MPMC-safe (a separate next_size/dequeue pair lets
        another consumer steal the message in between).
        """
        timeout_ms = -1 if timeout is None else max(0, int(timeout * 1000))
        buf = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        rc = self._lib.glt_shmq_dequeue_alloc(
            self._q, ctypes.byref(buf), ctypes.byref(size), timeout_ms)
        if rc == 1:
            return None
        if rc != 0:
            raise RuntimeError("shm dequeue failed")
        try:
            # Zero-copy view over the malloc'd buffer; deserialize copies
            # each array out of the view, so freeing afterwards is safe.
            view = memoryview(
                (ctypes.c_uint8 * size.value).from_address(
                    ctypes.addressof(buf.contents))).cast("B")
            return deserialize(view)
        finally:
            self._lib.glt_shmq_buf_free(buf)

    def empty(self) -> bool:
        return self._lib.glt_shmq_msg_count(self._q) == 0

    # -- pickling: re-attach by name on the other side ---------------------
    def __reduce__(self):
        return (_attach_channel, (self.name, self.capacity))

    def close(self, unlink: Optional[bool] = None) -> None:
        if self._q:
            self._lib.glt_shmq_close(self._q)
            self._q = None
            if unlink if unlink is not None else self._owner:
                self._lib.glt_shmq_unlink(self.name.encode())

    def __del__(self):
        try:
            self.close(unlink=False)
        except Exception:
            pass


def _attach_channel(name: str, capacity: int) -> ShmChannel:
    return ShmChannel(capacity_bytes=capacity, name=name, _attach=True)
