"""Flat byte serialization of SampleMessage (= Dict[str, ndarray]).

Same layout as the reference's TensorMap serializer
(include/tensor_map.h:24-28, csrc/tensor_map.cc):

    | u32 tensor_num |
    per tensor: | u32 key_len | key | u32 dtype_code | u32 ndim |
                | u64 shape[ndim] | u64 data_len | data |

Numpy-native here (the payload is host-side either way; the trainer hands
the deserialized arrays to ``jax.device_put``).
"""
from __future__ import annotations

import struct
from typing import Dict

import numpy as np

_DTYPES = [np.dtype(x) for x in (
    "float32", "float64", "int32", "int64", "int16", "int8", "uint8",
    "bool", "float16")]
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}


def serialized_size(msg: Dict[str, np.ndarray]) -> int:
    total = 4
    for k, v in msg.items():
        v = np.asarray(v)
        total += 4 + len(k.encode()) + 4 + 4 + 8 * v.ndim + 8 + v.nbytes
    return total


def serialize(msg: Dict[str, np.ndarray]) -> bytes:
    parts = [struct.pack("<I", len(msg))]
    for k, v in msg.items():
        v = np.ascontiguousarray(np.asarray(v))
        if v.dtype not in _DTYPE_CODE:
            raise TypeError(f"unsupported dtype {v.dtype} for key {k!r}")
        kb = k.encode()
        parts.append(struct.pack("<I", len(kb)))
        parts.append(kb)
        parts.append(struct.pack("<II", _DTYPE_CODE[v.dtype], v.ndim))
        parts.append(struct.pack(f"<{v.ndim}Q", *v.shape))
        parts.append(struct.pack("<Q", v.nbytes))
        parts.append(v.tobytes())
    return b"".join(parts)


def deserialize(buf: memoryview) -> Dict[str, np.ndarray]:
    buf = memoryview(buf)
    (n,) = struct.unpack_from("<I", buf, 0)
    off = 4
    out: Dict[str, np.ndarray] = {}
    for _ in range(n):
        (klen,) = struct.unpack_from("<I", buf, off)
        off += 4
        key = bytes(buf[off: off + klen]).decode()
        off += klen
        code, ndim = struct.unpack_from("<II", buf, off)
        off += 8
        shape = struct.unpack_from(f"<{ndim}Q", buf, off)
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", buf, off)
        off += 8
        arr = np.frombuffer(buf[off: off + nbytes],
                            dtype=_DTYPES[code]).reshape(shape).copy()
        off += nbytes
        out[key] = arr
    return out
