"""GAT stack over padded batches."""
from __future__ import annotations

from typing import Any

from flax import linen as nn

from .conv import GATConv


class GAT(nn.Module):
    hidden_features: int
    out_features: int
    num_layers: int = 2
    heads: int = 4
    dropout_rate: float = 0.5
    dtype: Any = None   # matmul compute dtype (see conv.py)

    @nn.compact
    def __call__(self, x, edge_index, edge_mask, *, train: bool = False):
        for i in range(self.num_layers):
            last = i == self.num_layers - 1
            if last:
                x = GATConv(self.out_features, heads=1, concat=False,
                            dtype=self.dtype,
                            name=f"conv{i}")(x, edge_index, edge_mask)
            else:
                x = GATConv(self.hidden_features, heads=self.heads,
                            dtype=self.dtype,
                            name=f"conv{i}")(x, edge_index, edge_mask)
                x = nn.elu(x)
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x
