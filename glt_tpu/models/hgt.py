"""Heterogeneous Graph Transformer (HGT), flax-native over hetero batches.

The reference ships no models (GNNs come from PyG — SURVEY §0) but its
examples train HGT on OGB-MAG (``examples/hetero/train_hgt_mag.py``); a
complete framework therefore provides the architecture.  This follows Hu
et al., *Heterogeneous Graph Transformer* (WWW 2020): type-specific K/Q/V
projections, per-edge-type attention and message transforms with a learned
relation prior, attention normalized **jointly across all edge types**
incoming to a destination node, and a gated residual per node type.

Consumes :class:`~glt_tpu.loader.transform.HeteroBatch` tensors: per-type
node features, per-edge-type padded COO (``edge_index[et][0]`` = message
source rows into ``x[src_t]``, ``[1]`` = destination rows into
``x[dst_t]``) and edge masks — the same interface as :class:`RGAT`, so it
drops into every hetero train step unchanged.
"""
from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..typing import as_str
from .conv import _mm_dtype


class HGTConv(nn.Module):
    """One HGT layer.

    Attention for edge ``s -> t`` of type ``et`` with heads ``i``:
    ``att = (K_i(s) @ W_att[et,i] . Q_i(t)) * mu[et,i] / sqrt(d)``,
    softmaxed over **all** incoming edges of ``t`` (across edge types);
    messages are ``V_i(s) @ W_msg[et,i]``; the per-type output is a
    gated residual ``x + skip_gate * A_t(gelu(agg))``.
    """
    edge_types: Sequence[Tuple[str, str, str]]
    out_features: int
    heads: int = 2
    dtype: Any = None   # matmul compute dtype; attention math stays f32

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray], edge_index, edge_mask):
        h = self.heads
        if self.out_features % h:
            raise ValueError("heads must divide out_features")
        d = self.out_features // h
        dt = _mm_dtype(self.dtype)

        def per_type(name):
            return {t: nn.Dense(h * d, use_bias=False, dtype=dt,
                                name=f"{name}_{t}")(v).astype(
                jnp.float32).reshape(-1, h, d)
                    for t, v in x.items()}

        K, Q, V = per_type("k"), per_type("q"), per_type("v")

        # Per-edge-type raw scores and transformed messages, grouped by
        # destination type for the joint softmax.
        grouped: Dict[str, list] = {}
        for et in self.edge_types:
            src_t, _, dst_t = et
            if et not in edge_index or src_t not in x or dst_t not in x:
                continue
            ei = edge_index[et]
            if ei.shape[-1] == 0:
                continue
            mask = edge_mask[et]
            n_src = x[src_t].shape[0]
            n_dst = x[dst_t].shape[0]
            s_idx = jnp.clip(ei[0], 0, n_src - 1)
            d_idx = jnp.clip(ei[1], 0, n_dst - 1)
            w_att = self.param(f"w_att_{as_str(et)}",
                               nn.initializers.glorot_uniform(), (h, d, d))
            w_msg = self.param(f"w_msg_{as_str(et)}",
                               nn.initializers.glorot_uniform(), (h, d, d))
            mu = self.param(f"mu_{as_str(et)}", nn.initializers.ones, (h,))
            ks = K[src_t][s_idx]                       # [E, h, d]
            qd = Q[dst_t][d_idx]
            score = jnp.einsum("ehd,hdc,ehc->eh", ks, w_att, qd)
            score = score * mu / jnp.sqrt(jnp.asarray(d, score.dtype))
            msg = jnp.einsum("ehd,hdc->ehc", V[src_t][s_idx], w_msg)
            grouped.setdefault(dst_t, []).append((score, msg, d_idx, mask))

        out = {}
        for t, items in grouped.items():
            n_t = x[t].shape[0]
            # Joint two-pass softmax across every edge type ending in t:
            # shared per-(node, head) max, then shared denominator.
            m = jnp.full((n_t + 1, h), -jnp.inf)
            for score, _, d_idx, mask in items:
                seg = jnp.where(mask, d_idx, n_t)
                m = jnp.maximum(m, jax.ops.segment_max(
                    jnp.where(mask[:, None], score, -jnp.inf), seg,
                    num_segments=n_t + 1))
            m = jnp.where(jnp.isfinite(m), m, 0)
            denom = jnp.zeros((n_t + 1, h))
            num = jnp.zeros((n_t + 1, h, d))
            for score, msg, d_idx, mask in items:
                seg = jnp.where(mask, d_idx, n_t)
                # Clamp the exponent at 0: valid lanes have score <= m;
                # masked lanes hit the spill row's reset max and would
                # otherwise overflow exp -> inf -> NaN grads through the
                # where backward (see conv.segment_softmax).
                ex = jnp.where(mask[:, None],
                               jnp.exp(jnp.minimum(score - m[seg], 0.0)),
                               0)
                denom = denom + jax.ops.segment_sum(
                    ex, seg, num_segments=n_t + 1)
                num = num + jax.ops.segment_sum(
                    ex[:, :, None] * msg, seg, num_segments=n_t + 1)
            agg = (num / jnp.maximum(denom, 1e-16)[:, :, None])[:n_t]
            # Observable invariant (flax intermediates): the normalized
            # attention mass per destination — 1 for nodes with >= 1
            # incoming edge ACROSS ALL edge types jointly, 0 otherwise.
            att_sum = jnp.zeros((n_t + 1, h))
            for score, _, d_idx, mask in items:
                seg = jnp.where(mask, d_idx, n_t)
                ex = jnp.where(mask[:, None],
                               jnp.exp(jnp.minimum(score - m[seg], 0.0)),
                               0)
                att_sum = att_sum + jax.ops.segment_sum(
                    ex / jnp.maximum(denom, 1e-16)[seg], seg,
                    num_segments=n_t + 1)
            self.sow("intermediates", f"att_weight_sum_{t}", att_sum[:n_t])
            a_out = nn.Dense(self.out_features, dtype=dt, name=f"a_{t}")(
                nn.gelu(agg.reshape(n_t, h * d))).astype(jnp.float32)
            gate = self.param(f"skip_{t}", nn.initializers.ones, ())
            out[t] = x[t] + jax.nn.sigmoid(gate) * a_out
        # untouched destination types pass through
        return {t: out.get(t, x[t]) for t in x}


class HGT(nn.Module):
    """Multi-layer HGT with per-type input projections and a target head
    (the ``train_hgt_mag.py`` configuration of the reference examples)."""
    edge_types: Sequence[Tuple[str, str, str]]
    hidden_features: int
    out_features: int
    target_type: str
    num_layers: int = 2
    heads: int = 2
    dropout_rate: float = 0.5
    dtype: Any = None   # matmul compute dtype (see conv.py)

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray], edge_index, edge_mask, *,
                 train: bool = False):
        dt = _mm_dtype(self.dtype)
        h = {t: nn.Dense(self.hidden_features, dtype=dt,
                         name=f"in_{t}")(v).astype(jnp.float32)
             for t, v in x.items()}
        for i in range(self.num_layers):
            h = HGTConv(self.edge_types, self.hidden_features,
                        heads=self.heads, dtype=self.dtype,
                        name=f"layer{i}")(
                h, edge_index, edge_mask)
            if train:
                h = {t: nn.Dropout(self.dropout_rate,
                                   deterministic=False)(v)
                     for t, v in h.items()}
        return nn.Dense(self.out_features,
                        name="head")(h[self.target_type])
