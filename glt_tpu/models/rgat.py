"""Relational (hetero) GNNs: HeteroConv combinator + R-GAT / R-SAGE.

The reference trains R-GAT on IGBH via PyG's ``HeteroConv`` dict-of-convs
pattern (examples/igbh); the framework-native equivalent consumes
:class:`~glt_tpu.loader.transform.HeteroBatch` dicts: one conv per edge
type, summed per destination node type, per-type output projections.
"""
from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from ..typing import as_str
from .conv import GATConv, SAGEConv, _mm_dtype


class HeteroConv(nn.Module):
    """Apply one conv per edge type; sum results per destination type.

    ``edge_types`` use the *batch's* (already reversed) keys: an edge type
    ``(src_t, rel, dst_t)`` aggregates messages from ``x[src_t]`` into
    ``x[dst_t]`` rows.
    """
    edge_types: Sequence[Tuple[str, str, str]]
    out_features: int
    conv: str = "sage"      # 'sage' | 'gat'
    heads: int = 2
    dtype: Any = None       # matmul compute dtype (see conv.py)

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray], edge_index, edge_mask):
        dt = _mm_dtype(self.dtype)
        outs: Dict[str, list] = {}
        for et in self.edge_types:
            src_t, _, dst_t = et
            if et not in edge_index or src_t not in x or dst_t not in x:
                continue
            ei = edge_index[et]
            if ei.shape[-1] == 0:
                continue
            mask = edge_mask[et]
            # Bipartite message passing: stack src rows behind dst rows so
            # a homogeneous conv can run on one node array.  The conv's own
            # input projections (lin_self/lin_nbr, lin) consume the raw
            # rows — an extra per-type Dense in front would stack a second
            # linear map that only slows optimization.  Src rows are
            # aligned to the dst width only when the types' feature dims
            # genuinely differ.
            n_dst = x[dst_t].shape[0]
            src_rows = x[src_t]
            if src_rows.shape[-1] != x[dst_t].shape[-1]:
                src_rows = nn.Dense(x[dst_t].shape[-1], dtype=dt,
                                    name=f"{as_str(et)}_align")(
                    src_rows).astype(jnp.float32)
            joint = jnp.concatenate([x[dst_t], src_rows], axis=0)
            ei_shift = jnp.stack([
                jnp.where(ei[0] >= 0, ei[0] + n_dst, -1),  # src rows shifted
                ei[1],                                      # dst rows as-is
            ])
            if self.conv == "gat":
                h = GATConv(self.out_features, heads=self.heads,
                            concat=False, dtype=self.dtype,
                            name=f"{as_str(et)}_conv")(joint, ei_shift, mask)
            else:
                h = SAGEConv(self.out_features, dtype=self.dtype,
                             name=f"{as_str(et)}_conv")(joint, ei_shift, mask)
            outs.setdefault(dst_t, []).append(h[:n_dst])
        return {t: sum(hs) for t, hs in outs.items()}


class RGAT(nn.Module):
    """Multi-layer relational GAT over hetero batches (IGBH-style)."""
    edge_types: Sequence[Tuple[str, str, str]]
    hidden_features: int
    out_features: int
    target_type: str
    num_layers: int = 2
    heads: int = 2
    conv: str = "gat"
    dropout_rate: float = 0.5
    dtype: Any = None       # matmul compute dtype (see conv.py)

    @nn.compact
    def __call__(self, x: Dict[str, jnp.ndarray], edge_index, edge_mask, *,
                 train: bool = False):
        dt = _mm_dtype(self.dtype)
        h = {t: nn.Dense(self.hidden_features, dtype=dt,
                         name=f"in_{t}")(v).astype(jnp.float32)
             for t, v in x.items()}
        for i in range(self.num_layers):
            out = HeteroConv(self.edge_types, self.hidden_features,
                             conv=self.conv, heads=self.heads,
                             dtype=self.dtype,
                             name=f"layer{i}")(h, edge_index, edge_mask)
            # Residual per layer (the HGT layers here do the same via a
            # gated skip): target-type identity features reach the head
            # directly instead of having to survive every conv.
            # Untouched types pass through.
            h = {t: h[t] + nn.relu(out[t]) if t in out else h[t]
                 for t in h}
            if train:
                h = {t: nn.Dropout(self.dropout_rate,
                                   deterministic=False)(v)
                     for t, v in h.items()}
        return nn.Dense(self.out_features,
                        name="head")(h[self.target_type])
