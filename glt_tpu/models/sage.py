"""GraphSAGE — the flagship model for sampled-batch training.

Matches the architecture of the reference's example trainer
(examples/train_sage_ogbn_products.py: PyG ``SAGEConv`` stack, relu +
dropout between layers).  Consumes padded :class:`Batch` tensors; padding
nodes flow through harmlessly (their features are zero and their outputs are
masked by the loss).
"""
from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from .conv import SAGEConv


class GraphSAGE(nn.Module):
    hidden_features: int
    out_features: int
    num_layers: int = 3
    dropout_rate: float = 0.5
    # Matmul compute dtype (e.g. jnp.bfloat16): params, aggregation, loss
    # all stay f32; only the MXU matmuls run reduced (see conv.py).
    dtype: Any = None

    @nn.compact
    def __call__(self, x, edge_index, edge_mask, *, train: bool = False):
        for i in range(self.num_layers):
            last = i == self.num_layers - 1
            dim = self.out_features if last else self.hidden_features
            x = SAGEConv(dim, dtype=self.dtype,
                         name=f"conv{i}")(x, edge_index, edge_mask)
            if not last:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x
