"""Graph convolution layers over padded COO batches, flax-native.

The reference library ships no models (GNNs come from PyG; see SURVEY §0),
but its sampled batches exist to feed SAGEConv/GATConv-style layers — so a
complete TPU framework must provide them.  These layers consume
:class:`~glt_tpu.loader.transform.Batch` tensors directly: ``[2, E]`` COO
with -1 padding and an ``edge_mask``, ``edge_index[0]`` = message source
(the sampler already transposed direction, neighbor_sampler.py:159-165).

TPU notes: aggregation is ``jax.ops.segment_sum`` with a spill segment for
padding edges (XLA lowers this to sorted-scatter, MXU-friendly); all matmuls
are batched over the padded node dimension so shapes are static.

Mixed precision: every layer takes ``dtype`` (e.g. ``jnp.bfloat16``) — the
COMPUTE dtype of its Dense matmuls only.  Params stay float32, the MXU
accumulates in float32 natively, outputs are cast back to float32, and the
gather/segment aggregation path is untouched (it is lane-tile-bound, not
precision-bound — see BASELINE.md).  The reference's torch examples train
in f32 (examples/train_sage_ogbn_products.py); bf16 matmuls are a
TPU-native win the MXU makes free.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def _mm_dtype(dtype):
    """Resolve a layer's matmul compute dtype (None = full f32)."""
    return None if dtype is None else jnp.dtype(dtype)


def scatter_sum(msgs: jnp.ndarray, dst: jnp.ndarray, num_nodes: int,
                mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Sum messages into destination slots; -1/masked edges go to a spill row."""
    if mask is None:
        mask = dst >= 0
    seg = jnp.where(mask, dst, num_nodes)
    msgs = jnp.where(mask[:, None], msgs, 0)
    return jax.ops.segment_sum(msgs, seg, num_segments=num_nodes + 1)[:num_nodes]


def scatter_mean(msgs: jnp.ndarray, dst: jnp.ndarray, num_nodes: int,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    if mask is None:
        mask = dst >= 0
    s = scatter_sum(msgs, dst, num_nodes, mask)
    seg = jnp.where(mask, dst, num_nodes)
    cnt = jax.ops.segment_sum(mask.astype(msgs.dtype), seg,
                              num_segments=num_nodes + 1)[:num_nodes]
    return s / jnp.maximum(cnt, 1)[:, None]


def segment_softmax(scores: jnp.ndarray, seg: jnp.ndarray, num_segments: int,
                    mask: jnp.ndarray) -> jnp.ndarray:
    """Numerically-stable softmax over edges grouped by destination.

    The exponent is clamped at 0 BEFORE ``exp``: valid lanes satisfy
    ``score <= smax`` by construction (no-op), but masked lanes route to
    the spill segment whose max is reset to 0 — once attention scores
    grow past ~88, ``exp`` of those discarded lanes overflows to inf and
    the ``where`` backward turns 0-cotangent x inf into NaN grads
    (observed on TPU at config-4 scale 10, batch 136).
    """
    seg_safe = jnp.where(mask, seg, num_segments)
    smax = jax.ops.segment_max(jnp.where(mask, scores, -jnp.inf), seg_safe,
                               num_segments=num_segments + 1)
    smax = jnp.where(jnp.isfinite(smax), smax, 0)
    ex = jnp.where(mask,
                   jnp.exp(jnp.minimum(scores - smax[seg_safe], 0.0)), 0)
    denom = jax.ops.segment_sum(ex, seg_safe, num_segments=num_segments + 1)
    return ex / jnp.maximum(denom[seg_safe], 1e-16)


class SAGEConv(nn.Module):
    """GraphSAGE convolution (mean aggregator).

    ``h_i = W_self x_i + W_nbr mean_{j->i} x_j``
    """
    out_features: int
    use_bias: bool = True
    dtype: Any = None   # matmul compute dtype (e.g. bf16); params/agg f32

    @nn.compact
    def __call__(self, x, edge_index, edge_mask):
        num_nodes = x.shape[0]
        src, dst = edge_index[0], edge_index[1]
        msgs = jnp.take(x, jnp.clip(src, 0, num_nodes - 1), axis=0)
        agg = scatter_mean(msgs, dst, num_nodes, edge_mask)
        dt = _mm_dtype(self.dtype)
        out = (nn.Dense(self.out_features, use_bias=self.use_bias,
                        dtype=dt, name="lin_self")(x)
               + nn.Dense(self.out_features, use_bias=False,
                          dtype=dt, name="lin_nbr")(agg))
        return out if dt is None else out.astype(jnp.float32)


class GATConv(nn.Module):
    """Graph attention convolution (GATv1, multi-head, concat)."""
    out_features: int
    heads: int = 1
    concat: bool = True
    negative_slope: float = 0.2
    dtype: Any = None   # matmul compute dtype; attention math stays f32

    @nn.compact
    def __call__(self, x, edge_index, edge_mask):
        num_nodes = x.shape[0]
        h, f = self.heads, self.out_features
        src, dst = edge_index[0], edge_index[1]
        src_c = jnp.clip(src, 0, num_nodes - 1)
        dst_c = jnp.clip(dst, 0, num_nodes - 1)

        z = nn.Dense(h * f, use_bias=False, dtype=_mm_dtype(self.dtype),
                     name="lin")(x).astype(jnp.float32).reshape(
            num_nodes, h, f)
        att_src = self.param("att_src", nn.initializers.glorot_uniform(),
                             (h, f))
        att_dst = self.param("att_dst", nn.initializers.glorot_uniform(),
                             (h, f))
        alpha_src = (z * att_src).sum(-1)   # [N, h]
        alpha_dst = (z * att_dst).sum(-1)

        e = alpha_src[src_c] + alpha_dst[dst_c]          # [E, h]
        e = nn.leaky_relu(e, self.negative_slope)
        # Per-head softmax over incoming edges of each destination.
        alpha = jax.vmap(
            lambda s: segment_softmax(s, dst, num_nodes, edge_mask),
            in_axes=1, out_axes=1)(e)                    # [E, h]
        msgs = z[src_c] * alpha[:, :, None]              # [E, h, f]
        out = scatter_sum(msgs.reshape(-1, h * f), dst, num_nodes,
                          edge_mask).reshape(num_nodes, h, f)
        if self.concat:
            out = out.reshape(num_nodes, h * f)
        else:
            out = out.mean(axis=1)
        bias = self.param("bias", nn.initializers.zeros,
                          (out.shape[-1],))
        return out + bias
