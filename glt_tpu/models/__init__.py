from .conv import GATConv, SAGEConv, scatter_mean, scatter_sum, segment_softmax
from .gat import GAT
from .hgt import HGT, HGTConv
from .rgat import RGAT, HeteroConv
from .sage import GraphSAGE
from .train import (
    TrainState,
    create_train_state,
    make_eval_step,
    link_seed_blocks,
    make_cached_gather_xy,
    make_gather_xy,
    init_hetero_state,
    make_scanned_hetero_train_step,
    make_scanned_link_train_step,
    make_scanned_node_train_step,
    node_seed_blocks,
    run_scanned_epoch,
    make_scanned_subgraph_train_step,
    make_train_step,
    seed_cross_entropy,
)

__all__ = [
    "GAT",
    "GATConv",
    "GraphSAGE",
    "HGT",
    "HGTConv",
    "HeteroConv",
    "RGAT",
    "SAGEConv",
    "TrainState",
    "create_train_state",
    "link_seed_blocks",
    "make_cached_gather_xy",
    "make_eval_step",
    "make_gather_xy",
    "init_hetero_state",
    "make_scanned_hetero_train_step",
    "make_scanned_link_train_step",
    "make_scanned_node_train_step",
    "node_seed_blocks",
    "run_scanned_epoch",
    "make_scanned_subgraph_train_step",
    "make_train_step",
    "scatter_mean",
    "scatter_sum",
    "seed_cross_entropy",
    "segment_softmax",
]
