"""Jitted supervised train/eval steps for sampled batches.

The reference leaves training loops to user PyTorch code
(examples/train_sage_ogbn_products.py); here the train step is part of the
framework so the whole batch -> loss -> grad -> update path is one XLA
program.  Loss is masked cross-entropy over the **seed rows only** — seeds
occupy ``node[:batch_size]`` by the sampler's first-occurrence contract.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def create_train_state(model, rng, sample_batch, tx) -> TrainState:
    params = model.init({"params": rng}, sample_batch.x,
                        sample_batch.edge_index, sample_batch.edge_mask)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))


def seed_cross_entropy(logits, y, batch_size: int, node_mask):
    """Mean CE over valid seed rows (first ``batch_size`` slots)."""
    sl = logits[:batch_size]
    sy = y[:batch_size]
    valid = (sy >= 0) & node_mask[:batch_size]
    sy_safe = jnp.where(valid, sy, 0)
    ce = optax.softmax_cross_entropy_with_integer_labels(sl, sy_safe)
    n = jnp.maximum(valid.sum(), 1)
    loss = jnp.where(valid, ce, 0).sum() / n
    acc = jnp.where(valid, jnp.argmax(sl, -1) == sy_safe, False).sum() / n
    return loss, acc


def make_train_step(model, tx, batch_size: int,
                    dropout_seed: int = 0) -> Callable:
    """Build a jitted ``(state, batch) -> (state, loss, acc)`` step."""

    @jax.jit
    def train_step(state: TrainState, batch):
        rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed), state.step)

        def loss_fn(params):
            logits = model.apply(params, batch.x, batch.edge_index,
                                 batch.edge_mask, train=True,
                                 rngs={"dropout": rng})
            return seed_cross_entropy(logits, batch.y, batch_size,
                                      batch.node_mask)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss, acc

    return train_step


def make_eval_step(model, batch_size: int) -> Callable:
    @jax.jit
    def eval_step(params, batch):
        logits = model.apply(params, batch.x, batch.edge_index,
                             batch.edge_mask, train=False)
        return seed_cross_entropy(logits, batch.y, batch_size,
                                  batch.node_mask)

    return eval_step
