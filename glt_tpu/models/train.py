"""Jitted supervised train/eval steps for sampled batches.

The reference leaves training loops to user PyTorch code
(examples/train_sage_ogbn_products.py); here the train step is part of the
framework so the whole batch -> loss -> grad -> update path is one XLA
program.  Loss is masked cross-entropy over the **seed rows only** — seeds
occupy ``node[:batch_size]`` by the sampler's first-occurrence contract.

**The fused epoch.**  The canonical epoch driver is the *scanned* path
(:func:`make_scanned_node_train_step` + :func:`run_scanned_epoch`):
sample -> dedup -> gather -> fwd/bwd -> update for ``G`` consecutive
batches compiles as ONE XLA program per scan group, so intermediate ids
never round-trip through host dispatch and per-batch host work drops to
one seed-block feed per ``G`` batches.  An earlier "overlapped" driver
(``make_pipelined_train_step`` — one program fusing "train batch k"
with "sample batch k+1") was DELETED in the gather-wall round: three
bench rounds measured ``overlap_speedup`` at 0.97-0.99, because both
halves of the fused program contend for the same HBM bandwidth — the
gather-dominated step has no idle resource for sampling to hide in.
The scanned route beat it honestly (BENCH_r05: 9.35 s vs 10.01 s per
config-1 epoch) and carries the same resume/cache/donation seams, so
the losing path is gone rather than reported at 0.99 a fourth time.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..obs import compilewatch as _compilewatch
from ..obs import device as _device
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs import profiler as _profiler
from ..obs.trace import span as _span
from ..typing import PADDING_ID

# Epoch-driver instrumentation (docs/observability.md).  Only the HOST
# loops are instrumented — the jitted step bodies must stay span-free
# (gltlint GLT010: a span inside a traced function runs once at trace
# time and vanishes from the compiled program).
_M_STEPS = _metrics.counter(
    "glt.train.steps", "train steps dispatched by the epoch drivers")
_M_EPOCHS = _metrics.counter(
    "glt.train.epochs", "scanned epochs driven")
_M_BLOCK_MS = _metrics.histogram(
    "glt.train.block_ms",
    "wall per [G, B] block: dispatch + (when a hook syncs) device wait")


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def create_train_state(model, rng, sample_batch, tx) -> TrainState:
    params = model.init({"params": rng}, sample_batch.x,
                        sample_batch.edge_index, sample_batch.edge_mask)
    for leaf in jax.tree_util.tree_leaves(params):
        _device.register_owner("params", array=leaf)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))


def seed_cross_entropy(logits, y, batch_size: int, node_mask):
    """Mean CE over valid seed rows (first ``batch_size`` slots)."""
    sl = logits[:batch_size]
    sy = y[:batch_size]
    valid = (sy >= 0) & node_mask[:batch_size]
    sy_safe = jnp.where(valid, sy, 0)
    ce = optax.softmax_cross_entropy_with_integer_labels(sl, sy_safe)
    n = jnp.maximum(valid.sum(), 1)
    loss = jnp.where(valid, ce, 0).sum() / n
    acc = jnp.where(valid, jnp.argmax(sl, -1) == sy_safe, False).sum() / n
    return loss, acc


def make_train_step(model, tx, batch_size: int,
                    dropout_seed: int = 0) -> Callable:
    """Build a jitted ``(state, batch) -> (state, loss, acc)`` step."""

    @jax.jit
    def train_step(state: TrainState, batch):
        rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed), state.step)

        def loss_fn(params):
            logits = model.apply(params, batch.x, batch.edge_index,
                                 batch.edge_mask, train=True,
                                 rngs={"dropout": rng})
            return seed_cross_entropy(logits, batch.y, batch_size,
                                      batch.node_mask)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss, acc

    return train_step


def make_gather_xy(id2index=None, dedup: bool = False,
                   force: str = "auto", fused: str = "off"):
    """Pure ``(rows, labels, out) -> (x, y)`` batch gather.

    Feature rows and labels ride as arguments (not closures) so callers
    can jit without re-marshalling GB-scale captured arrays; ``id2index``
    (the hotness-reorder indirection) applies to feature ROWS only —
    labels stay indexed by global id.

    ``dedup=True`` fetches each unique row from HBM once and scatters it
    back to every batch position (bit-identical ``x``; see
    :func:`~glt_tpu.ops.dedup_gather.dedup_gather_rows`) — the win when
    the node list repeats ids (un-deduped leaf hops, hub nodes).
    ``force`` selects the row-gather kernel
    (:func:`~glt_tpu.ops.gather_pallas.gather_rows`).  ``fused`` != 'off'
    routes the whole dedup+gather through the one-dispatch
    :func:`~glt_tpu.ops.fused_frontier.fused_frontier` kernel
    ('auto'|'pallas'|'interpret'; same bits, unique rows never bounce
    through HBM) — it subsumes ``dedup``.
    """
    from ..ops.dedup_gather import dedup_gather_rows
    from ..ops.fused_frontier import fused_frontier
    from ..ops.gather_pallas import gather_rows

    def gather_xy(rows_arg, labels_arg, out):
        ids = out.node
        valid = ids >= 0
        gid = jnp.where(valid, ids, 0)
        if fused != "off":
            x = fused_frontier(rows_arg, ids, id2index=id2index,
                               force=fused).features
        elif dedup:
            x = dedup_gather_rows(rows_arg, ids, id2index=id2index,
                                  force=force)
        else:
            ridx = (gid if id2index is None
                    else jnp.take(id2index, gid, axis=0, mode="clip"))
            x = gather_rows(rows_arg, ridx, force=force)
            x = jnp.where(valid[:, None], x, 0)
        y = jnp.where(valid,
                      jnp.take(labels_arg, gid, axis=0, mode="clip"),
                      PADDING_ID)
        return x, y

    return gather_xy


def make_cached_gather_xy(id2index=None, force: str = "auto"):
    """Dedup + cross-batch-cache batch gather:
    ``(cache, rows, labels, out) -> (cache, x, y)``.

    The node list is routed through one unique pass; unique ids are
    served by the :mod:`~glt_tpu.data.feature_cache` (hits from the HBM
    cache table, misses fetched from ``rows`` and inserted), then rows
    scatter back to every batch position — ``x`` is bit-identical to
    :func:`make_gather_xy`'s as long as ``rows`` is unchanged.  The
    returned cache must be threaded into the next call (scan carry /
    donated step argument).
    """
    from ..data.feature_cache import cache_gather
    from ..ops.gather_pallas import gather_rows
    from ..ops.unique import unique_first_occurrence

    def gather_xy(cache, rows_arg, labels_arg, out):
        ids = out.node.astype(jnp.int32)
        uniq, inv, _ = unique_first_occurrence(ids)

        def fetch(fids):
            v = fids >= 0
            fidx = jnp.where(v, fids, 0)
            if id2index is not None:
                fidx = jnp.take(id2index, fidx, axis=0, mode="clip")
            return jnp.where(v[:, None],
                             gather_rows(rows_arg, fidx, force), 0)

        cache, urows = cache_gather(cache, uniq, fetch, force=force)
        x = jnp.take(urows, jnp.clip(inv, 0, inv.shape[0] - 1), axis=0)
        x = jnp.where((inv >= 0)[:, None], x, 0)
        valid = ids >= 0
        gid = jnp.where(valid, ids, 0)
        y = jnp.where(valid,
                      jnp.take(labels_arg, gid, axis=0, mode="clip"),
                      PADDING_ID)
        return cache, x, y

    return gather_xy


def _check_cache(feature_cache, rows_dtype, dim):
    """The cache table's dtype/width must match the feature rows, or the
    cached-path ``x`` would silently change dtype vs the naive path."""
    if feature_cache.table.dtype != rows_dtype:
        raise ValueError(
            f"feature_cache dtype {feature_cache.table.dtype} != feature "
            f"rows dtype {rows_dtype}; build it with cache_init(..., "
            f"dtype=rows.dtype)")
    if feature_cache.dim != dim:
        raise ValueError(
            f"feature_cache dim {feature_cache.dim} != feature dim {dim}")


def make_scanned_node_train_step(model, tx, sampler, rows, labels,
                                 batch_size: int, dropout_seed: int = 0,
                                 dedup: bool = False, feature_cache=None,
                                 gather_force: str = "auto",
                                 fused_frontier: str = "off"):
    """ONE jitted program trains ``G`` consecutive seed-node batches.

    The supervised-node analog of :func:`make_scanned_link_train_step`:
    per batch — multi-hop sampling, feature/label gather, fwd/bwd,
    optimizer update — rolled into a ``lax.scan`` so host dispatch and
    per-batch seed transfers are paid once per ``G`` batches.  Config-1
    is device-bound at batch 1024 (the scan amortises only the ~2 ms
    dispatch + seed-feed overhead), but smaller-batch supervised configs
    are dispatch-bound exactly like the link/subgraph paths where the
    same trick bought 7–17×.

    Returns ``step(state, seeds_blk [G, B], key) -> (state, losses [G],
    accs [G], overflows [G])``; seed blocks are -1 padded (fully-padded
    trailing batches contribute zero-valid losses).  ``overflows`` is
    each batch's occupancy-cap overflow flag (all zeros for uncapped
    samplers) — with a capped sampler, overflowed batches train with
    their excess-node edges masked; monitor the flags and re-run hot
    batches at full capacity (or raise the cap) if the rate matters.

    ``dedup=True`` switches the in-scan feature gather to the dedup-aware
    path; ``feature_cache`` threads a cross-batch HBM cache through the
    scan carry AND across blocks (buffers donated — read the live state
    via ``step.feature_cache()``).  Both leave ``x`` bit-identical.
    ``gather_force`` pins the row-gather kernel inside the fused program
    ('auto' serves the :func:`~glt_tpu.ops.gather_pallas.
    autotune_gather_rows` winner for this table/batch shape — autotune
    at the CAPPED shape before building the step so the fused gather
    runs the tile/ring point measured for its own batch size).
    ``fused_frontier`` != 'off' routes the in-scan feature gather through
    the one-dispatch sample->dedup->gather kernel
    (:func:`~glt_tpu.ops.fused_frontier.fused_frontier`; bit-identical
    ``x``, VMEM-overflowing frontiers fall back to the unfused path).
    The cross-batch ``feature_cache`` wins when both are set — its
    unique-pass bookkeeping IS the fusion's dedup half, so the fused
    kernel only applies to the cache-less gather.  The kernel compiles
    under the ``scanned_node_step`` compilewatch label like everything
    else in the scan.
    """
    import numpy as np

    from ..data.feature import Feature

    g = sampler.graph
    labels = jnp.asarray(labels)
    if not isinstance(rows, Feature):
        rows = Feature(np.asarray(rows))
    if rows.hot_count < rows.size:
        raise ValueError("scanned node step needs device-resident rows")
    hot_rows = rows.hot_rows
    if feature_cache is not None:
        _check_cache(feature_cache, hot_rows.dtype, hot_rows.shape[-1])
        cached_xy = make_cached_gather_xy(rows.id2index,
                                          force=gather_force)
    gather_xy = make_gather_xy(rows.id2index, dedup=dedup,
                               force=gather_force, fused=fused_frontier)

    @partial(jax.jit, donate_argnums=(6,))
    def run(indptr, indices, eids, rows_arg, labels_arg,
            state: TrainState, cache, seeds_blk, key):
        def body(carry, inp):
            st, cache = carry
            seeds, k = inp
            out = sampler._sample_impl(indptr, indices, eids, seeds, k)
            if cache is None:
                x, y = gather_xy(rows_arg, labels_arg, out)
            else:
                cache, x, y = cached_xy(cache, rows_arg, labels_arg, out)
            edge_index = jnp.stack([out.row, out.col])
            rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed),
                                     st.step)

            def loss_fn(p):
                logits = model.apply(p, x, edge_index, out.edge_mask,
                                     train=True, rngs={"dropout": rng})
                return seed_cross_entropy(logits, y, batch_size,
                                          out.node_mask)

            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(st.params)

            def apply(s):
                updates, opt_state = tx.update(grads, s.opt_state,
                                               s.params)
                params = optax.apply_updates(s.params, updates)
                return TrainState(params, opt_state, s.step + 1)

            # Fully-padded trailing batches (block padding) must be
            # no-ops: their grads are zero, but a stateful optimizer
            # (adam momentum decay) would still move params and the step
            # bump would shift later dropout keys — gating keeps the
            # scanned path equivalent to the serial loop over REAL
            # batches only.
            st = jax.lax.cond(jnp.any(seeds >= 0), apply, lambda s: s, st)
            ovf = (out.metadata["overflow"].astype(jnp.int32)
                   if out.metadata else jnp.zeros((), jnp.int32))
            return (st, cache), (loss, acc, ovf)

        keys = jax.random.split(key, seeds_blk.shape[0])
        (state, cache), (losses, accs, ovfs) = jax.lax.scan(
            body, (state, cache), (seeds_blk, keys))
        return state, cache, losses, accs, ovfs

    cache_holder = {"cache": feature_cache}

    def step(state: TrainState, seeds_blk, key):
        with _compilewatch.label("scanned_node_step"):
            state, cache_holder["cache"], losses, accs, ovfs = run(
                g.indptr, g.indices, g.gather_edge_ids, hot_rows,
                labels, state, cache_holder["cache"],
                jnp.asarray(seeds_blk, jnp.int32), key)
        return state, losses, accs, ovfs

    step.feature_cache = lambda: cache_holder["cache"]

    def _set_feature_cache(new_cache):
        # Checkpoint-restore seam (glt_tpu.ckpt): the cross-block cache
        # rides the closure, so a resumed run pushes the captured
        # FeatureCacheState back in here before its first block.
        cache_holder["cache"] = new_cache

    step.set_feature_cache = _set_feature_cache
    return step


def node_seed_blocks(train_idx, batch_size: int, group: int, rng):
    """Shuffled ``[G, B]`` seed blocks, -1 padded (epoch driver for
    :func:`make_scanned_node_train_step`)."""
    import numpy as np

    ids = np.asarray(train_idx)[rng.permutation(len(train_idx))]
    per_block = batch_size * group
    for lo in range(0, len(ids), per_block):
        blk = np.full((group, batch_size), -1, np.int64)
        chunk = ids[lo: lo + per_block]
        blk.reshape(-1)[: chunk.shape[0]] = chunk
        yield blk


def run_scanned_epoch(step, state, train_idx, batch_size: int,
                      group: int, rng, base_key, start_block: int = 0,
                      on_block=None):
    """One epoch through a scanned train step (node or hetero variant).

    Shuffles ``train_idx`` into ``[G, B]`` blocks, pre-stages them to
    the device, drives ``step`` per block, and reduces the metrics with
    ONE device concat + ONE host fetch — per-element ``list(ls)`` slices
    and per-array fetches both put tunnel round trips on the critical
    path.  Returns ``(state, losses [n_real], accs [n_real],
    overflow_count)`` as host numpy (the fetch is the epoch's sync
    point); ``overflow_count`` is 0 for steps without an overflow
    channel.

    ``start_block``/``on_block`` are the resume seam
    (:class:`~glt_tpu.ckpt.driver.TrainLoop`): the first ``start_block``
    blocks are skipped WITHOUT disturbing the key schedule — block ``i``
    always trains under ``fold_in(base_key, i)``, a pure function of its
    position — so an epoch resumed mid-way replays the identical
    remaining batch stream.  ``on_block(state, block_idx)`` fires after
    each block completes (checkpoint cadence, supervisor polls, fault
    hooks); it forces the block's device work to finish first, so state
    captured inside the hook is the exact post-block state.
    """
    import time

    import numpy as np

    blocks = [jax.device_put(jnp.asarray(b.astype(np.int32)))
              for b in node_seed_blocks(train_idx, batch_size, group, rng)]
    n_real = -(-len(train_idx) // batch_size)
    # Real batches already consumed before the resume point: the loss
    # trim below only accounts for the blocks this call actually runs.
    n_real = max(0, n_real - int(start_block) * group)
    losses, accs, ovfs = [], [], []
    with _span("train.scanned_epoch", blocks=len(blocks),
               start_block=int(start_block)):
        t_epoch0 = time.perf_counter()
        for i, blk in enumerate(blocks):
            if i < start_block:
                continue
            t_blk0 = time.perf_counter()
            with _span("train.scanned_block_dispatch"):
                res = step(state, blk, jax.random.fold_in(base_key, i))
            _M_STEPS.inc()
            state = res[0]
            losses.append(res[1])
            accs.append(res[2])
            if len(res) > 3:
                ovfs.append(res[3])
            if on_block is not None:
                # The hook may checkpoint: block on this block's update
                # first so the captured TrainState is post-block exact
                # (dispatch is async; a capture of an in-flight state
                # would still be *correct* — device_get syncs — but the
                # explicit wait keeps save timing honest in traces).
                # The sync is the hook's contract, not an accidental
                # per-batch fetch (GLT013 fires only when a hook is set).
                # gltlint: disable-next=dispatch-in-epoch-loop
                jax.block_until_ready(state)
                on_block(state, i)
            blk_ms = (time.perf_counter() - t_blk0) * 1e3
            _M_BLOCK_MS.observe(blk_ms)
            # Spike-triggered profiler capture (no-op while disarmed).
            _profiler.spike_observe(blk_ms)
        _M_EPOCHS.inc()
        # Epoch boundary: refresh glt.device.* gauges (absent on CPU)
        # and advance the live-bytes leak watch.
        _device.observe_epoch()
        _flight.record("train.epoch",
                       blocks=len(blocks) - int(start_block),
                       start_block=int(start_block),
                       duration_ms=(time.perf_counter() - t_epoch0) * 1e3)
        # The epoch's own host fetch below is the sync; the span closes
        # around it so the scanned epoch's trace duration is truthful.
        losses = (np.asarray(jax.device_get(
            jnp.concatenate(losses)))[:n_real] if losses
            else np.zeros((0,), np.float32))
    accs = (np.asarray(jax.device_get(jnp.concatenate(accs)))[:n_real]
            if accs else np.zeros((0,), np.float32))
    ovf = (int(np.asarray(jax.device_get(
        jnp.concatenate(ovfs))).sum()) if ovfs else 0)
    return state, losses, accs, ovf


def hetero_init_shapes(sampler, feats, rows_of):
    """Zero-filled ``(x, edge_index, edge_mask)`` dummies matching a
    hetero sampler's static output shapes — the shared shape builder for
    :func:`init_hetero_state` and ``parallel.init_hetero_dist_state``.

    ``sampler`` exposes ``node_capacity`` / ``hop_widths`` /
    ``edge_types`` / ``num_neighbors`` (both the single-device and
    distributed hetero samplers do); ``rows_of(feats[t])`` returns the
    per-type ``[N_t, d]`` array whose dtype/width the dummies mirror.
    """
    from ..typing import reverse_edge_type

    capacity = sampler.node_capacity
    widths = sampler.hop_widths
    x = {t: jnp.zeros((max(capacity[t], 1), rows_of(feats[t]).shape[-1]),
                      rows_of(feats[t]).dtype)
         for t in feats if t in capacity}
    ei, mask = {}, {}
    for et in sampler.edge_types:
        fanouts = sampler.num_neighbors[et]
        ecap = sum(widths[hop][et[0]] * f
                   for hop, f in enumerate(fanouts) if f > 0)
        rev = reverse_edge_type(et)
        ei[rev] = jnp.full((2, max(ecap, 1)), PADDING_ID, jnp.int32)
        mask[rev] = jnp.zeros((max(ecap, 1),), bool)
    return x, ei, mask


def init_hetero_state(model, tx, sampler, feats, rng) -> TrainState:
    """Params/opt-state for hetero models from a
    :class:`~glt_tpu.sampler.hetero_neighbor_sampler.HeteroNeighborSampler`'s
    static shapes (the single-device analog of
    ``parallel.init_hetero_dist_state``)."""
    import numpy as np

    from ..data.feature import Feature

    def _rows(f):
        if isinstance(f, Feature):
            return f.hot_rows
        return jnp.asarray(np.asarray(f))

    x, ei, mask = hetero_init_shapes(sampler, feats, _rows)
    params = model.init({"params": rng}, x, ei, mask)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))


def make_scanned_hetero_train_step(model, tx, sampler, feats, labels,
                                   batch_size: int, dropout_seed: int = 0):
    """ONE jitted program trains ``G`` consecutive hetero seed batches.

    The hetero analog of :func:`make_scanned_node_train_step`: per batch
    — multi-type multi-hop sampling
    (:class:`HeteroNeighborSampler._sample_impl`), per-type feature
    gather, target-type label gather, fwd/bwd, update — under
    ``lax.scan``.  Hetero configs run small batches over several graphs
    (IGBH: batch 64), so per-batch dispatch dominates the eager loader
    loop exactly as in the link/subgraph configs; measured on TPU the
    eager config-4 epoch was ~60 ms/batch of pure dispatch.

    Args:
      sampler: a :class:`HeteroNeighborSampler`.
      feats: dict ``node_type -> Feature | [N_t, d] array`` (device
        resident).
      labels: dict ``node_type -> [N_t] int array`` — the sampler's
        ``input_type`` entry supplies the supervised target.

    Returns ``step(state, seeds_blk [G, B], key) -> (state, losses [G],
    accs [G])``.
    """
    import numpy as np

    from ..data.feature import Feature

    tgt = sampler.input_type
    graphs = sampler.graphs
    graph_arrays = {et: (g.indptr, g.indices, g.gather_edge_ids)
                    for et, g in graphs.items()}

    def _rows(f):
        if isinstance(f, Feature):
            if f.hot_count < f.size:
                raise ValueError(
                    "scanned hetero step needs device-resident features")
            return f.hot_rows
        return jnp.asarray(np.asarray(f))

    rows = {t: _rows(f) for t, f in feats.items()}
    labels_tgt = jnp.asarray(np.asarray(labels[tgt]))
    widths, cap = sampler._widths, sampler._capacity

    @jax.jit
    def run(graph_args, rows_args, labels_arg, state: TrainState,
            seeds_blk, key):
        def body(carry, inp):
            st = carry
            seeds, k = inp
            out = sampler._sample_impl(widths, cap, graph_args,
                                       {tgt: seeds}, k)
            x = {}
            for t, node in out.node.items():
                if t not in rows_args:
                    continue
                valid = node >= 0
                gid = jnp.where(valid, node, 0)
                xt = jnp.take(rows_args[t], gid, axis=0, mode="clip")
                x[t] = jnp.where(valid[:, None], xt, 0)
            node_t = out.node[tgt]
            y = jnp.where(node_t >= 0,
                          jnp.take(labels_arg,
                                   jnp.clip(node_t, 0,
                                            labels_arg.shape[0] - 1),
                                   axis=0),
                          PADDING_ID)
            edge_index = {et: jnp.stack([out.row[et], out.col[et]])
                          for et in out.row}
            rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed),
                                     st.step)

            def loss_fn(p):
                logits = model.apply(p, x, edge_index, out.edge_mask,
                                     train=True, rngs={"dropout": rng})
                return seed_cross_entropy(logits, y, batch_size,
                                          out.node_mask[tgt])

            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(st.params)

            def apply(s):
                updates, opt_state = tx.update(grads, s.opt_state,
                                               s.params)
                params = optax.apply_updates(s.params, updates)
                return TrainState(params, opt_state, s.step + 1)

            st = jax.lax.cond(jnp.any(seeds >= 0), apply, lambda s: s, st)
            return st, (loss, acc)

        keys = jax.random.split(key, seeds_blk.shape[0])
        state, (losses, accs) = jax.lax.scan(body, state,
                                             (seeds_blk, keys))
        return state, losses, accs

    def step(state: TrainState, seeds_blk, key):
        with _compilewatch.label("scanned_hetero_step"):
            return run(graph_arrays, rows, labels_tgt, state,
                       jnp.asarray(seeds_blk, jnp.int32), key)

    return step


def make_scanned_link_train_step(model, tx, sampler, rows, loss_fn,
                                 neg_sampling=None, group: int = 8):
    """ONE jitted program trains ``group`` consecutive seed-edge batches.

    Per batch — negative sampling (strict trials + padding), multi-hop
    sampling, feature gather, fwd/bwd, optimizer update — rolled into a
    ``lax.scan``, so host dispatch cost is paid once per ``group``
    batches instead of per batch.  This is the TPU answer to the
    reference's per-worker in-flight batch concurrency
    (dist_options.py:21-100): link-prediction configs run small batches
    whose per-batch device time is comparable to dispatch/tunnel
    latency, so G-batching moves epoch time directly.

    Args:
      sampler: :class:`~glt_tpu.sampler.neighbor_sampler.NeighborSampler`.
      rows: device-resident feature matrix / Feature (split_ratio 1.0).
      loss_fn: ``(z, meta) -> scalar`` given node embeddings ``z`` and
        the batch metadata (``edge_label_index``, ``edge_label`` for
        binary mode, triplet indices for triplet mode).
      neg_sampling: the loader's :class:`NegativeSampling` (or None).

    Returns ``step(params, opt_state, src [G, q], dst [G, q], key) ->
    (params, opt_state, losses [G])``; seed-edge blocks are -1 padded.
    """
    import numpy as np

    from ..data.feature import Feature

    g = sampler.graph
    if not isinstance(rows, Feature):
        rows = Feature(np.asarray(rows))
    if rows.hot_count < rows.size:
        raise ValueError("scanned link step needs device-resident rows")
    hot_rows = rows.hot_rows
    id2index = rows.id2index

    mode = None if neg_sampling is None else neg_sampling.mode
    amount = 0 if neg_sampling is None else int(round(neg_sampling.amount))
    cdf = None if neg_sampling is None else neg_sampling.cdf()
    weighted = cdf is not None
    impl = partial(sampler._sample_edges_impl, mode, amount, weighted)
    q = sampler.batch_size

    @jax.jit
    def run(indptr, indices, eids, sorted_indices, rows_arg, params,
            opt_state, src_blk, dst_blk, cdf_arg, key):
        def body(carry, inp):
            params, opt = carry
            s, d, k = inp
            out = impl(indptr, indices, eids, sorted_indices, s, d,
                       cdf_arg, k)
            meta = dict(out.metadata)
            if mode == "binary":
                pos = jnp.where(s >= 0, 1, PADDING_ID)
                meta["edge_label"] = jnp.concatenate(
                    [pos, jnp.zeros((q * amount,), jnp.int32)])
            valid = out.node >= 0
            gid = jnp.where(valid, out.node, 0)
            ridx = (gid if id2index is None
                    else jnp.take(id2index, gid, axis=0, mode="clip"))
            x = jnp.take(rows_arg, ridx, axis=0, mode="clip")
            x = jnp.where(valid[:, None], x, 0)
            edge_index = jnp.stack([out.row, out.col])

            def lf(p):
                z = model.apply(p, x, edge_index, out.edge_mask)
                return loss_fn(z, meta)

            loss, grads = jax.value_and_grad(lf)(params)
            updates, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(params, updates)
            return (params, opt), loss

        keys = jax.random.split(key, src_blk.shape[0])
        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), (src_blk, dst_blk, keys))
        return params, opt_state, losses

    def step(params, opt_state, src_blk, dst_blk, key):
        sorted_ix = g.sorted_indices if mode is not None else g.indices
        cdf_arg = (jnp.zeros((1,), jnp.float32) if cdf is None else cdf)
        with _compilewatch.label("scanned_link_step"):
            return run(g.indptr, g.indices, g.gather_edge_ids, sorted_ix,
                       hot_rows, params, opt_state,
                       jnp.asarray(src_blk, jnp.int32),
                       jnp.asarray(dst_blk, jnp.int32), cdf_arg, key)

    return step


def make_scanned_subgraph_train_step(model, tx, sampler, rows, loss_fn,
                                     max_degree: int):
    """ONE jitted program trains a block of induced-subgraph batches.

    Per batch — hop expansion, induced extraction
    (:func:`~glt_tpu.ops.subgraph.node_subgraph`), feature gather,
    fwd/bwd, update — under ``lax.scan`` (scan length = the seed block's
    leading axis); the SEAL-style configs run tiny batches where per-call
    dispatch/transfer dominates, so G-batching (plus device-resident seed
    blocks) moves epoch time the same way it does for the link path.

    ``loss_fn(z, out, y) -> scalar`` gets node embeddings over the
    extracted subgraph, the per-batch :class:`SamplerOutput` (graph-
    direction COO), and the per-batch label block ``y``.  Seeds are
    DEDUPED in the node list, so positional slicing of ``z`` mispairs
    whenever a seed repeats — use ``out.metadata['seed_index']``
    (``[B_seeds]`` local indices of the seed slots, -1 for padding) to
    locate seed embeddings.

    Returns ``step(params, opt_state, seeds [G, B], y [G, ...], key)``.
    """
    import numpy as np

    from ..data.feature import Feature
    from ..ops.subgraph import node_subgraph
    from ..ops.unique import relabel_by_reference
    from ..sampler.base import SamplerOutput

    g = sampler.graph
    if not isinstance(rows, Feature):
        rows = Feature(np.asarray(rows))
    if rows.hot_count < rows.size:
        raise ValueError("scanned subgraph step needs device-resident rows")
    if not sampler.last_hop_dedup:
        # Same guard as NeighborSampler.subgraph(): the induced extract
        # relabels against a UNIQUE node set.
        raise ValueError(
            "scanned subgraph step requires last_hop_dedup=True")
    hot_rows = rows.hot_rows
    id2index = rows.id2index
    k_deg = int(max_degree)

    @jax.jit
    def run(indptr, indices, eids, sub_eids, rows_arg, params, opt_state,
            seeds_blk, y_blk, key):
        def body(carry, inp):
            params, opt = carry
            seeds, y, k = inp
            base = sampler._sample_impl(indptr, indices, eids, seeds, k)
            sub = node_subgraph(indptr, indices, base.node, k_deg,
                                edge_ids=sub_eids)
            ref = base.node[: seeds.shape[0]]
            out = SamplerOutput(
                node=base.node, row=sub.rows, col=sub.cols, edge=sub.eids,
                batch=seeds, node_mask=base.node_mask, edge_mask=sub.mask,
                num_sampled_nodes=base.num_sampled_nodes,
                metadata={"seed_index":
                          relabel_by_reference(ref, seeds)})
            valid = out.node >= 0
            gid = jnp.where(valid, out.node, 0)
            ridx = (gid if id2index is None
                    else jnp.take(id2index, gid, axis=0, mode="clip"))
            x = jnp.where(valid[:, None],
                          jnp.take(rows_arg, ridx, axis=0, mode="clip"), 0)
            edge_index = jnp.stack([out.row, out.col])

            def lf(p):
                z = model.apply(p, x, edge_index, out.edge_mask)
                return loss_fn(z, out, y)

            loss, grads = jax.value_and_grad(lf)(params)
            updates, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(params, updates)
            return (params, opt), loss

        keys = jax.random.split(key, seeds_blk.shape[0])
        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), (seeds_blk, y_blk, keys))
        return params, opt_state, losses

    def step(params, opt_state, seeds_blk, y_blk, key):
        with _compilewatch.label("scanned_subgraph_step"):
            return run(g.indptr, g.indices, g.gather_edge_ids, g.edge_ids,
                       hot_rows, params, opt_state,
                       jnp.asarray(seeds_blk, jnp.int32),
                       jnp.asarray(y_blk), key)

    return step


def link_seed_blocks(edge_index, batch_size: int, group: int, rng):
    """Shuffled seed-edge ``[G, q]`` src/dst blocks, -1 padded.

    Host-side epoch driver for :func:`make_scanned_link_train_step`:
    yields ``(src_blk, dst_blk, n_batches)`` where the trailing block may
    carry fully-padded batches (their losses are 0-valid and ignorable).
    """
    import numpy as np

    e = np.asarray(edge_index)
    perm = rng.permutation(e.shape[1])
    src, dst = e[0][perm], e[1][perm]
    n = src.shape[0]
    per_block = batch_size * group
    for lo in range(0, n, per_block):
        sb = np.full((group, batch_size), -1, np.int64)
        db = np.full((group, batch_size), -1, np.int64)
        chunk_s = src[lo: lo + per_block]
        chunk_d = dst[lo: lo + per_block]
        m = chunk_s.shape[0]
        sb.reshape(-1)[:m] = chunk_s
        db.reshape(-1)[:m] = chunk_d
        yield sb, db, -(-m // batch_size)


def make_eval_step(model, batch_size: int) -> Callable:
    @jax.jit
    def eval_step(params, batch):
        logits = model.apply(params, batch.x, batch.edge_index,
                             batch.edge_mask, train=False)
        return seed_cross_entropy(logits, batch.y, batch_size,
                                  batch.node_mask)

    return eval_step
