"""Sampling server: owns the dataset, produces batches for remote clients.

Rebuild of ``distributed/dist_server.py``: the reference's server owns a
DistDataset plus a pool of mp producers + shm buffers, and clients RPC
``create_sampling_producer / start_new_epoch_sampling /
fetch_one_sampled_message / destroy`` over torch RPC (:38-144).  The TPU
build speaks a small length-prefixed TCP protocol instead (JSON control
frames + TensorMap-serialized sample frames) — the transport the zero-
dependency host runtime actually needs; RDMA-class speed on-host comes from
the shm channel path, and cross-host bulk data rides the same socket.

Protocol (all frames ``u32 kind | u64 len | payload``):
  kind 0: JSON control request/response
  kind 1: serialized SampleMessage
"""
from __future__ import annotations

import json
import queue
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..channel.serialization import deserialize, serialize

_KIND_JSON = 0
_KIND_MSG = 1


def send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
    sock.sendall(struct.pack("<IQ", kind, len(payload)) + payload)


def recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, 12)
    if hdr is None:
        return None, None
    kind, length = struct.unpack("<IQ", hdr)
    data = _recv_exact(sock, length)
    return kind, data


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _Producer:
    """Server-side sampling producer: a thread filling a bounded queue
    (the reference's producer + shm buffer pair, dist_server.py:83-116)."""

    def __init__(self, dataset, num_neighbors, input_nodes, batch_size,
                 buffer_capacity: int = 8, seed: int = 0):
        from ..loader.node_loader import NeighborLoader

        self.loader = NeighborLoader(dataset, num_neighbors,
                                     input_nodes, batch_size=batch_size,
                                     shuffle=True, seed=seed)
        self.buffer: "queue.Queue" = queue.Queue(maxsize=buffer_capacity)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def num_expected(self) -> int:
        return len(self.loader)

    def start_epoch(self) -> None:
        if self._thread is not None:
            # The previous epoch's producer may still be draining its last
            # put even after the client consumed every batch — wait for it
            # rather than racing.
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                raise RuntimeError("previous epoch still producing")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from .sample_message import batch_to_message

        for batch in self.loader:
            payload = serialize(batch_to_message(batch))
            # put with a stop check so a producer whose client vanished
            # mid-epoch can exit instead of wedging on the bounded buffer
            # (and permanently poisoning this producer id).
            while not self._stop.is_set():
                try:
                    self.buffer.put(payload, timeout=0.5)
                    break
                except queue.Full:
                    continue
            if self._stop.is_set():
                return

    def fetch(self) -> bytes:
        return self.buffer.get()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class DistServer:
    """Args mirror init_server (dist_server.py:158-190)."""

    def __init__(self, dataset, host: str = "127.0.0.1", port: int = 0):
        self.dataset = dataset
        self._producers: Dict[int, _Producer] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- request handlers (cf. _call_func_on_server, dist_server.py:214) ---
    def _handle(self, req: dict):
        op = req["op"]
        if op == "get_dataset_meta":
            g = self.dataset.get_graph()
            return {"num_nodes": g.num_nodes, "num_edges": g.num_edges}
        if op == "create_sampling_producer":
            with self._lock:
                pid = self._next_id
                self._next_id += 1
                self._producers[pid] = _Producer(
                    self.dataset, req["num_neighbors"],
                    np.asarray(req["input_nodes"], np.int64),
                    req["batch_size"],
                    buffer_capacity=req.get("buffer_capacity", 8),
                    seed=req.get("seed", 0))
            return {"producer_id": pid,
                    "num_expected": self._producers[pid].num_expected()}
        if op == "start_new_epoch_sampling":
            self._producers[req["producer_id"]].start_epoch()
            return {"ok": True}
        if op == "destroy_sampling_producer":
            with self._lock:
                prod = self._producers.pop(req["producer_id"], None)
            if prod is not None:
                prod.stop()
            return {"ok": True}
        if op == "exit":
            self._stop.set()
            return {"ok": True}
        raise ValueError(f"unknown op {op!r}")

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                kind, data = recv_frame(conn)
                if kind is None:
                    return
                req = json.loads(data)
                if req["op"] == "fetch_one_sampled_message":
                    payload = self._producers[req["producer_id"]].fetch()
                    send_frame(conn, _KIND_MSG, payload)
                else:
                    resp = self._handle(req)
                    send_frame(conn, _KIND_JSON, json.dumps(resp).encode())
        except Exception as e:  # connection-scoped errors end the session
            try:
                send_frame(conn, _KIND_JSON,
                           json.dumps({"error": str(e)}).encode())
            except OSError:
                pass
        finally:
            conn.close()

    def wait_for_exit(self, timeout: Optional[float] = None) -> None:
        self._stop.wait(timeout)

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def init_server(dataset, host: str = "127.0.0.1", port: int = 0
                ) -> DistServer:
    return DistServer(dataset, host=host, port=port)
